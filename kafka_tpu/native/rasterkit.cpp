// rasterkit — thread-pooled tile codec for the GeoTIFF pipeline.
//
// The reference leans on GDAL's C++ raster stack for all tile
// encode/decode (SURVEY.md §2.2); this is the TPU build's native
// equivalent for the codec hot path: batch zlib inflate/deflate of
// TIFF tiles across a worker pool, callable from Python via ctypes with
// zero per-tile Python overhead.  A 10980x10980 Sentinel-2 tile-year is
// ~10^5 tile inflations — embarrassingly parallel, GIL-free here.
//
// C ABI:
//   rk_inflate_batch(n, in_ptrs, in_sizes, out_buf, out_stride, out_sizes,
//                    n_threads) -> 0 on success
//   rk_deflate_batch(n, in_ptrs, in_sizes, level, out_buf, out_stride,
//                    out_sizes, n_threads) -> 0 on success
//
// Each output slot i is out_buf + i*out_stride with capacity out_stride;
// actual byte counts land in out_sizes.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> workers;
  int n_workers = static_cast<int>(
      std::min<int64_t>(n, static_cast<int64_t>(n_threads)));
  workers.reserve(n_workers);
  for (int t = 0; t < n_workers; ++t) {
    workers.emplace_back([&] {
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        fn(i);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// TIFF LZW decode (MSB-first bit order with the early-change quirk) —
// mirrors the Python reference decoder in io/geotiff.py bit for bit.
// Returns 0 on success, -1 on a corrupt stream / overfull output.
int lzw_decode_one(const uint8_t* in, int64_t in_size, uint8_t* out,
                   int64_t out_cap, int64_t* out_len) {
  constexpr int kClear = 256, kEoi = 257;
  uint16_t prefix[4096];
  uint8_t suffix[4096];
  uint8_t stack[4097];
  int next = 258;
  int nbits = 9;
  int64_t bitpos = 0;
  const int64_t total_bits = in_size * 8;
  int prev = -1;
  int64_t len = 0;
  while (bitpos + nbits <= total_bits) {
    const int64_t byte_idx = bitpos >> 3;
    uint32_t chunk = 0;
    for (int b = 0; b < 4; ++b) {
      chunk = (chunk << 8) |
              (byte_idx + b < in_size ? in[byte_idx + b] : 0);
    }
    const int code = static_cast<int>(
        (chunk >> (32 - nbits - (bitpos & 7))) & ((1u << nbits) - 1));
    bitpos += nbits;
    if (code == kEoi) break;
    if (code == kClear) {
      next = 258;
      nbits = 9;
      prev = -1;
      continue;
    }
    int sp = 0;
    uint8_t first;
    if (prev < 0) {
      if (code > 255) return -1;
      if (len >= out_cap) return -1;
      out[len++] = static_cast<uint8_t>(code);
      first = static_cast<uint8_t>(code);
      prev = code;
      // (no table append on the first code after a clear — matches the
      // Python decoder; early-change check still runs below)
      if (next >= (1 << nbits) - 1 && nbits < 12) ++nbits;
      continue;
    }
    int walk;
    if (code < next) {
      walk = code;
    } else if (code == next) {
      // KwKwK: emission = string(prev) + first(string(prev))
      walk = prev;
    } else {
      return -1;
    }
    while (walk >= 258) {
      if (sp >= 4096) return -1;
      stack[sp++] = suffix[walk];
      walk = prefix[walk];
    }
    stack[sp++] = static_cast<uint8_t>(walk);
    first = stack[sp - 1];
    if (len + sp + (code == next ? 1 : 0) > out_cap) return -1;
    while (sp) out[len++] = stack[--sp];
    if (code == next) out[len++] = first;
    if (next < 4096) {
      prefix[next] = static_cast<uint16_t>(prev);
      suffix[next] = first;
      ++next;
    }
    prev = code;
    if (next >= (1 << nbits) - 1 && nbits < 12) ++nbits;
  }
  *out_len = len;
  return 0;
}

// TIFF LZW encode — matched to the decoders above: width switch one
// append later than the decoder (its table lags by one entry), clear at
// 4094, and the LZWPostEncode-style final width bump before the EOI.
int lzw_encode_one(const uint8_t* in, int64_t n, uint8_t* out,
                   int64_t cap, int64_t* out_len) {
  constexpr int kHSize = 18013;  // prime, ~4.4x load for 4096 codes
  std::vector<int32_t> hkey(kHSize, -1);
  std::vector<uint16_t> hval(kHSize);
  int64_t len = 0;
  uint32_t bitbuf = 0;
  int bitcnt = 0;
  int nbits = 9;
  int next = 258;
  bool ok = true;
  auto put = [&](int code) {
    bitbuf = (bitbuf << nbits) | static_cast<uint32_t>(code);
    bitcnt += nbits;
    while (bitcnt >= 8) {
      if (len >= cap) { ok = false; return; }
      out[len++] = static_cast<uint8_t>((bitbuf >> (bitcnt - 8)) & 0xFF);
      bitcnt -= 8;
    }
  };
  put(256);
  int w = -1;
  for (int64_t i = 0; i < n && ok; ++i) {
    const int c = in[i];
    if (w < 0) {
      w = c;
      continue;
    }
    const int32_t key = (w << 8) | c;
    int h = static_cast<int>(
        (static_cast<uint32_t>(key) * 2654435761u) % kHSize);
    int found = -1;
    while (hkey[h] != -1) {
      if (hkey[h] == key) {
        found = hval[h];
        break;
      }
      h = (h + 1) % kHSize;
    }
    if (found >= 0) {
      w = found;
      continue;
    }
    put(w);
    hkey[h] = key;
    hval[h] = static_cast<uint16_t>(next);
    ++next;
    if (next >= 4094) {
      put(256);
      std::fill(hkey.begin(), hkey.end(), -1);
      next = 258;
      nbits = 9;
    } else if (next >= (1 << nbits) && nbits < 12) {
      ++nbits;
    }
    w = c;
  }
  if (w >= 0 && ok) {
    put(w);
    if (next >= (1 << nbits) - 1 && nbits < 12) ++nbits;
  }
  if (ok) put(257);
  if (ok && bitcnt) {
    if (len >= cap) {
      ok = false;
    } else {
      out[len++] = static_cast<uint8_t>((bitbuf << (8 - bitcnt)) & 0xFF);
    }
  }
  if (!ok) return -1;
  *out_len = len;
  return 0;
}

// TIFF predictor-3 inverse (libtiff fpAcc): per row, byte-wise prefix sum
// with stride nb over the 4 byte-significance planes (MSB plane first),
// then unshuffle planes back into little-endian float32 samples.
void fp3_accumulate(const uint8_t* raw, int rows, int cols, int nb,
                    float* out, std::vector<uint8_t>& scratch) {
  const int cn = cols * nb;
  const int rowbytes = 4 * cn;
  scratch.resize(rowbytes);
  for (int r = 0; r < rows; ++r) {
    const uint8_t* src = raw + static_cast<size_t>(r) * rowbytes;
    uint8_t* acc = scratch.data();
    std::memcpy(acc, src, rowbytes);
    for (int i = nb; i < rowbytes; ++i)
      acc[i] = static_cast<uint8_t>(acc[i] + acc[i - nb]);
    uint8_t* o = reinterpret_cast<uint8_t*>(out
                                            + static_cast<size_t>(r) * cn);
    const uint8_t* p0 = acc;            // MSB plane
    const uint8_t* p1 = acc + cn;
    const uint8_t* p2 = acc + 2 * cn;
    const uint8_t* p3 = acc + 3 * cn;   // LSB plane
    for (int j = 0; j < cn; ++j) {
      o[4 * j + 0] = p3[j];
      o[4 * j + 1] = p2[j];
      o[4 * j + 2] = p1[j];
      o[4 * j + 3] = p0[j];
    }
  }
}

// TIFF predictor-3 forward (libtiff fpDiff): shuffle float32 samples into
// byte-significance planes (MSB first) per row, then byte-wise
// horizontal differencing with stride nb.
void fp3_difference(const float* in, int rows, int cols, int nb,
                    uint8_t* out) {
  const int cn = cols * nb;
  const int rowbytes = 4 * cn;
  for (int r = 0; r < rows; ++r) {
    const uint8_t* s = reinterpret_cast<const uint8_t*>(
        in + static_cast<size_t>(r) * cn);
    uint8_t* dst = out + static_cast<size_t>(r) * rowbytes;
    uint8_t* p0 = dst;
    uint8_t* p1 = dst + cn;
    uint8_t* p2 = dst + 2 * cn;
    uint8_t* p3 = dst + 3 * cn;
    for (int j = 0; j < cn; ++j) {
      p0[j] = s[4 * j + 3];
      p1[j] = s[4 * j + 2];
      p2[j] = s[4 * j + 1];
      p3[j] = s[4 * j + 0];
    }
    for (int i = rowbytes - 1; i >= nb; --i)
      dst[i] = static_cast<uint8_t>(dst[i] - dst[i - nb]);
  }
}

}  // namespace

extern "C" {

// Batch TIFF-LZW inflate across the worker pool (GDAL's default
// compression for real-world S2 trees; the Python fallback decodes at
// ~1 MB/s, crippling at tile-year scale).
int rk_lzw_inflate_batch(int64_t n, const uint8_t** in_ptrs,
                         const int64_t* in_sizes, uint8_t* out_buf,
                         int64_t out_stride, int64_t* out_sizes,
                         int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    int64_t out_len = 0;
    int rc = lzw_decode_one(in_ptrs[i], in_sizes[i],
                            out_buf + i * out_stride, out_stride,
                            &out_len);
    if (rc != 0) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = out_len;
    }
  });
  return status.load();
}

// Batch TIFF-LZW deflate across the worker pool (makes the writer's
// compress="lzw" GDAL-compatibility mode a parallel production path
// instead of the serial Python encoder).
int rk_lzw_deflate_batch(int64_t n, const uint8_t** in_ptrs,
                         const int64_t* in_sizes, uint8_t* out_buf,
                         int64_t out_stride, int64_t* out_sizes,
                         int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    int64_t out_len = 0;
    int rc = lzw_encode_one(in_ptrs[i], in_sizes[i],
                            out_buf + i * out_stride, out_stride,
                            &out_len);
    if (rc != 0) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = out_len;
    }
  });
  return status.load();
}

// Fused tile decode for float32 predictor-3 tiles: (optional) zlib
// inflate + fpAcc + byte unshuffle, one parallel pass over n tiles.
// in_sizes[i] == 0 means a sparse/absent tile -> zero-filled output.
// Short payloads are zero-padded (the Python codec's ljust contract).
int rk_decode_fp3_batch(int64_t n, const uint8_t** in_ptrs,
                        const int64_t* in_sizes, int rows, int cols,
                        int nb, int compressed, float* out,
                        int64_t out_stride_floats, int n_threads) {
  std::atomic<int> status(0);
  const size_t rawbytes = static_cast<size_t>(rows) * 4 * cols * nb;
  parallel_for(n, n_threads, [&](int64_t i) {
    float* dst = out + i * out_stride_floats;
    if (in_sizes[i] == 0) {
      std::memset(dst, 0, rawbytes);
      return;
    }
    std::vector<uint8_t> raw(rawbytes, 0);
    if (compressed) {
      uLongf dest_len = static_cast<uLongf>(rawbytes);
      int rc = uncompress(raw.data(), &dest_len, in_ptrs[i],
                          static_cast<uLong>(in_sizes[i]));
      if (rc != Z_OK) {
        status.store(rc);
        std::memset(dst, 0, rawbytes);
        return;
      }
    } else {
      std::memcpy(raw.data(), in_ptrs[i],
                  std::min(rawbytes, static_cast<size_t>(in_sizes[i])));
    }
    std::vector<uint8_t> scratch;
    fp3_accumulate(raw.data(), rows, cols, nb, dst, scratch);
  });
  return status.load();
}

// Fused tile encode: fpDiff + zlib deflate, one parallel pass.  Input is
// n contiguous float32 tiles at in_stride_floats; output slot i is
// out_buf + i*out_stride with capacity out_stride, byte counts in
// out_sizes.
int rk_encode_fp3_batch(int64_t n, const float* in,
                        int64_t in_stride_floats, int rows, int cols,
                        int nb, int level, uint8_t* out_buf,
                        int64_t out_stride, int64_t* out_sizes,
                        int n_threads) {
  std::atomic<int> status(0);
  const size_t rawbytes = static_cast<size_t>(rows) * 4 * cols * nb;
  parallel_for(n, n_threads, [&](int64_t i) {
    std::vector<uint8_t> raw(rawbytes);
    fp3_difference(in + i * in_stride_floats, rows, cols, nb, raw.data());
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = compress2(out_buf + i * out_stride, &dest_len, raw.data(),
                       static_cast<uLong>(rawbytes), level);
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}

int rk_inflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = uncompress(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                        static_cast<uLong>(in_sizes[i]));
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}

int rk_deflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, int level, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = compress2(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                       static_cast<uLong>(in_sizes[i]), level);
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}


}  // extern "C"
