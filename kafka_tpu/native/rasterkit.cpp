// rasterkit — thread-pooled tile codec for the GeoTIFF pipeline.
//
// The reference leans on GDAL's C++ raster stack for all tile
// encode/decode (SURVEY.md §2.2); this is the TPU build's native
// equivalent for the codec hot path: batch zlib inflate/deflate of
// TIFF tiles across a worker pool, callable from Python via ctypes with
// zero per-tile Python overhead.  A 10980x10980 Sentinel-2 tile-year is
// ~10^5 tile inflations — embarrassingly parallel, GIL-free here.
//
// C ABI:
//   rk_inflate_batch(n, in_ptrs, in_sizes, out_buf, out_stride, out_sizes,
//                    n_threads) -> 0 on success
//   rk_deflate_batch(n, in_ptrs, in_sizes, level, out_buf, out_stride,
//                    out_sizes, n_threads) -> 0 on success
//
// Each output slot i is out_buf + i*out_stride with capacity out_stride;
// actual byte counts land in out_sizes.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> workers;
  int n_workers = static_cast<int>(
      std::min<int64_t>(n, static_cast<int64_t>(n_threads)));
  workers.reserve(n_workers);
  for (int t = 0; t < n_workers; ++t) {
    workers.emplace_back([&] {
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        fn(i);
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // namespace

extern "C" {

int rk_inflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = uncompress(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                        static_cast<uLong>(in_sizes[i]));
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}

int rk_deflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, int level, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = compress2(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                       static_cast<uLong>(in_sizes[i]), level);
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}


}  // extern "C"
