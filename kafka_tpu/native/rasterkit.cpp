// rasterkit — thread-pooled tile codec for the GeoTIFF pipeline.
//
// The reference leans on GDAL's C++ raster stack for all tile
// encode/decode (SURVEY.md §2.2); this is the TPU build's native
// equivalent for the codec hot path: batch zlib inflate/deflate of
// TIFF tiles across a worker pool, callable from Python via ctypes with
// zero per-tile Python overhead.  A 10980x10980 Sentinel-2 tile-year is
// ~10^5 tile inflations — embarrassingly parallel, GIL-free here.
//
// C ABI:
//   rk_inflate_batch(n, in_ptrs, in_sizes, out_buf, out_stride, out_sizes,
//                    n_threads) -> 0 on success
//   rk_deflate_batch(n, in_ptrs, in_sizes, level, out_buf, out_stride,
//                    out_sizes, n_threads) -> 0 on success
//
// Each output slot i is out_buf + i*out_stride with capacity out_stride;
// actual byte counts land in out_sizes.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

template <typename Fn>
void parallel_for(int64_t n, int n_threads, Fn fn) {
  if (n_threads <= 1 || n <= 1) {
    for (int64_t i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int64_t> next(0);
  std::vector<std::thread> workers;
  int n_workers = static_cast<int>(
      std::min<int64_t>(n, static_cast<int64_t>(n_threads)));
  workers.reserve(n_workers);
  for (int t = 0; t < n_workers; ++t) {
    workers.emplace_back([&] {
      while (true) {
        int64_t i = next.fetch_add(1);
        if (i >= n) break;
        fn(i);
      }
    });
  }
  for (auto& w : workers) w.join();
}

// TIFF predictor-3 inverse (libtiff fpAcc): per row, byte-wise prefix sum
// with stride nb over the 4 byte-significance planes (MSB plane first),
// then unshuffle planes back into little-endian float32 samples.
void fp3_accumulate(const uint8_t* raw, int rows, int cols, int nb,
                    float* out, std::vector<uint8_t>& scratch) {
  const int cn = cols * nb;
  const int rowbytes = 4 * cn;
  scratch.resize(rowbytes);
  for (int r = 0; r < rows; ++r) {
    const uint8_t* src = raw + static_cast<size_t>(r) * rowbytes;
    uint8_t* acc = scratch.data();
    std::memcpy(acc, src, rowbytes);
    for (int i = nb; i < rowbytes; ++i)
      acc[i] = static_cast<uint8_t>(acc[i] + acc[i - nb]);
    uint8_t* o = reinterpret_cast<uint8_t*>(out
                                            + static_cast<size_t>(r) * cn);
    const uint8_t* p0 = acc;            // MSB plane
    const uint8_t* p1 = acc + cn;
    const uint8_t* p2 = acc + 2 * cn;
    const uint8_t* p3 = acc + 3 * cn;   // LSB plane
    for (int j = 0; j < cn; ++j) {
      o[4 * j + 0] = p3[j];
      o[4 * j + 1] = p2[j];
      o[4 * j + 2] = p1[j];
      o[4 * j + 3] = p0[j];
    }
  }
}

// TIFF predictor-3 forward (libtiff fpDiff): shuffle float32 samples into
// byte-significance planes (MSB first) per row, then byte-wise
// horizontal differencing with stride nb.
void fp3_difference(const float* in, int rows, int cols, int nb,
                    uint8_t* out) {
  const int cn = cols * nb;
  const int rowbytes = 4 * cn;
  for (int r = 0; r < rows; ++r) {
    const uint8_t* s = reinterpret_cast<const uint8_t*>(
        in + static_cast<size_t>(r) * cn);
    uint8_t* dst = out + static_cast<size_t>(r) * rowbytes;
    uint8_t* p0 = dst;
    uint8_t* p1 = dst + cn;
    uint8_t* p2 = dst + 2 * cn;
    uint8_t* p3 = dst + 3 * cn;
    for (int j = 0; j < cn; ++j) {
      p0[j] = s[4 * j + 3];
      p1[j] = s[4 * j + 2];
      p2[j] = s[4 * j + 1];
      p3[j] = s[4 * j + 0];
    }
    for (int i = rowbytes - 1; i >= nb; --i)
      dst[i] = static_cast<uint8_t>(dst[i] - dst[i - nb]);
  }
}

}  // namespace

extern "C" {

// Fused tile decode for float32 predictor-3 tiles: (optional) zlib
// inflate + fpAcc + byte unshuffle, one parallel pass over n tiles.
// in_sizes[i] == 0 means a sparse/absent tile -> zero-filled output.
// Short payloads are zero-padded (the Python codec's ljust contract).
int rk_decode_fp3_batch(int64_t n, const uint8_t** in_ptrs,
                        const int64_t* in_sizes, int rows, int cols,
                        int nb, int compressed, float* out,
                        int64_t out_stride_floats, int n_threads) {
  std::atomic<int> status(0);
  const size_t rawbytes = static_cast<size_t>(rows) * 4 * cols * nb;
  parallel_for(n, n_threads, [&](int64_t i) {
    float* dst = out + i * out_stride_floats;
    if (in_sizes[i] == 0) {
      std::memset(dst, 0, rawbytes);
      return;
    }
    std::vector<uint8_t> raw(rawbytes, 0);
    if (compressed) {
      uLongf dest_len = static_cast<uLongf>(rawbytes);
      int rc = uncompress(raw.data(), &dest_len, in_ptrs[i],
                          static_cast<uLong>(in_sizes[i]));
      if (rc != Z_OK) {
        status.store(rc);
        std::memset(dst, 0, rawbytes);
        return;
      }
    } else {
      std::memcpy(raw.data(), in_ptrs[i],
                  std::min(rawbytes, static_cast<size_t>(in_sizes[i])));
    }
    std::vector<uint8_t> scratch;
    fp3_accumulate(raw.data(), rows, cols, nb, dst, scratch);
  });
  return status.load();
}

// Fused tile encode: fpDiff + zlib deflate, one parallel pass.  Input is
// n contiguous float32 tiles at in_stride_floats; output slot i is
// out_buf + i*out_stride with capacity out_stride, byte counts in
// out_sizes.
int rk_encode_fp3_batch(int64_t n, const float* in,
                        int64_t in_stride_floats, int rows, int cols,
                        int nb, int level, uint8_t* out_buf,
                        int64_t out_stride, int64_t* out_sizes,
                        int n_threads) {
  std::atomic<int> status(0);
  const size_t rawbytes = static_cast<size_t>(rows) * 4 * cols * nb;
  parallel_for(n, n_threads, [&](int64_t i) {
    std::vector<uint8_t> raw(rawbytes);
    fp3_difference(in + i * in_stride_floats, rows, cols, nb, raw.data());
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = compress2(out_buf + i * out_stride, &dest_len, raw.data(),
                       static_cast<uLong>(rawbytes), level);
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}

int rk_inflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = uncompress(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                        static_cast<uLong>(in_sizes[i]));
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}

int rk_deflate_batch(int64_t n, const uint8_t** in_ptrs,
                     const int64_t* in_sizes, int level, uint8_t* out_buf,
                     int64_t out_stride, int64_t* out_sizes,
                     int n_threads) {
  std::atomic<int> status(0);
  parallel_for(n, n_threads, [&](int64_t i) {
    uLongf dest_len = static_cast<uLongf>(out_stride);
    int rc = compress2(out_buf + i * out_stride, &dest_len, in_ptrs[i],
                       static_cast<uLong>(in_sizes[i]), level);
    if (rc != Z_OK) {
      status.store(rc);
      out_sizes[i] = 0;
    } else {
      out_sizes[i] = static_cast<int64_t>(dest_len);
    }
  });
  return status.load();
}


}  // extern "C"
