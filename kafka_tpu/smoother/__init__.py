"""Reanalysis: fixed-interval RTS smoothing over the checkpoint chain.

The forward filter conditions every date on the PAST only, so mid-series
uncertainties are strictly worse than what the full series supports.
This package runs the Rauch–Tung–Striebel backward recursion over the
per-timestep analysis states the :class:`~kafka_tpu.engine.Checkpointer`
already persists — near-zero new I/O — and turns the same run into a
reanalysis product: ``kafka-smooth`` (offline driver) and the
``smoothed=true`` serve request kind both answer from it.

The smoother is strictly READ-ONLY over the chain (kafkalint rule
``forward-state-mutation-in-smoother`` enforces this statically): it
loads checkpoint sets, never writes them.  See BASELINE.md "Reanalysis
smoother".
"""

from .rts_pass import (
    QA_CLAMPED,
    QA_REDERIVED,
    QA_SMOOTHED,
    QA_TERMINAL,
    ChainNode,
    SmootherError,
    SmootherResult,
    load_chain,
    smooth_chain,
    smooth_checkpoints,
    state_sha256,
)

__all__ = [
    "QA_CLAMPED",
    "QA_REDERIVED",
    "QA_SMOOTHED",
    "QA_TERMINAL",
    "ChainNode",
    "SmootherError",
    "SmootherResult",
    "load_chain",
    "smooth_chain",
    "smooth_checkpoints",
    "state_sha256",
]
