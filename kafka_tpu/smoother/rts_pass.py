"""The RTS backward pass over a checkpoint chain.

Recursion (information form — ``p_analysis_inverse`` is what the chain
stores; covariances only ever exist as batched per-pixel ``p x p``
inverses on device):

    P_a(t)   = P_a_inv(t)^-1
    G(t)     = P_a(t) M^T P_f_inv(t+1)
    x_s(t)   = x_a(t) + G(t) (x_s(t+1) - x_f(t+1))
    P_s(t)   = P_a(t) + G(t) (P_s(t+1) - P_f(t+1)) G(t)^T

anchored at the newest analysis: ``x_s(T) = x_a(T)``,
``P_s(T) = P_a_inv(T)^-1`` — so the final date is bit-identical to the
filter by construction.  The per-pixel step is vmapped over the pixel
axis and driven by a reverse ``jax.lax.scan``, one jitted program for
the whole sweep (same compilation-cache/pjit path as the forward
filter's fused scan).

The forecast pair ``(x_f(t+1), P_f_inv(t+1))`` comes from the
checkpoint's forecast sidecar when present (``checkpoint.SIDECAR_SCHEMA``)
and is otherwise re-derived by running the configured propagator forward
from the previous analysis — exact whenever the forward run used the
same propagator with no date-varying prior, and the documented
approximation that bridges corrupt or pre-sidecar sets.

Reported uncertainty stays in the filter's convention
(``sigma = 1/sqrt(diag(P_inv))``).  Smoothing can only add information
(``P_s <= P_a`` in the Loewner order, so ``diag(P_s_inv) >=
diag(P_a_inv)``); the smoothed information diagonal is clamped to the
filter's from below at output time so float32 roundoff can never report
a smoothed sigma LARGER than the filter's — the clamp restores a
mathematically guaranteed invariant and never touches the mean.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.propagators import propagate_information_filter
from ..engine.checkpoint import _UNREADABLE_ERRORS, Checkpointer
from ..telemetry import get_registry
from ..telemetry.tracing import trace_span

#: smoother QA bitmask (the ``dump_qa`` twin for the backward pass;
#: 0 outside the state mask, like the forward solver-QA band).
QA_SMOOTHED = 1    #: pixel carries a smoothed value
QA_CLAMPED = 2     #: sigma clamped at the filter floor (f32 roundoff)
QA_REDERIVED = 4   #: forecast re-derived via the propagator (no sidecar)
QA_TERMINAL = 8    #: newest date: smoothed == analysis by construction


class SmootherError(RuntimeError):
    """The chain cannot support a smoothing pass (empty, no information
    matrices, or sidecar-less with no propagator configuration)."""


@dataclasses.dataclass
class ChainNode:
    """One intact checkpoint set, loaded: the analysis state plus the
    optional forecast sidecar ``(x_forecast, p_forecast_inverse)``."""

    timestep: datetime.datetime
    x_analysis: np.ndarray
    p_analysis_inverse: Optional[np.ndarray]
    sidecar: Optional[Tuple[np.ndarray, np.ndarray]] = None


@dataclasses.dataclass
class SmootherResult:
    """The backward pass, oldest first: smoothed means, smoothed
    marginal information diagonals (filter sigma convention), per-pixel
    QA bitmasks, and the dates whose forecast had to be re-derived."""

    timesteps: List[datetime.datetime]
    x_smoothed: np.ndarray          # (T, n, p)
    p_inv_diag: np.ndarray          # (T, n, p) smoothed marginal info
    p_inv_diag_filter: np.ndarray   # (T, n, p) the FILTER's, for QA
    qa: np.ndarray                  # (T, n) uint8 bitmask
    rederived: List[datetime.datetime]
    skipped: List[datetime.datetime]

    def index_of(self, timestep: datetime.datetime) -> int:
        for i, ts in enumerate(self.timesteps):
            if ts == timestep:
                return i
        raise KeyError(f"{timestep} not in smoothed chain")

    def sigma_shrink(self, t: int) -> List[float]:
        """Per-parameter mean ``sigma_smoothed / sigma_filter`` at step
        ``t`` over pixels carrying information — <= 1 for a correct
        pass (the quality-ledger signal for smoothed records)."""
        f = self.p_inv_diag_filter[t]
        s = self.p_inv_diag[t]
        out = []
        for k in range(f.shape[-1]):
            ok = np.isfinite(f[:, k]) & np.isfinite(s[:, k]) \
                & (f[:, k] > 0) & (s[:, k] > 0)
            if not ok.any():
                out.append(float("nan"))
                continue
            out.append(float(np.mean(
                np.sqrt(f[ok, k] / s[ok, k])
            )))
        return out


def state_sha256(x: np.ndarray) -> str:
    """Digest of a smoothed state plane — over ALL stored pixel rows
    (the chain's layout), so the offline driver and the serve path hash
    the same bytes without either knowing the other's pixel mask."""
    return hashlib.sha256(
        np.ascontiguousarray(np.asarray(x, np.float32)).tobytes()
    ).hexdigest()


def load_chain(checkpointer: Checkpointer,
               shard: Optional[int] = None) -> Tuple[List[ChainNode],
                                                     List[datetime.datetime]]:
    """Walk the chain newest -> oldest with ``load_latest``'s corruption
    fallback semantics — an unreadable, incomplete or shape-inconsistent
    set is skipped with the same logged event/counter and the walk
    continues — then return the intact nodes OLDEST first plus the
    skipped timesteps (the recursion bridges them via the propagator)."""
    nodes: List[ChainNode] = []
    skipped: List[datetime.datetime] = []
    for ts, paths, strays in reversed(checkpointer._scan_sets()):
        if paths is None:
            checkpointer._note_unreadable(
                ts, strays,
                "incomplete shard set (missing shard files)",
            )
            skipped.append(ts)
            continue
        use = [paths[shard]] if shard is not None else paths
        try:
            x, p_inv, sidecar = checkpointer._load_set(
                use, with_sidecar=True
            )
        except _UNREADABLE_ERRORS as exc:
            checkpointer._note_unreadable(ts, use, repr(exc)[:300])
            skipped.append(ts)
            continue
        nodes.append(ChainNode(ts, x, p_inv, sidecar))
    nodes.reverse()
    skipped.reverse()
    return nodes, skipped


def _pixel_step(x_a, p_a_inv, x_f, p_f_inv, x_s_next, p_s_next, m_matrix):
    """One pixel's backward update — vmapped over the pixel axis."""
    p_a = jnp.linalg.inv(p_a_inv)
    gain = p_a @ m_matrix.T @ p_f_inv
    x_s = x_a + gain @ (x_s_next - x_f)
    p_f = jnp.linalg.inv(p_f_inv)
    p_s = p_a + gain @ (p_s_next - p_f) @ gain.T
    # Symmetrise against accumulated roundoff: the recursion preserves
    # symmetry exactly, float32 does not.
    return x_s, 0.5 * (p_s + p_s.T)


@partial(jax.jit, static_argnames=())
def _rts_sweep(x_a, p_a_inv, x_f_next, p_f_inv_next, m_matrix,
               x_anchor, p_anchor_inv):
    """The whole backward pass as one program: reverse ``lax.scan`` over
    the stacked steps ``t = 0..T-2`` (oldest first), carry anchored at
    the newest analysis.  Returns the smoothed means and the smoothed
    marginal INFORMATION diagonals for those steps."""
    step = jax.vmap(_pixel_step,
                    in_axes=(0, 0, 0, 0, 0, 0, None))

    def body(carry, inp):
        x_s_next, p_s_next = carry
        xa, pa_inv, xf, pf_inv = inp
        x_s, p_s = step(xa, pa_inv, xf, pf_inv, x_s_next, p_s_next,
                        m_matrix)
        return (x_s, p_s), (x_s, p_s)

    p_anchor = jax.vmap(jnp.linalg.inv)(p_anchor_inv)
    _, (xs, ps) = jax.lax.scan(
        body, (x_anchor, p_anchor),
        (x_a, p_a_inv, x_f_next, p_f_inv_next), reverse=True,
    )
    # Marginal sigma in the filter's convention needs diag(P_s^-1):
    # one more batched inverse over the stacked smoothed covariances.
    ps_inv = jax.vmap(jax.vmap(jnp.linalg.inv))(ps)
    diag_s = jnp.diagonal(ps_inv, axis1=-2, axis2=-1)
    diag_a = jnp.diagonal(p_a_inv, axis1=-2, axis2=-1)
    # Smoothing adds information; clamp restores the invariant under
    # float32 roundoff (QA records where it engaged).
    clamped = jnp.any(diag_s < diag_a, axis=-1)
    return xs, jnp.maximum(diag_s, diag_a), clamped


def _derive_forecast(node: ChainNode, m_matrix, q_diag,
                     state_propagator):
    """Propagator fallback: the forecast at ``t+1`` re-derived from the
    analysis at ``t`` — what the forward run computed, when it used the
    same propagator and no date-varying prior."""
    x_f, p_f, p_f_inv = state_propagator(
        jnp.asarray(node.x_analysis, jnp.float32), None,
        jnp.asarray(node.p_analysis_inverse, jnp.float32),
        m_matrix, q_diag,
    )
    if p_f_inv is None:
        p_f_inv = jax.vmap(jnp.linalg.inv)(p_f)
    return np.asarray(x_f), np.asarray(p_f_inv)


def smooth_chain(nodes: Sequence[ChainNode],
                 m_matrix: Optional[np.ndarray] = None,
                 q_diag: Optional[np.ndarray] = None,
                 state_propagator=propagate_information_filter,
                 skipped: Sequence[datetime.datetime] = (),
                 ) -> SmootherResult:
    """Run the fixed-interval RTS recursion over loaded chain nodes
    (oldest first).  ``m_matrix`` defaults to identity (the reference's
    trajectory model); ``q_diag``/``state_propagator`` configure the
    fallback used wherever a node carries no forecast sidecar."""
    nodes = list(nodes)
    if not nodes:
        raise SmootherError("checkpoint chain is empty")
    for node in nodes:
        if node.p_analysis_inverse is None:
            raise SmootherError(
                f"checkpoint {node.timestep} carries no information "
                "matrix; the smoother gain needs the analysis in "
                "information form"
            )
    p = nodes[0].x_analysis.shape[-1]
    widths = {n.x_analysis.shape for n in nodes}
    if len(widths) > 1:
        raise SmootherError(
            f"chain nodes disagree on the state shape: {sorted(widths)}"
        )
    m = (jnp.eye(p, dtype=jnp.float32) if m_matrix is None
         else jnp.asarray(m_matrix, jnp.float32))
    reg = get_registry()
    rederived: List[datetime.datetime] = []
    timesteps = [n.timestep for n in nodes]

    if len(nodes) == 1:
        only = nodes[0]
        diag = np.ascontiguousarray(np.diagonal(
            only.p_analysis_inverse, axis1=-2, axis2=-1), np.float32)
        qa = np.full((1, only.x_analysis.shape[0]),
                     QA_SMOOTHED | QA_TERMINAL, np.uint8)
        return SmootherResult(
            timesteps, only.x_analysis[None].astype(np.float32),
            diag[None], diag[None].copy(), qa, rederived, list(skipped),
        )

    # Forecast at t+1 for every pair (t, t+1): sidecar when present,
    # propagator fallback otherwise.  A sidecar is NOT usable across a
    # bridged gap (a skipped corrupt set between the pair): it was
    # propagated from the skipped analysis, not from ``prev`` — the
    # propagator bridge re-derives from the surviving neighbour instead.
    x_f_next, p_f_inv_next = [], []
    for prev, node in zip(nodes[:-1], nodes[1:]):
        gap = any(prev.timestep < ts < node.timestep for ts in skipped)
        if node.sidecar is not None and not gap:
            x_f, p_f_inv = node.sidecar
        else:
            if q_diag is None or state_propagator is None:
                raise SmootherError(
                    f"checkpoint {node.timestep} has no forecast "
                    "sidecar; pass q_diag (and the forward run's "
                    "propagator) so the smoother can re-derive it"
                )
            with trace_span("smooth_rederive",
                            timestep=str(node.timestep)):
                x_f, p_f_inv = _derive_forecast(
                    prev, m, jnp.asarray(q_diag, jnp.float32),
                    state_propagator,
                )
            rederived.append(node.timestep)
        x_f_next.append(np.asarray(x_f, np.float32))
        p_f_inv_next.append(np.asarray(p_f_inv, np.float32))

    last = nodes[-1]
    with trace_span("smooth_sweep", windows=len(nodes)):
        xs, diag_s, clamped = _rts_sweep(
            jnp.asarray(np.stack([n.x_analysis for n in nodes[:-1]]),
                        jnp.float32),
            jnp.asarray(
                np.stack([n.p_analysis_inverse for n in nodes[:-1]]),
                jnp.float32),
            jnp.asarray(np.stack(x_f_next), jnp.float32),
            jnp.asarray(np.stack(p_f_inv_next), jnp.float32),
            m,
            jnp.asarray(last.x_analysis, jnp.float32),
            jnp.asarray(last.p_analysis_inverse, jnp.float32),
        )
    xs = np.asarray(xs)
    diag_s = np.asarray(diag_s)
    clamped = np.asarray(clamped)

    n_pix = last.x_analysis.shape[0]
    t_total = len(nodes)
    x_out = np.empty((t_total, n_pix, p), np.float32)
    d_out = np.empty((t_total, n_pix, p), np.float32)
    qa = np.full((t_total, n_pix), QA_SMOOTHED, np.uint8)
    x_out[:-1] = xs
    d_out[:-1] = diag_s
    qa[:-1][clamped] |= QA_CLAMPED
    # Newest date: EXACT passthrough of the filter analysis (never
    # routed through inv(inv(.)) — the bit-identity pin).
    x_out[-1] = np.asarray(last.x_analysis, np.float32)
    d_out[-1] = np.ascontiguousarray(np.diagonal(
        last.p_analysis_inverse, axis1=-2, axis2=-1), np.float32)
    qa[-1] |= QA_TERMINAL
    for ts in rederived:
        qa[timesteps.index(ts)] |= QA_REDERIVED
    d_filter = np.stack([
        np.ascontiguousarray(np.diagonal(
            n.p_analysis_inverse, axis1=-2, axis2=-1), np.float32)
        for n in nodes
    ])

    reg.counter(
        "kafka_smoother_windows_total",
        "checkpointed windows smoothed by RTS backward passes",
    ).inc(t_total)
    if rederived:
        reg.counter(
            "kafka_smoother_rederived_total",
            "smoothed windows whose forecast had no sidecar and was "
            "re-derived through the propagator",
        ).inc(len(rederived))
    reg.emit(
        "smooth_pass", windows=t_total,
        rederived=len(rederived), skipped=len(skipped),
        newest=str(last.timestep),
    )
    return SmootherResult(timesteps, x_out, d_out, d_filter, qa,
                          rederived, list(skipped))


def smooth_checkpoints(checkpointer: Checkpointer,
                       m_matrix: Optional[np.ndarray] = None,
                       q_diag: Optional[np.ndarray] = None,
                       state_propagator=propagate_information_filter,
                       shard: Optional[int] = None) -> SmootherResult:
    """``load_chain`` + ``smooth_chain`` in one call — the entry point
    both ``kafka-smooth`` and the ``smoothed=true`` serve path use, so
    their outputs are the SAME jitted program over the same bytes."""
    nodes, skipped = load_chain(checkpointer, shard=shard)
    return smooth_chain(nodes, m_matrix=m_matrix, q_diag=q_diag,
                        state_propagator=state_propagator,
                        skipped=skipped)
