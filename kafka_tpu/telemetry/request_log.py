"""Per-request "wide events": one structured record per served request.

The metrics registry answers aggregate questions (p99, shed rate) and
the trace timeline answers "what happened on this thread" — neither
answers "why was REQUEST X slow".  This module is that record: both the
router (``serve.router``) and the replica (``serve.service``) append
one JSON line per finished request to ``request_log.jsonl`` under their
telemetry directory, carrying everything a tail-latency investigation
needs in one place:

- identity: ``request_id`` (the per-request trace key), tile, date,
  role (``serve`` / ``route``), replica, run id;
- outcome: status, ``served_from``, ``replayed``;
- attribution: ``e2e_ms`` and the named phase durations
  (``admission_wait_ms`` / ``queue_wait_ms`` / ``resume_ms`` /
  ``solve_ms`` / ``dump_ms`` on a replica; plus ``failover_ms`` /
  ``forward_ms`` / ``relay_ms`` on the router) — the same numbers the
  response's ``trace`` block carries, so ``tools/trace_report.py`` can
  rank slow requests and flag unattributed wall time offline;
- quality: the response's ``solver_health`` / ``quality`` summaries —
  a fast answer with quarantined pixels is not a good answer;
- history: the router's reroute/backoff record (failover forensics).

A bounded in-process ring of the same records (plus the in-flight set)
backs the ``/requestz`` live endpoint and the compact
``recent_requests`` status fact the fleet view renders — the last-N
view with zero file reads.  The on-disk log rotates like
``events.jsonl`` (size-capped segments, keep-N) so a resident daemon's
request history stays bounded.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import live, tracing
from .registry import MetricsRegistry, get_registry

LOG_FILENAME = "request_log.jsonl"

#: rotation defaults (the events.jsonl discipline: size-capped segments,
#: newest ``keep`` survive — bounded on-disk growth for daemons).
ROTATE_BYTES = 32 * 1024 * 1024
KEEP_SEGMENTS = 3

#: bounded in-process history backing /requestz and the fleet view.
RECENT_MAX = 256

#: phase-coverage bar: a request whose named phases attribute less than
#: this fraction of its end-to-end wall time has unexplained latency
#: (``tools/trace_report.py --unattributed`` flags it; loadgen's
#: ``serve_trace_coverage`` row counts the complement).
COVERAGE_TARGET = 0.95

#: absolute slack below which an unattributed remainder is noise, not
#: a finding: a 0.7 ms cache hit with 40 µs of glue fails a 95%
#: FRACTION check while being perfectly explained — the bar is
#: "no unexplained latency", and microseconds are not latency.
UNATTRIBUTED_FLOOR_MS = 1.0


class _State:
    """Per-registry request history (ring + in-flight set), so tests
    isolating the registry (``telemetry.use``) isolate this too."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.recent: deque = deque(maxlen=RECENT_MAX)
        self.inflight: Dict[str, dict] = {}
        self.log_bytes: Optional[int] = None


def _state(registry: Optional[MetricsRegistry] = None) -> _State:
    reg = registry if registry is not None else get_registry()
    st = getattr(reg, "_request_log_state", None)
    if st is None:
        st = reg._request_log_state = _State()
    return st


# ---------------------------------------------------------------------------
# In-flight tracking (the live half of /requestz).
# ---------------------------------------------------------------------------

def note_inflight(request_id: str,
                  registry: Optional[MetricsRegistry] = None,
                  **fields) -> None:
    """Mark one request in flight (admission) or update its stage
    (``stage="queued"/"solving"/"forwarded"``)."""
    st = _state(registry)
    with st.lock:
        rec = st.inflight.setdefault(
            request_id,
            {"request_id": request_id, "ts": round(time.time(), 6)},
        )
        rec.update({k: v for k, v in fields.items() if v is not None})


def clear_inflight(request_id: str,
                   registry: Optional[MetricsRegistry] = None) -> None:
    st = _state(registry)
    with st.lock:
        st.inflight.pop(request_id, None)


# ---------------------------------------------------------------------------
# The wide event itself.
# ---------------------------------------------------------------------------

def build_record(role: str, request_id: str, status: str,
                 e2e_ms: Optional[float],
                 phases: Optional[Dict[str, float]] = None,
                 **fields) -> dict:
    """Assemble one wide-event record (JSON-serialisable)."""
    ctx = tracing.current_context()
    rec = {
        "ts": round(time.time(), 6),
        "role": role,
        "request_id": request_id,
        "status": status,
        "e2e_ms": None if e2e_ms is None else round(float(e2e_ms), 3),
        "phases": {
            k: round(float(v), 3) for k, v in (phases or {}).items()
        },
        "run_id": None if ctx is None else ctx.run_id,
    }
    rec.update({k: v for k, v in fields.items() if v is not None})
    return rec


def record(rec: dict, registry: Optional[MetricsRegistry] = None) -> dict:
    """Land one finished-request record in every sink: the on-disk
    ``request_log.jsonl`` (when a telemetry directory is configured),
    the bounded in-process ring (``/requestz``), the per-role counter,
    and the compact ``recent_requests`` live-status fact the fleet view
    renders."""
    reg = registry if registry is not None else get_registry()
    st = _state(reg)
    with st.lock:
        st.inflight.pop(rec.get("request_id"), None)
        st.recent.append(rec)
        compact = [
            {"request_id": r.get("request_id"),
             "status": r.get("status"),
             "served_from": r.get("served_from"),
             "e2e_ms": r.get("e2e_ms")}
            for r in list(st.recent)[-5:]
        ]
    reg.counter(
        "kafka_request_log_records_total",
        "per-request wide events recorded, labelled by role (the "
        "request_log.jsonl write side)",
    ).inc(role=str(rec.get("role", "?")))
    live.update_status(recent_requests=compact)
    if reg.directory:
        _append(reg, st, rec)
    return rec


def _append(reg: MetricsRegistry, st: _State, rec: dict) -> None:
    path = os.path.join(reg.directory, LOG_FILENAME)
    line = json.dumps(rec, default=str) + "\n"
    try:
        with st.lock:
            if st.log_bytes is None:
                try:
                    st.log_bytes = os.path.getsize(path)
                except OSError:
                    st.log_bytes = 0
            if st.log_bytes >= ROTATE_BYTES:
                _rotate(path)
                st.log_bytes = 0
            with open(path, "a") as f:
                f.write(line)
            st.log_bytes += len(line)
    except OSError as exc:
        # The record must never kill the serving path — degrade to the
        # in-memory ring only, counted.
        reg.emit("request_log_write_failed", error=repr(exc)[:200])


def _rotate(path: str) -> None:
    """events.jsonl shift discipline: .(keep-1) dropped, live -> .1."""
    for i in range(KEEP_SEGMENTS - 1, 0, -1):
        src = f"{path}.{i}"
        if os.path.exists(src):
            os.replace(src, f"{path}.{i + 1}")
    if os.path.exists(path):
        os.replace(path, f"{path}.1")


# ---------------------------------------------------------------------------
# Read side: /requestz and tools/trace_report.py.
# ---------------------------------------------------------------------------

def requestz(n: int = 32,
             registry: Optional[MetricsRegistry] = None) -> dict:
    """The ``/requestz`` payload: in-flight plus the last-``n``
    completed requests, newest first."""
    st = _state(registry)
    with st.lock:
        inflight = sorted(
            st.inflight.values(), key=lambda r: r.get("ts", 0),
        )
        recent = list(st.recent)[-max(0, int(n)):]
    return {"inflight": inflight, "recent": list(reversed(recent))}


def attributed_fraction(rec: dict) -> Optional[float]:
    """Fraction of one record's end-to-end wall time its named phases
    explain (None when the record carries no usable timing)."""
    e2e = rec.get("e2e_ms")
    phases = rec.get("phases") or {}
    if not isinstance(e2e, (int, float)) or e2e <= 0 or not phases:
        return None
    total = sum(v for v in phases.values()
                if isinstance(v, (int, float)) and v > 0)
    return min(1.0, total / float(e2e))


def is_covered(rec: dict,
               target: float = COVERAGE_TARGET) -> Optional[bool]:
    """Whether one record's latency is explained: >= ``target`` of its
    wall time attributed to named phases, OR the unattributed
    remainder below the absolute noise floor
    (:data:`UNATTRIBUTED_FLOOR_MS`).  None when the record carries no
    usable timing."""
    frac = attributed_fraction(rec)
    if frac is None:
        return None
    if frac >= target:
        return True
    return float(rec["e2e_ms"]) * (1.0 - frac) <= UNATTRIBUTED_FLOOR_MS


def log_paths(root: str) -> List[str]:
    """Every ``request_log.jsonl`` (+ rotated segments) under ``root``,
    sorted — rotated segments oldest-first per directory."""
    found: List[str] = []
    if not os.path.isdir(root):
        return found
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        segments = []  # (sort_key, name): rotated .N oldest first, live last
        for fn in filenames:
            if fn == LOG_FILENAME:
                segments.append((0, fn))
            elif fn.startswith(LOG_FILENAME + "."):
                suffix = fn[len(LOG_FILENAME) + 1:]
                if suffix.isdigit():
                    segments.append((-int(suffix), fn))
        found.extend(os.path.join(dirpath, fn)
                     for _, fn in sorted(segments))
    return found


def load_records(root: str) -> Tuple[List[dict], int]:
    """(records, torn_lines) from every request log under ``root``
    (recursive; a torn tail — crash mid-append — is counted and
    skipped, never a crashed report)."""
    records: List[dict] = []
    torn = 0
    for path in log_paths(root):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        torn += 1
                        continue
                    if isinstance(rec, dict) and rec.get("request_id"):
                        records.append(rec)
        except OSError:
            continue
    records.sort(key=lambda r: r.get("ts", 0))
    return records, torn
