"""Host-side metrics registry — the observability layer the reference
lacks entirely (SURVEY.md §5: timestamped DEBUG logging is its only
instrumentation).

One ``MetricsRegistry`` per process holds three metric kinds, all
thread-safe and label-aware:

- :class:`Counter` — monotonically increasing totals (windows assimilated,
  pixels clipped, chunks completed);
- :class:`Gauge` — last-written values (prefetch queue depth, writer
  backlog, health probe readings);
- :class:`Histogram` — bucketed distributions with sum/count/min/max
  (phase wall-times, per-date read times, GN iteration counts).

Two export surfaces:

- **JSONL events** (``events.jsonl`` under the telemetry directory): every
  ``emit()`` appends one ``{"ts", "event", ...}`` line — the structured
  replacement for the reference's DEBUG log, greppable and loadable with
  one ``json.loads`` per line.  A bounded in-memory ring keeps the tail
  available to tests and crash handlers even with no directory configured.
- **Prometheus text exposition** (``metrics.prom``): ``dump()`` writes the
  standard ``name{label="v"} value`` format so a node-exporter textfile
  collector (or any file scraper) picks a run up with zero extra infra,
  plus ``metrics.json`` carrying the full :meth:`snapshot`.

Metric names follow ``kafka_<subsystem>_<name>`` (see BASELINE.md
"Observability"); ``tools/check_metric_names.py`` enforces the convention
statically, so each name literal must appear at exactly one registration
site.
"""

from __future__ import annotations

import collections
import json
import math
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .tracing import TraceBuffer

#: default histogram buckets (seconds-flavoured: spans ~1 ms .. ~2 min,
#: which covers phase walls, reads and chunk runs alike).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 120.0,
)

_NAME_RE = re.compile(r"^kafka_[a-z0-9]+_[a-z0-9_]+$")

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def escape_label_value(value: str) -> str:
    """Prometheus text-exposition label-value escaping (v0.0.4):
    backslash, double-quote and newline must be escaped or the scraped
    line is unparseable — chunk prefixes and error strings end up in
    labels, so this is not theoretical."""
    return (value.replace("\\", "\\\\")
                 .replace('"', '\\"')
                 .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline only (quotes are legal
    in help text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def format_value(value: float) -> str:
    """One sample value in exposition form.  Python's ``{:g}`` renders
    infinities as ``inf``, which Prometheus parsers reject — the format
    spells them ``+Inf`` / ``-Inf`` (and ``NaN``)."""
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return f"{v:g}"


def _label_text(key: LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in key
    ) + "}"


class _Metric:
    """Shared bookkeeping: one value slot per distinct label combination."""

    kind = "metric"

    def __init__(self, name: str, help: str, lock: threading.Lock):
        self.name = name
        self.help = help
        self._lock = lock
        self._values: Dict[LabelKey, Any] = {}

    def value(self, **labels):
        """Current value for this label combination (None if never set)."""
        with self._lock:
            return self._values.get(_label_key(labels))

    def _series(self) -> List[Tuple[LabelKey, Any]]:
        with self._lock:
            return list(self._values.items())


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, lock, buckets: Sequence[float]):
        super().__init__(name, help, lock)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            st = self._values.get(key)
            if st is None:
                st = self._values[key] = {
                    "count": 0, "sum": 0.0,
                    "min": math.inf, "max": -math.inf,
                    "buckets": [0] * len(self.buckets),
                }
            st["count"] += 1
            st["sum"] += float(value)
            st["min"] = min(st["min"], float(value))
            st["max"] = max(st["max"], float(value))
            for i, le in enumerate(self.buckets):
                if value <= le:
                    st["buckets"][i] += 1


class MetricsRegistry:
    """Thread-safe metric store + structured event log.

    ``directory`` (optional) roots the export files: events stream to
    ``events.jsonl`` as they are emitted; ``dump()`` writes
    ``metrics.prom`` and ``metrics.json`` snapshots.  Without a directory
    everything stays in memory (metrics fully usable, events kept in the
    ring only) so instrumented code needs no "is telemetry on" branches.
    """

    #: events.jsonl rotation defaults: segments are size-capped and only
    #: the newest ``keep`` rotated segments survive, so a LONG-LIVED
    #: process (the serving daemon) cannot grow its telemetry without
    #: bound.  Batch runs never reach the cap, so their behaviour is
    #: unchanged.
    EVENTS_ROTATE_BYTES = 32 * 1024 * 1024
    EVENTS_KEEP = 3

    def __init__(self, directory: Optional[str] = None,
                 max_events: int = 4096,
                 events_rotate_bytes: Optional[int] = None,
                 events_keep: Optional[int] = None):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self.directory = directory
        self.events: collections.deque = collections.deque(
            maxlen=max_events
        )
        #: the run's trace timeline (spans + counter samples); exported
        #: as Chrome trace-event JSON by dump().  See telemetry.tracing.
        self.trace = TraceBuffer()
        self._events_fh = None
        self._events_rotate_bytes = (
            events_rotate_bytes if events_rotate_bytes is not None
            else self.EVENTS_ROTATE_BYTES
        )
        self._events_keep = (
            events_keep if events_keep is not None else self.EVENTS_KEEP
        )
        self._events_bytes = 0
        if directory:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, "events.jsonl")
            try:
                self._events_bytes = os.path.getsize(path)
            except OSError:
                self._events_bytes = 0
            self._events_fh = open(path, "a", buffering=1)

    # -- registration ---------------------------------------------------

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} does not follow the "
                "kafka_<subsystem>_<name> convention"
            )
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(
                    name, help, threading.Lock(), **kw
                )
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            elif help and not m.help:
                m.help = help
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    def value(self, name: str, **labels):
        """Read one series' current value; None if absent — the accessor
        the bench health layer and tests consume."""
        with self._lock:
            m = self._metrics.get(name)
        return None if m is None else m.value(**labels)

    # -- events ---------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Append one structured event (ring buffer + JSONL when a
        directory is configured).  Values must be JSON-serialisable.
        The JSONL stream rotates when the current segment passes the
        size cap (``events.jsonl`` -> ``events.jsonl.1`` ...), keeping
        the newest ``events_keep`` segments — bounded on-disk growth for
        long-lived processes."""
        rec = {"ts": round(time.time(), 6), "event": event, **fields}
        self.events.append(rec)
        fh = self._events_fh
        if fh is not None:
            line = json.dumps(rec, default=str) + "\n"
            try:
                fh.write(line)
            except ValueError:  # closed file during teardown
                return
            with self._lock:
                self._events_bytes += len(line)
                if self._events_bytes >= self._events_rotate_bytes:
                    self._rotate_events_locked()

    def _rotate_events_locked(self) -> None:
        """Rotate events.jsonl (caller holds ``self._lock``).  The live
        handle is swapped atomically under the lock so concurrent
        emitters at worst write one late line into the segment being
        rotated (buffering=1 keeps lines whole)."""
        fh = self._events_fh
        if fh is None or not self.directory:
            return
        path = os.path.join(self.directory, "events.jsonl")
        try:
            fh.close()
            # Shift the keep-window: .(keep-1) -> dropped, ... .1 -> .2,
            # live -> .1.  keep=0 means "no history": truncate in place.
            for i in range(self._events_keep - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            if self._events_keep > 0:
                os.replace(path, f"{path}.1")
            else:
                os.unlink(path)
            self._events_fh = open(path, "a", buffering=1)
            self._events_bytes = 0
        except OSError:
            # Rotation is bookkeeping; losing it must not kill the run.
            # Reopen append-mode so events keep flowing either way.
            try:
                self._events_fh = open(path, "a", buffering=1)
                self._events_bytes = os.path.getsize(path)
            except OSError:
                self._events_fh = None

    # -- export ---------------------------------------------------------

    def snapshot(self) -> dict:
        """Full nested snapshot: {name: {"type", "help", "series":
        [{"labels": {...}, "value"|histogram-state}]}}."""
        out: Dict[str, Any] = {}
        for m in self.metrics():
            series = []
            for key, val in m._series():
                entry: Dict[str, Any] = {"labels": dict(key)}
                if m.kind == "histogram":
                    entry.update({
                        k: (None if isinstance(v, float)
                            and math.isinf(v) else v)
                        for k, v in val.items() if k != "buckets"
                    })
                    # Bucket state rides the snapshot so cross-process
                    # consumers (telemetry.aggregate, live snapshots)
                    # can merge histograms and derive fleet quantiles —
                    # count/sum alone cannot reconstruct a p99.
                    entry["le"] = list(m.buckets)
                    entry["buckets"] = list(val["buckets"])
                else:
                    entry["value"] = val
                series.append(entry)
            out[m.name] = {"type": m.kind, "help": m.help, "series": series}
        return out

    def flat(self) -> Dict[str, float]:
        """Compact {name{labels}: value} view of counters and gauges (plus
        histogram count/sum) — the form embedded in bench artifacts."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            for key, val in m._series():
                tag = m.name + _label_text(key)
                if m.kind == "histogram":
                    out[tag + "_count"] = val["count"]
                    out[tag + "_sum"] = round(val["sum"], 6)
                else:
                    out[tag] = val
        return out

    def prom_text(self) -> str:
        """Prometheus text exposition format v0.0.4.

        Histogram ``_bucket{le=}`` lines are CUMULATIVE (each bucket
        counts every observation ``<= le``, the ``+Inf`` bucket equals
        ``_count``) and every series carries ``_sum``/``_count`` —
        scraped latency histograms work with ``histogram_quantile``.
        Label values and HELP text are escaped, non-finite samples are
        spelled ``+Inf``/``-Inf``/``NaN``; the round-trip is pinned by
        the ``telemetry.aggregate.parse_prom_text`` tests."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, val in m._series():
                if m.kind == "histogram":
                    for le, count in zip(m.buckets, val["buckets"]):
                        k = key + (("le", f"{le:g}"),)
                        lines.append(
                            f"{m.name}_bucket{_label_text(k)} {count}"
                        )
                    inf_key = key + (("le", "+Inf"),)
                    lines.append(
                        f"{m.name}_bucket{_label_text(inf_key)} "
                        f"{val['count']}"
                    )
                    lines.append(
                        f"{m.name}_sum{_label_text(key)} "
                        f"{format_value(val['sum'])}"
                    )
                    lines.append(
                        f"{m.name}_count{_label_text(key)} {val['count']}"
                    )
                else:
                    lines.append(
                        f"{m.name}{_label_text(key)} {format_value(val)}"
                    )
        return "\n".join(lines) + "\n"

    def dump(self, directory: Optional[str] = None) -> Optional[str]:
        """Write ``metrics.prom`` + ``metrics.json`` (and ``trace.json``
        when any spans were recorded) into ``directory`` (default: the
        configured one).  Returns the directory or None when there is
        nowhere to write.  The streamed ``events.jsonl`` is flushed first
        so the three artifacts are mutually consistent on disk."""
        directory = directory or self.directory
        if not directory:
            return None
        fh = self._events_fh
        if fh is not None:
            try:
                fh.flush()
            except ValueError:  # lost the race against close()
                pass
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "metrics.prom"), "w") as f:
            f.write(self.prom_text())
        with open(os.path.join(directory, "metrics.json"), "w") as f:
            json.dump(self.snapshot(), f, indent=2, default=str)
        if len(self.trace):
            self.trace.export(os.path.join(directory, "trace.json"))
        return directory

    def close(self) -> None:
        """Close the events stream.  Idempotent and race-safe: the handle
        is detached under the lock, so concurrent dump()/close() callers
        flush/close it exactly once."""
        with self._lock:
            fh, self._events_fh = self._events_fh, None
        if fh is not None:
            fh.close()


# ---------------------------------------------------------------------------
# Process-default registry.  Instrumented modules call ``get_registry()``
# at record time, so ``configure()`` (CLI drivers) or ``use()`` (tests)
# swap the sink without threading a registry through every constructor.
# ---------------------------------------------------------------------------

_default = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    return _default


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Replace the process-default registry; returns the previous one."""
    global _default
    with _default_lock:
        prev, _default = _default, registry
    return prev


def configure(directory: Optional[str],
              events_rotate_bytes: Optional[int] = None,
              events_keep: Optional[int] = None) -> MetricsRegistry:
    """Point the process-default registry at ``directory`` (the CLI
    drivers' ``--telemetry-dir``).  ``None`` resets to in-memory-only.
    ``events_rotate_bytes``/``events_keep`` tune the events.jsonl
    rotation for long-lived processes (the serving daemon)."""
    return_to = MetricsRegistry(
        directory, events_rotate_bytes=events_rotate_bytes,
        events_keep=events_keep,
    )
    set_registry(return_to)
    return return_to


class use:
    """Context manager: temporarily install ``registry`` as the default —
    the test-isolation hook (``with use(MetricsRegistry()) as reg: ...``)."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._prev: Optional[MetricsRegistry] = None

    def __enter__(self) -> MetricsRegistry:
        self._prev = set_registry(self.registry)
        return self.registry

    def __exit__(self, *exc) -> None:
        set_registry(self._prev)
