"""Crash flight recorder: post-mortem forensics for dead runs.

Before this module, a chunk worker dying mid-run left ONE artifact: a
missing ``.done`` marker.  The flight recorder turns every abnormal end —
unhandled exception, SIGTERM/SIGINT, or an unhealthy health-probe verdict
(``health.probe_health``) — into a readable ``crash_<ts>.json`` next to
the run's telemetry:

- the tail of the registry's bounded event ring (the last solves, phases,
  chunk completions and health probes before death);
- the final metric values (``MetricsRegistry.flat()``);
- the active :class:`~.tracing.TraceContext` (run/chunk/window ids);
- the exception (type, message, traceback) or signal that killed the run;
- a stack snapshot of every live thread (prefetcher stuck in a read?
  writer wedged on disk?).

It also best-effort flushes the registry's normal exports
(``metrics.prom`` / ``metrics.json`` / ``trace.json``) so the timeline
survives the crash too.

Installed by every CLI driver, the chunk worker and ``bench.py``
(module-level :func:`install` — idempotent per process).  Dumps are
written only when a destination exists (the recorder's directory or the
registry's telemetry directory): a run without ``--telemetry-dir`` opted
out of run artifacts, and scattering crash files into random working
directories would be litter, not forensics.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from typing import Optional

from .registry import get_registry
from .tracing import current_context


class FlightRecorder:
    """Bounded-ring crash dumper; also a context manager (``with
    recorder:`` dumps on exception and re-raises)."""

    #: events kept in a dump (the registry ring may hold more).
    MAX_DUMP_EVENTS = 256

    #: accumulated ``crash_*.json`` files kept in the destination
    #: directory — after each dump the oldest beyond this cap are
    #: removed, so a long-lived process that keeps hitting (and
    #: surviving) unhealthy-probe or per-request crash dumps cannot fill
    #: the disk.  The filename's timestamp prefix sorts chronologically.
    MAX_CRASH_DUMPS = 16

    def __init__(self, directory: Optional[str] = None):
        self.directory = directory
        self._lock = threading.Lock()
        self._installed = False
        self._prev_excepthook = None
        self._prev_handlers: dict = {}
        #: id() of the last exception dumped — the guard and the
        #: excepthook may both see the same exception; one dump only.
        self._last_exc_id: Optional[int] = None

    # -- dump -----------------------------------------------------------

    def _target_dir(self) -> Optional[str]:
        return self.directory or get_registry().directory

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             ) -> Optional[str]:
        """Write ``crash_<ts>.json``; returns the path (None when no
        destination directory exists or this exception already dumped)."""
        with self._lock:
            if exc is not None:
                if id(exc) == self._last_exc_id:
                    return None
                self._last_exc_id = id(exc)
            directory = self._target_dir()
            if not directory:
                return None
            reg = get_registry()
            ctx = current_context()
            rec = {
                "reason": reason,
                "ts": round(time.time(), 6),
                "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
                "pid": os.getpid(),
                "context": None if ctx is None else ctx.fields(),
                "exception": None,
                "threads": self._thread_snapshot(),
                "events": list(reg.events)[-self.MAX_DUMP_EVENTS:],
                "metrics": reg.flat(),
            }
            if exc is not None:
                rec["exception"] = {
                    "type": type(exc).__name__,
                    "message": str(exc),
                    "traceback": traceback.format_exception(
                        type(exc), exc, exc.__traceback__
                    ),
                }
                # Device-plane OOM forensics (telemetry.devprof): a
                # RESOURCE_EXHAUSTED (or injected device.oom) unwind
                # gets the live-buffer census, the newest kernel table
                # and the per-device memory stats attached — the dump
                # names the resident buffers, not just the allocator's
                # apology.  Best effort: the dump itself must survive
                # a forensics failure.
                try:
                    from .devprof import forensics, is_oom

                    if is_oom(exc):
                        rec["device_forensics"] = forensics(reg)
                except Exception:  # noqa: BLE001 — forensics are garnish on the dump
                    pass
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(
                directory,
                f"crash_{time.strftime('%Y%m%dT%H%M%S')}_{os.getpid()}.json",
            )
            with open(path, "w") as f:
                json.dump(rec, f, indent=2, default=str)
            # Flush the run's normal exports too — a crash is exactly when
            # the timeline matters most.  Best effort: the dump above is
            # the primary artifact and must survive an export failure.
            try:
                reg.dump(directory)
            except OSError:
                pass
            reg.emit("crash_dump", reason=reason, path=path)
            self._prune_dumps(directory)
        # Refresh the live heartbeat snapshot so the fleet view points
        # at this forensics file NOW, not one publish interval later —
        # for a process about to die, "later" never comes.  Late import:
        # live builds on the registry only, no cycle.
        from .live import publish_now

        publish_now()
        return path

    def _prune_dumps(self, directory: str) -> None:
        """Drop the oldest ``crash_*.json`` beyond MAX_CRASH_DUMPS."""
        try:
            dumps = sorted(
                n for n in os.listdir(directory)
                if n.startswith("crash_") and n.endswith(".json")
            )
            for name in dumps[:-self.MAX_CRASH_DUMPS or None]:
                os.unlink(os.path.join(directory, name))
        except OSError:
            pass  # pruning is hygiene; the dump above is the artifact

    @staticmethod
    def _thread_snapshot() -> list:
        frames = sys._current_frames()
        out = []
        for t in threading.enumerate():
            frame = frames.get(t.ident)
            out.append({
                "name": t.name,
                "daemon": t.daemon,
                "stack": (
                    traceback.format_stack(frame) if frame is not None
                    else None
                ),
            })
        return out

    # -- hooks ----------------------------------------------------------

    def install(self) -> "FlightRecorder":
        """Install the excepthook and SIGTERM/SIGINT handlers (signal
        install degrades gracefully off the main thread)."""
        if self._installed:
            return self
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._on_signal
                )
            except ValueError:
                # signal.signal only works on the main thread; a recorder
                # installed from a worker still gets excepthook + guard.
                pass
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        # == not `is`: attribute access mints a fresh bound method, so
        # identity against the one stored in sys.excepthook never holds.
        if sys.excepthook == self._excepthook:
            sys.excepthook = self._prev_excepthook
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                pass
        self._prev_handlers.clear()
        self._installed = False

    def _excepthook(self, etype, evalue, tb) -> None:
        try:
            self.dump("exception", exc=evalue)
        finally:
            (self._prev_excepthook or sys.__excepthook__)(etype, evalue, tb)

    def _on_signal(self, signum, frame) -> None:
        self.dump(
            "sigterm" if signum == signal.SIGTERM else "sigint"
        )
        # Hand the signal back to whoever owned it: restore the previous
        # handler and re-raise, so default termination semantics (or an
        # outer supervisor's handler) still apply after the dump.
        prev = self._prev_handlers.get(signum)
        signal.signal(
            signum, prev if prev is not None else signal.SIG_DFL
        )
        self._prev_handlers.pop(signum, None)
        signal.raise_signal(signum)

    # -- guard ----------------------------------------------------------

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, etype, evalue, tb) -> bool:
        if evalue is not None:
            self.dump("exception", exc=evalue)
        return False  # never swallow


# ---------------------------------------------------------------------------
# Process-level recorder: one per process, shared by driver + health layer.
# ---------------------------------------------------------------------------

_active: Optional[FlightRecorder] = None


def install(directory: Optional[str] = None) -> FlightRecorder:
    """Install (or return) the process recorder; a later call with a
    directory re-points an already-installed recorder at it."""
    global _active
    if _active is None:
        _active = FlightRecorder(directory).install()
    elif directory:
        _active.directory = directory
    return _active


def active_recorder() -> Optional[FlightRecorder]:
    return _active


def uninstall() -> None:
    """Remove the process recorder's hooks (test teardown)."""
    global _active
    if _active is not None:
        _active.uninstall()
        _active = None
