"""Device-plane observability: kernel-time attribution, the HBM memory
ledger, and mesh/sharding introspection.

The host-side observability plane (PRs 10-15) watches processes,
science and SLOs; the device itself stayed a black box: ``/profilez``
dumps raw ``jax.profiler`` capture dirs nothing parses, the memory
watermark publishes bare byte gauges with no buffer attribution, and
nothing reports mesh topology or collective time at all.  This module
is the device half, in three parts (BASELINE.md "Device-plane
observability"):

- **Kernel-time attribution**: a stdlib-only (gzip+json) parser for the
  ``*.trace.json.gz`` Chrome traces inside profiler capture dirs.
  Device-lane spans (XLA kernel executions, identified by their
  ``hlo_op``/``hlo_module`` args or a ``/device:`` process track) fold
  into a ranked per-kernel table with fusion/collective/transfer
  buckets, publish ``kafka_devprof_kernel_ms_total{bucket=}`` and the
  collective-time fraction gauge, and join the stitched fleet trace as
  device lanes beside the host phase spans (``aggregate.stitch_traces``
  aligns them on the ``capture_meta.json`` epoch sidecar
  ``telemetry.perf`` writes at capture start).  The measured device
  time cross-checks against the analytic ``perf.min_traffic_*`` bounds
  (:func:`roofline_crosscheck`).  Surfaced by ``/kernelz`` and
  ``tools/device_report.py``.
- **HBM memory ledger**: a live-buffer census via ``jax.live_arrays()``
  grouped by (shape, dtype, sharding) — host-side array metadata only,
  zero device->host transfers — refreshed per assimilated window by
  ``device.record_memory_watermark`` and captured as OOM forensics:
  when a ``RESOURCE_EXHAUSTED`` (or a fault-injected ``device.oom``)
  unwinds, the flight recorder attaches :func:`forensics` — the census,
  the newest kernel table and the per-device memory stats — so a
  mesh-scale OOM names the resident buffers post mortem.
- **Mesh introspection**: ``/meshz`` reports device topology, mesh
  axes (:func:`note_mesh`, registered by the engine's mesh path), the
  partition specs of compiled solve programs (:func:`note_compiled`,
  from ``lower().compile()`` metadata), the per-device share of parsed
  device time, and the collective fraction — the per-shard balance
  view the ROADMAP's tile-year mesh item needs on day one.

Everything degrades gracefully on the CPU backend: the parser works on
CPU captures (XLA CPU kernel spans carry ``hlo_op`` too), the census
returns host-buffer groups, and ``/meshz`` reports topology with no
mesh registered.
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
import weakref
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .registry import MetricsRegistry, get_registry

#: epoch sidecar filename written by ``perf._start_trace`` at the
#: capture root — the wall-clock anchor that lets stitched traces put
#: device lanes on the same axis as the TraceBuffer host spans (the
#: profiler's own timestamps are monotonic ticks with no epoch).
CAPTURE_META = "capture_meta.json"

#: kernel-table rows kept per capture (ranked by total time; the long
#: tail is aggregated into the table's ``truncated_ms`` remainder).
MAX_KERNELS = 64

#: buffer-census groups kept (ranked by resident bytes).
MAX_CENSUS_GROUPS = 64

#: minimum seconds between per-window ledger censuses.  The watermark
#: tick rides EVERY engine window; walking ``jax.live_arrays()`` each
#: time is O(live buffers) host work that dominates short windows
#: (measured 5x wall on the CPU-mesh driver test).  The gauges only
#: feed dashboards, so a stale-by-seconds census is fine — and OOM
#: forensics takes its OWN fresh census at dump time regardless.
LEDGER_MIN_INTERVAL_S = 15.0


# ---------------------------------------------------------------------------
# Kernel buckets.
# ---------------------------------------------------------------------------

_COLLECTIVE_TOKENS = (
    "all-reduce", "allreduce", "all-gather", "allgather",
    "reduce-scatter", "reducescatter", "all-to-all", "alltoall",
    "collective", "psum", "ppermute",
)
_TRANSFER_TOKENS = (
    "copy", "memcpy", "transfer", "infeed", "outfeed", "send", "recv",
)


def bucket_for(name: str) -> str:
    """fusion / collective / transfer / other, from the kernel name —
    the label vocabulary of ``kafka_devprof_kernel_ms_total``."""
    low = name.lower()
    if any(t in low for t in _COLLECTIVE_TOKENS):
        return "collective"
    if any(t in low for t in _TRANSFER_TOKENS):
        return "transfer"
    if "fusion" in low:
        return "fusion"
    return "other"


# ---------------------------------------------------------------------------
# Per-registry state (the perf._states weakref pattern).
# ---------------------------------------------------------------------------

class _DevprofState:
    def __init__(self):
        self.lock = threading.Lock()
        #: ranked kernel table of the newest parsed capture.
        self.kernel_table: List[dict] = []
        self.capture_dir: Optional[str] = None
        self.device_ms: float = 0.0
        self.collective_fraction: Optional[float] = None
        #: per-device-lane share of parsed device time (track -> frac).
        self.device_split: Dict[str, float] = {}
        self.n_captures_parsed = 0
        #: newest live-buffer census (memory ledger).
        self.census: List[dict] = []
        self.census_bytes: float = 0.0
        #: monotonic time of the newest census (throttle anchor).
        self.census_t: Optional[float] = None
        #: mesh facts registered by the engine / compile sites.
        self.mesh: Optional[dict] = None
        self.programs: Dict[str, dict] = {}


_states: "weakref.WeakKeyDictionary[MetricsRegistry, _DevprofState]" = \
    weakref.WeakKeyDictionary()
_states_lock = threading.Lock()


def _state_for(reg: MetricsRegistry) -> _DevprofState:
    with _states_lock:
        st = _states.get(reg)
        if st is None:
            st = _states[reg] = _DevprofState()
        return st


def _parse_failures(reg: MetricsRegistry):
    """Single registration site (metric-name lint)."""
    return reg.counter(
        "kafka_devprof_parse_failures_total",
        "profiler captures that could not be parsed into a kernel "
        "table (malformed/empty trace.json.gz) — the run degrades, "
        "never crashes",
    )


# ---------------------------------------------------------------------------
# Capture discovery and parsing (stdlib only: gzip + json).
# ---------------------------------------------------------------------------

def find_capture_sessions(root: str) -> List[str]:
    """Profiler session dirs under ``root``: every directory holding at
    least one ``*.trace.json.gz`` (jax.profiler lays captures out as
    ``<root>/plugins/profile/<ts>/<host>.trace.json.gz``), sorted so
    the newest timestamped session is last."""
    sessions: List[str] = []
    if not os.path.isdir(root):
        return sessions
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        if any(fn.endswith(".trace.json.gz") for fn in filenames):
            sessions.append(dirpath)
    return sorted(sessions)


def capture_epoch(session_dir: str, stop_at: Optional[str] = None,
                  ) -> Optional[float]:
    """The wall-clock epoch of a capture session, from the
    ``capture_meta.json`` sidecar ``perf._start_trace`` wrote at the
    capture root (the session dir sits a few ``plugins/profile/<ts>``
    levels below it).  None when no sidecar exists — an externally
    produced capture still parses, it just can't be epoch-aligned."""
    d = os.path.abspath(session_dir)
    stop = os.path.abspath(stop_at) if stop_at else None
    for _ in range(6):
        meta = os.path.join(d, CAPTURE_META)
        if os.path.isfile(meta):
            try:
                with open(meta, encoding="utf-8") as f:
                    doc = json.load(f)
                return float(doc["epoch_unix_s"])
            except (OSError, ValueError, KeyError, TypeError):
                return None
        parent = os.path.dirname(d)
        if parent == d or (stop is not None and d == stop):
            return None
        d = parent
    return None


def load_capture_events(session_dir: str) -> Tuple[List[dict], int]:
    """Every trace event from every ``*.trace.json.gz`` in the session
    dir, plus the count of files that failed to parse."""
    events: List[dict] = []
    errors = 0
    try:
        names = sorted(os.listdir(session_dir))
    except OSError:
        return events, 1
    for fn in names:
        if not fn.endswith(".trace.json.gz"):
            continue
        path = os.path.join(session_dir, fn)
        try:
            with gzip.open(path, "rt", encoding="utf-8",
                           errors="replace") as f:
                doc = json.load(f)
            ev = doc.get("traceEvents") if isinstance(doc, dict) else None
            if not isinstance(ev, list):
                errors += 1
                continue
            events.extend(e for e in ev if isinstance(e, dict))
        except (OSError, ValueError, EOFError):
            errors += 1
    return events, errors


def _track_names(events: Iterable[dict],
                 ) -> Tuple[Dict[Any, str], Dict[Tuple[Any, Any], str]]:
    """(pid -> process name, (pid, tid) -> thread name) from the
    metadata events."""
    procs: Dict[Any, str] = {}
    threads: Dict[Tuple[Any, Any], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        name = (e.get("args") or {}).get("name")
        if not isinstance(name, str):
            continue
        if e.get("name") == "process_name":
            procs[e.get("pid")] = name
        elif e.get("name") == "thread_name":
            threads[(e.get("pid"), e.get("tid"))] = name
    return procs, threads


def device_events(events: List[dict]) -> List[dict]:
    """The device-lane kernel spans of a capture: complete (``ph: X``)
    events that carry XLA HLO attribution (``args.hlo_op`` /
    ``args.hlo_module`` — how the CPU backend labels kernel executions)
    or sit on a ``/device:`` process track (how TPU device lanes are
    named).  Host python frames and infra dispatch spans stay out."""
    procs, _ = _track_names(events)
    out: List[dict] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        on_device_track = procs.get(e.get("pid"), "").startswith("/device:")
        if "hlo_op" in args or "hlo_module" in args or on_device_track:
            out.append(e)
    return out


def kernel_table_from_events(dev_events: List[dict],
                             max_kernels: int = MAX_KERNELS) -> dict:
    """Aggregate device spans into the ranked kernel table:
    ``{"kernels": [{name, bucket, ms, count, fraction}...],
    "device_ms", "by_bucket", "collective_fraction", "truncated_ms"}``.
    Fractions are of total parsed device time."""
    acc: Dict[str, List[float]] = {}
    for e in dev_events:
        dur = e.get("dur")
        if not isinstance(dur, (int, float)) or dur < 0:
            continue
        name = str(e.get("name") or "?")
        cell = acc.setdefault(name, [0.0, 0.0])
        cell[0] += float(dur) / 1000.0  # us -> ms
        cell[1] += 1.0
    total_ms = sum(v[0] for v in acc.values())
    by_bucket: Dict[str, float] = {}
    ranked = sorted(acc.items(), key=lambda kv: -kv[1][0])
    kernels: List[dict] = []
    for name, (ms, count) in ranked:
        by_bucket[bucket_for(name)] = \
            by_bucket.get(bucket_for(name), 0.0) + ms
        if len(kernels) < max_kernels:
            kernels.append({
                "name": name,
                "bucket": bucket_for(name),
                "ms": round(ms, 4),
                "count": int(count),
                "fraction": round(ms / total_ms, 4) if total_ms else 0.0,
            })
    truncated_ms = total_ms - sum(k["ms"] for k in kernels)
    return {
        "kernels": kernels,
        "device_ms": round(total_ms, 4),
        "by_bucket": {b: round(v, 4) for b, v in sorted(by_bucket.items())},
        "collective_fraction": (
            round(by_bucket.get("collective", 0.0) / total_ms, 4)
            if total_ms else None
        ),
        "truncated_ms": round(max(0.0, truncated_ms), 4),
    }


def _device_split(dev_events: List[dict], events: List[dict],
                  ) -> Dict[str, float]:
    """Per-device-track share of parsed device time — the per-shard
    balance column of ``/meshz`` (one entry on a single-device CPU
    run; a skewed mesh shows up as unequal fractions)."""
    procs, _ = _track_names(events)
    per: Dict[str, float] = {}
    for e in dev_events:
        dur = e.get("dur")
        if not isinstance(dur, (int, float)):
            continue
        track = procs.get(e.get("pid")) or f"pid{e.get('pid')}"
        per[track] = per.get(track, 0.0) + float(dur)
    total = sum(per.values())
    if total <= 0:
        return {}
    return {t: round(v / total, 4) for t, v in sorted(per.items())}


def parse_capture(session_dir: str) -> Optional[dict]:
    """One session dir -> parsed capture summary (kernel table +
    device split), or None when nothing parseable/attributable was
    found.  Pure function — no registry side effects (callers count)."""
    events, errors = load_capture_events(session_dir)
    dev = device_events(events)
    if not dev:
        return None
    table = kernel_table_from_events(dev)
    table["session_dir"] = session_dir
    table["parse_errors"] = errors
    table["device_split"] = _device_split(dev, events)
    return table


def ingest_capture(root: str,
                   registry: Optional[MetricsRegistry] = None,
                   ) -> Optional[dict]:
    """Parse the NEWEST capture session under ``root`` into the
    registry's devprof state and publish the kernel metrics.  Called by
    ``telemetry.perf`` after every completed capture (``/profilez`` and
    ``--profile-windows`` both), so ``/kernelz`` is live the moment a
    capture lands.  A malformed or empty capture increments
    ``kafka_devprof_parse_failures_total`` and emits a
    ``devprof_parse_failed`` event — degrade, never crash."""
    reg = registry if registry is not None else get_registry()
    sessions = find_capture_sessions(root)
    table = parse_capture(sessions[-1]) if sessions else None
    if table is None:
        _parse_failures(reg).inc()
        reg.emit(
            "devprof_parse_failed", directory=root,
            sessions=len(sessions),
        )
        return None
    st = _state_for(reg)
    with st.lock:
        st.kernel_table = table["kernels"]
        st.capture_dir = table["session_dir"]
        st.device_ms = table["device_ms"]
        st.collective_fraction = table["collective_fraction"]
        st.device_split = table["device_split"]
        st.n_captures_parsed += 1
    kernel_ms = reg.counter(
        "kafka_devprof_kernel_ms_total",
        "parsed device kernel time (ms) from profiler captures, by "
        "fusion/collective/transfer/other bucket",
    )
    for b, ms in table["by_bucket"].items():
        kernel_ms.inc(ms, bucket=b)
    if table["collective_fraction"] is not None:
        reg.gauge(
            "kafka_devprof_collective_fraction",
            "fraction of parsed device time spent in collectives "
            "(newest capture) — the mesh-balance red flag",
        ).set(table["collective_fraction"])
    reg.counter(
        "kafka_devprof_captures_parsed_total",
        "profiler captures parsed into a kernel table",
    ).inc()
    reg.emit(
        "devprof_capture_parsed", directory=table["session_dir"],
        device_ms=table["device_ms"],
        kernels=len(table["kernels"]),
        collective_fraction=table["collective_fraction"],
    )
    return table


def roofline_crosscheck(registry: Optional[MetricsRegistry] = None,
                        ) -> Optional[dict]:
    """Measured-vs-analytic cross-check: the newest capture's measured
    device time against the analytic minimum-traffic time of the last
    recorded window's solve (``perf.min_traffic_*`` over the HBM roof).
    The ratio is a consistency probe, not a utilization claim — a
    capture spans many windows, so only the ORDER of magnitude should
    agree; None when either side is missing (no capture, no window)."""
    from . import perf

    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    dims = perf.last_window_dims(reg)
    with st.lock:
        device_ms = st.device_ms
        have_capture = st.n_captures_parsed > 0
    if not have_capture or device_ms <= 0 or dims is None:
        return None
    n_pad, n_params, n_bands, component = dims
    bound_fn = perf.TRAFFIC_BOUNDS.get(component,
                                       perf.min_traffic_gn_full)
    bound_bytes = bound_fn(n_pad, n_params, n_bands)
    analytic_ms = bound_bytes / (perf.HBM_GBPS * 1e9) * 1e3
    return {
        "measured_device_ms": round(device_ms, 4),
        "analytic_min_ms_per_window": round(analytic_ms, 6),
        "component": component,
        "n_pad": n_pad,
        "n_params": n_params,
        "n_bands": n_bands,
        "measured_over_analytic": (
            round(device_ms / analytic_ms, 2) if analytic_ms > 0 else None
        ),
    }


def kernel_summary(registry: Optional[MetricsRegistry] = None,
                   n: int = 16) -> dict:
    """The ``/kernelz`` payload: newest ranked kernel table, bucket
    split, collective fraction, and the roofline cross-check."""
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    with st.lock:
        table = list(st.kernel_table[:max(0, n)])
        payload = {
            "captures_parsed": st.n_captures_parsed,
            "capture_dir": st.capture_dir,
            "device_ms": round(st.device_ms, 4),
            "collective_fraction": st.collective_fraction,
            "kernels": table,
        }
    payload["roofline_crosscheck"] = roofline_crosscheck(reg)
    return payload


# ---------------------------------------------------------------------------
# Stitched-trace fold-in: device lanes beside the host phase spans.
# ---------------------------------------------------------------------------

def device_lane_tracks(root: str, epoch0: float, first_pid: int,
                       ) -> Tuple[List[dict], List[dict]]:
    """Device-lane Chrome-trace tracks for every capture session under
    ``root``, pid-remapped from ``first_pid`` and shifted onto the
    stitched timeline's shared epoch axis.

    The profiler's timestamps are monotonic ticks with no wall-clock
    anchor, so alignment pins each session's EARLIEST device event to
    the ``capture_meta.json`` epoch recorded when the capture started —
    exact to within profiler startup latency, which is enough to read
    "which host phase was live during this kernel burst" off one
    Perfetto window.  Sessions with no sidecar pin to ``epoch0``
    (trace-relative time zero).  Returns ``(events, sources)`` in
    ``stitch_traces``'s vocabulary.
    """
    events: List[dict] = []
    sources: List[dict] = []
    pid = first_pid
    for session in find_capture_sessions(root):
        raw, _ = load_capture_events(session)
        dev = device_events(raw)
        if not dev:
            continue
        epoch = capture_epoch(session, stop_at=root)
        ts_min = min(e.get("ts", 0) for e in dev)
        shift = ((epoch - epoch0) * 1e6 if epoch is not None else 0.0) \
            - ts_min
        rel = os.path.relpath(session, root).replace(os.sep, "/")
        _, threads = _track_names(raw)
        events.append({
            "name": "process_name", "ph": "M", "ts": 0.0,
            "pid": pid, "tid": 0,
            "args": {"name": f"kafka_tpu device {rel}"},
        })
        seen_tids = set()
        for e in dev:
            tid = e.get("tid", 0)
            if tid not in seen_tids:
                seen_tids.add(tid)
                tname = threads.get((e.get("pid"), tid))
                if tname:
                    events.append({
                        "name": "thread_name", "ph": "M", "ts": 0.0,
                        "pid": pid, "tid": tid,
                        "args": {"name": tname},
                    })
            events.append({
                "name": e.get("name"), "ph": "X",
                "ts": round(float(e.get("ts", 0)) + shift, 1),
                "dur": e.get("dur"),
                "pid": pid, "tid": tid,
                "args": {
                    k: v for k, v in (e.get("args") or {}).items()
                    if k in ("hlo_op", "hlo_module", "long_name")
                },
            })
        sources.append({
            "pid": pid, "path": rel,
            "epoch_unix_s": epoch, "device_lane": True,
        })
        pid += 1
    return events, sources


# ---------------------------------------------------------------------------
# HBM memory ledger: live-buffer census (host-side metadata only).
# ---------------------------------------------------------------------------

def buffer_census(max_groups: int = MAX_CENSUS_GROUPS) -> List[dict]:
    """Live device buffers grouped by (shape, dtype, sharding), ranked
    by resident bytes.  ``jax.live_arrays()`` and the per-array fields
    read here are HOST-side bookkeeping — the census adds ZERO
    device->host transfers (the ``kafka_engine_device_reads_total``
    invariant is untouched).  Degrades to ``[]`` when the runtime
    refuses (stripped build, teardown)."""
    try:
        import jax

        arrays = jax.live_arrays()
    except Exception:  # noqa: BLE001 — census is forensics, never a crash
        return []
    groups: Dict[Tuple[str, str, str], List[float]] = {}
    for a in arrays:
        try:
            shape = tuple(a.shape)
            dtype = str(a.dtype)
            # The partition spec (or the sharding's type for the
            # spec-less kinds) — NOT repr(sharding), whose embedded
            # mesh/device listing is far too expensive per array.
            sh = getattr(a, "sharding", None)
            spec = getattr(sh, "spec", None)
            sharding = (
                str(spec) if spec is not None
                else type(sh).__name__ if sh is not None else "None"
            )
            nbytes = float(a.dtype.itemsize)
            for dim in shape:
                nbytes *= dim
        except Exception:  # noqa: BLE001 — a deleted/donated array mid-iteration
            continue
        key = (str(shape), dtype, sharding)
        cell = groups.setdefault(key, [0.0, 0.0])
        cell[0] += nbytes
        cell[1] += 1.0
    ranked = sorted(groups.items(), key=lambda kv: -kv[1][0])
    return [
        {
            "shape": shape, "dtype": dtype, "sharding": sharding,
            "count": int(count), "bytes": int(nbytes),
        }
        for (shape, dtype, sharding), (nbytes, count)
        in ranked[:max_groups]
    ]


def update_ledger(registry: Optional[MetricsRegistry] = None,
                  force: bool = False) -> List[dict]:
    """Refresh the per-window memory ledger: take a buffer census,
    store it as this registry's newest ledger entry, and publish the
    live-buffer gauges.  Called from
    ``device.record_memory_watermark`` — once per window, host-side.
    Throttled to one census per ``LEDGER_MIN_INTERVAL_S`` (the walk is
    O(live buffers) — too hot for every short window); ``force=True``
    bypasses the throttle (tests, forensics-adjacent callers)."""
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    now = time.monotonic()
    if not force:
        with st.lock:
            last, census = st.census_t, st.census
        if last is not None and now - last < LEDGER_MIN_INTERVAL_S:
            return census
    census = buffer_census()
    total = float(sum(g["bytes"] for g in census))
    n = sum(g["count"] for g in census)
    with st.lock:
        st.census = census
        st.census_bytes = total
        st.census_t = now
    reg.gauge(
        "kafka_devprof_live_buffer_bytes",
        "bytes resident in live jax arrays (buffer-census total, "
        "host-side metadata — no device reads)",
    ).set(total)
    reg.gauge(
        "kafka_devprof_live_buffers",
        "count of live jax arrays in the newest buffer census",
    ).set(float(n))
    return census


# ---------------------------------------------------------------------------
# OOM forensics.
# ---------------------------------------------------------------------------

def is_oom(exc: Optional[BaseException]) -> bool:
    """True when the exception is a device out-of-memory unwind: an XLA
    ``RESOURCE_EXHAUSTED``, an allocator OOM message, or an injected
    fault at the ``device.oom`` chaos site."""
    if exc is None:
        return False
    if getattr(exc, "site", None) == "device.oom":
        return True
    text = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in text
            or "Out of memory" in text
            or "out of memory" in text)


def forensics(registry: Optional[MetricsRegistry] = None) -> dict:
    """The OOM forensic bundle the flight recorder attaches to a crash
    dump: a FRESH buffer census (what is resident right now, the
    question an OOM asks), the newest kernel table, and the per-device
    memory stats."""
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    with st.lock:
        table = list(st.kernel_table[:16])
    mem: List[dict] = []
    try:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats() or {}
            except Exception:  # noqa: BLE001 — per-backend API, optional
                stats = {}
            mem.append({
                "device": d.id,
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            })
    except Exception:  # noqa: BLE001 — backend gone mid-crash
        pass
    return {
        "buffer_census": buffer_census(),
        "kernel_table": table,
        "memory": mem,
    }


# ---------------------------------------------------------------------------
# Mesh / sharding introspection.
# ---------------------------------------------------------------------------

def note_mesh(mesh: Any,
              registry: Optional[MetricsRegistry] = None) -> None:
    """Register the engine's device mesh (axis names/sizes) for
    ``/meshz``.  Called by the engine's mesh path at construction."""
    reg = registry if registry is not None else get_registry()
    try:
        axes = {
            str(name): int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        }
        n = int(mesh.devices.size)
    except Exception:  # noqa: BLE001 — anything mesh-shaped is acceptable, nothing is required
        axes, n = {}, 0
    st = _state_for(reg)
    with st.lock:
        st.mesh = {"axes": axes, "n_devices": n}


def _spec_strings(shardings: Any) -> List[str]:
    out: List[str] = []
    for s in shardings or ():
        spec = getattr(s, "spec", None)
        out.append(str(spec) if spec is not None else str(s))
    return out


def note_compiled(name: str, compiled: Any,
                  registry: Optional[MetricsRegistry] = None) -> None:
    """Register one compiled program's partition specs for ``/meshz``,
    from ``jax.jit(f).lower(...).compile()`` metadata.  Extraction is
    best-effort across jax versions — a program that exposes nothing
    still registers (name only), so the endpoint shows WHAT compiled
    even when the sharding metadata moved."""
    reg = registry if registry is not None else get_registry()
    entry: Dict[str, Any] = {}
    try:
        in_sh = getattr(compiled, "input_shardings", None)
        if in_sh is not None:
            # (positional, keyword) on modern jax; a flat tuple earlier.
            pos = in_sh[0] if (isinstance(in_sh, tuple) and len(in_sh) == 2
                               and isinstance(in_sh[1], dict)) else in_sh
            entry["in"] = _spec_strings(pos)
        out_sh = getattr(compiled, "output_shardings", None)
        if out_sh is not None:
            if not isinstance(out_sh, (list, tuple)):
                out_sh = (out_sh,)
            entry["out"] = _spec_strings(out_sh)
    except Exception:  # noqa: BLE001 — metadata shape varies by jax version
        pass
    st = _state_for(reg)
    with st.lock:
        st.programs[str(name)] = entry


def mesh_summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """The ``/meshz`` payload: device topology, registered mesh axes,
    compiled-program partition specs, per-device share of parsed
    device time, and the collective fraction.  Degrades to
    topology-only on a CPU backend with nothing registered."""
    reg = registry if registry is not None else get_registry()
    backend = None
    devices: List[dict] = []
    try:
        import jax

        backend = jax.default_backend()
        for d in jax.devices()[:64]:
            devices.append({
                "id": d.id,
                "platform": d.platform,
                "kind": getattr(d, "device_kind", None),
                "process_index": getattr(d, "process_index", None),
            })
    except Exception:  # noqa: BLE001 — no backend is a reportable state, not an error
        pass
    st = _state_for(reg)
    with st.lock:
        mesh = dict(st.mesh) if st.mesh else None
        programs = {k: dict(v) for k, v in st.programs.items()}
        split = dict(st.device_split)
        coll = st.collective_fraction
    return {
        "backend": backend,
        "n_devices": len(devices),
        "devices": devices,
        "mesh": mesh,
        "programs": programs,
        "device_time_split": split,
        "collective_fraction": coll,
    }


# ---------------------------------------------------------------------------
# Snapshots for the live plane / BENCH artifact.
# ---------------------------------------------------------------------------

def summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """Compact device-plane state for live snapshots, ``/statusz`` and
    the fleet view: capture count, top kernel, collective fraction,
    mesh axes, live-buffer total."""
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    with st.lock:
        top = st.kernel_table[0] if st.kernel_table else None
        return {
            "captures_parsed": st.n_captures_parsed,
            "device_ms": round(st.device_ms, 4),
            "collective_fraction": st.collective_fraction,
            "top_kernel": None if top is None else {
                "name": top["name"], "bucket": top["bucket"],
                "ms": top["ms"], "fraction": top["fraction"],
            },
            "mesh": dict(st.mesh) if st.mesh else None,
            "live_buffer_bytes": st.census_bytes,
        }
