"""First-class observability for the assimilation stack (SURVEY.md §5:
the reference has none beyond timestamped DEBUG logging).

Three layers, shared by the engine, the prefetch pipeline, the multi-host
scheduler, the output writers, the CLI drivers and ``bench.py``:

- :mod:`registry` — the thread-safe host-side metrics store (counters /
  gauges / histograms with labels), JSONL event emission and
  Prometheus-style text exposition;
- :mod:`spans` — timed engine phases recorded in BOTH the registry and
  ``jax.profiler`` traces;
- :mod:`device` — the single funnel for packed diagnostic device->host
  reads (zero-extra-transfer guarantee, counted);
- :mod:`health` — the host/device health probes (grown out of bench.py),
  readings sourced from the registry.

See BASELINE.md "Observability" for metric names, label conventions and
the event schema.
"""

from .device import fetch_scalars
from .registry import (
    MetricsRegistry,
    configure,
    get_registry,
    set_registry,
    use,
)
from .spans import span

__all__ = [
    "MetricsRegistry",
    "configure",
    "fetch_scalars",
    "get_registry",
    "set_registry",
    "span",
    "use",
]
