"""First-class observability for the assimilation stack (SURVEY.md §5:
the reference has none beyond timestamped DEBUG logging).

Layers, shared by the engine, the prefetch pipeline, the multi-host
scheduler, the output writers, the CLI drivers and ``bench.py``:

- :mod:`registry` — the thread-safe host-side metrics store (counters /
  gauges / histograms with labels), JSONL event emission and
  Prometheus-style text exposition;
- :mod:`spans` — timed engine phases recorded in the registry,
  ``jax.profiler`` traces AND the trace timeline;
- :mod:`tracing` — the distributed trace timeline: run/chunk/window
  context propagated across threads, completed spans and counter samples
  exported as Perfetto-openable Chrome trace-event JSON (``trace.json``);
- :mod:`flight_recorder` — the crash flight recorder: last events + final
  metrics + thread stacks dumped to ``crash_<ts>.json`` on unhandled
  exception, SIGTERM/SIGINT or an unhealthy probe verdict;
- :mod:`compilemon` — compilation-cache hit/miss counters and
  per-program compile wall time from ``jax.monitoring``;
- :mod:`device` — the single funnel for packed diagnostic device->host
  reads (zero-extra-transfer guarantee, counted) and the per-window
  device-memory watermark gauges;
- :mod:`health` — the host/device health probes (grown out of bench.py),
  readings sourced from the registry;
- :mod:`live` — the fleet plane's write side: a tracked background
  publisher on every process writing a bounded ``live_<host>_<pid>.json``
  heartbeat snapshot atomically into the telemetry dir;
- :mod:`httpd` — the stdlib-only live HTTP endpoint (``/metrics``
  Prometheus text, ``/healthz``, ``/statusz``; port 0 = disabled);
- :mod:`aggregate` — the fleet plane's read side: live snapshots merged
  into one fleet view (counters summed, gauges per-host, histograms
  into fleet p50/p99, stale heartbeats flagged dead) and per-process
  ``trace.json`` fragments stitched into one Chrome trace;
- :mod:`quality` — assimilation-quality observability: the per-window
  innovation-consistency ledger (``quality.jsonl``), filter-consistency
  verdicts, EWMA/CUSUM drift sentinels, and the ``obs.bias`` chaos
  site (BASELINE.md "Assimilation quality");
- :mod:`perf` — performance observability: always-on per-window
  throughput/device-fraction/phase attribution, the live roofline
  utilization gauge (analytic traffic bounds shared with
  ``tools/roofline.py``), and on-demand ``jax.profiler`` capture
  (``/profilez``, ``--profile-windows``; BASELINE.md "Performance
  observability");
- :mod:`devprof` — device-plane observability: XLA kernel-time
  attribution parsed from ``jax.profiler`` captures (ranked kernel
  table, fusion/collective/transfer buckets, device lanes folded into
  the stitched fleet trace), the HBM memory ledger (live-buffer census
  + headroom gauges + OOM flight-recorder forensics), and
  mesh/sharding introspection (``/kernelz``, ``/meshz``,
  ``tools/device_report.py``; BASELINE.md "Device-plane
  observability");
- :mod:`slo` — the SLO engine: declarative objectives over the metric
  vocabulary above, multi-window burn-rate alerting (fast window
  pages, slow window warns), a pending/firing/resolved alert state
  machine with an ``alerts.jsonl`` ledger, and per-objective error
  budgets (``/alertz``, ``tools/slo_report.py``; BASELINE.md "SLOs &
  alerting").

See BASELINE.md "Observability" for metric names, label conventions, the
event schema, and "Tracing & crash forensics" for the trace/crash
artifacts.
"""

from . import devprof, flight_recorder, live, perf, quality, slo, tracing
from .compilemon import install_compile_listeners
from .device import fetch_scalars, record_memory_watermark
from .registry import (
    MetricsRegistry,
    configure,
    get_registry,
    set_registry,
    use,
)
from .spans import span, stopwatch

__all__ = [
    "MetricsRegistry",
    "configure",
    "devprof",
    "fetch_scalars",
    "flight_recorder",
    "get_registry",
    "install_compile_listeners",
    "live",
    "perf",
    "quality",
    "record_memory_watermark",
    "set_registry",
    "slo",
    "span",
    "stopwatch",
    "tracing",
    "use",
]
