"""Compilation observability: cache hits/misses and per-program compile
wall time, sourced from ``jax.monitoring``'s host-side event stream.

A cold per-date assimilation program costs ~10 s of XLA compile on TPU
(``utils.compilation_cache``), and a run that silently recompiles — a new
scan-block K, an operator rebuilt per chunk, a cache directory miss —
loses its roofline without any metric saying why.  JAX already announces
every compile on the host (``monitoring.record_event`` /
``record_event_duration_secs``); this module forwards the relevant ones
into the telemetry registry:

- ``kafka_compile_cache_hits_total`` / ``kafka_compile_cache_misses_total``
  — persistent compilation-cache outcome per program;
- ``kafka_compile_program_seconds`` — wall seconds per backend compile,
  plus a ``compile`` JSONL event and a ``cat: "compile"`` span in the
  trace timeline, so compile stalls show up as visible blocks between the
  phase spans in ``trace.json``.

Listeners resolve :func:`~.registry.get_registry` at event time, so
``configure()``/``use()`` swap the sink as usual.  Installation is
idempotent and degrades to a no-op on a JAX without ``jax.monitoring``.
All of this rides existing host-side code paths: zero device transfers.
"""

from __future__ import annotations

import time

from .registry import get_registry

#: jax.monitoring counter events -> registry counters.
_EVENT_COUNTERS = {
    "/jax/compilation_cache/cache_hits": (
        "kafka_compile_cache_hits_total",
        "persistent compilation-cache hits (program loaded from disk)",
    ),
    "/jax/compilation_cache/cache_misses": (
        "kafka_compile_cache_misses_total",
        "persistent compilation-cache misses (full XLA compile paid)",
    ),
}

#: jax.monitoring duration event for one backend compile.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: compile-wall buckets: spans ~10 ms (tiny CPU programs) .. minutes
#: (large TPU scan programs).
_COMPILE_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                    30.0, 60.0, 180.0)

_installed = False


def _on_event(event: str, **kwargs) -> None:
    hit = _EVENT_COUNTERS.get(event)
    if hit is not None:
        name, help = hit
        get_registry().counter(name, help).inc()


def _on_duration(event: str, duration: float, **kwargs) -> None:
    if event != BACKEND_COMPILE_EVENT:
        return
    reg = get_registry()
    reg.histogram(
        "kafka_compile_program_seconds",
        "wall seconds per XLA backend compile",
        buckets=_COMPILE_BUCKETS,
    ).observe(duration)
    fields = {
        k: v for k, v in kwargs.items() if isinstance(v, (str, int, float))
    }
    reg.emit("compile", seconds=round(duration, 3), **fields)
    # The duration arrives at compile END on the compiling thread: a
    # synthesized [now - duration, now] span puts the stall on that
    # thread's track in the timeline.
    t1 = time.perf_counter()
    reg.trace.add_span(
        "xla_compile", t1 - duration, t1, cat="compile", **fields
    )


def install_compile_listeners() -> bool:
    """Register the listeners once per process; returns False (and stays
    a no-op) when ``jax.monitoring`` is unavailable."""
    global _installed
    if _installed:
        return True
    try:
        from jax import monitoring
    except ImportError:
        return False
    try:
        monitoring.register_event_listener(_on_event)
        monitoring.register_event_duration_secs_listener(_on_duration)
    except AttributeError:
        return False
    _installed = True
    return True
