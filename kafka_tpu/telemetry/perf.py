"""Performance observability: always-on attribution, live roofline
utilization, and on-demand profiler capture.

The fleet plane (PR 10) watches processes and the quality ledger (PR 11)
watches the science; this module watches SPEED.  Until now performance
existed only as post-hoc BENCH artifacts compared pairwise — the e2e row
swung 35.7k/72.8k/44.0k px-steps/s across rounds 3-5 with no code change
(bench.py docstring), and the ROADMAP's mesh/ingest acceptance bars
(``e2e_device_fraction >= 0.9``, ``device_mesh_px_s``) could not be
observed on a live run at all.  Three layers close that:

- **Steady-state attribution** (:func:`record_window`): the engine calls
  this once per assimilated window, from the SAME host-side record the
  one packed ``fetch_scalars`` read already built — zero added device
  transfers, ``kafka_engine_device_reads_total == dispatches`` holds
  with attribution active (tier-1-asserted).  Publishes live gauges:
  ``kafka_perf_px_steps_per_s`` (rolling per-window throughput),
  ``kafka_perf_device_fraction`` (rolling device share of wall time,
  the live form of bench.py's ``e2e_device_fraction``), and
  ``kafka_perf_phase_fraction{phase=}`` (busy fractions derived from
  the PR 2/3 span histograms: fetch/advance/solve/dump/write — phases
  on concurrent threads are per-phase busy fractions and may sum past
  1.0 when the pipeline overlaps well; that overlap IS the signal).
- **Live roofline utilization** (:func:`roofline_utilization`): the
  analytic minimum-traffic bounds from ``tools/roofline.py`` live here
  now (the tool imports them back), so every window's device time folds
  into ``kafka_perf_roofline_utilization{component=}`` — the fraction of
  the HBM roof the solve is provably sustaining (a LOWER bound, same
  derivation as the tool; see PAPER.md's 3.80 ms vs ~0.32 ms bound).  A
  degraded run shows up as a utilization drop on a dashboard instead of
  three PRs later in a bench diff.  Only meaningful on a real TPU; the
  gauge still publishes off-TPU (tiny values) so the plumbing is
  testable on CPU.
- **On-demand profiler capture** (:func:`capture` /
  :func:`start_windowed_capture`): programmatic ``jax.profiler`` capture
  into the telemetry dir, serving the ``/profilez?seconds=N`` httpd
  endpoint and the drivers' ``--profile-windows N`` flag.  One capture
  at a time (concurrent requests get :class:`CaptureBusy`); where the
  profiler is unavailable the caller gets :class:`CaptureUnavailable`
  and the endpoint answers a clean 503.  Captured traces join
  compilemon's compile spans and the span annotations in one timeline.

See BASELINE.md "Performance observability" for the gauge table and the
capture recipe.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import threading
import time
import weakref
from typing import Deque, Dict, Optional, Tuple

from .registry import MetricsRegistry, get_registry

# ---------------------------------------------------------------------------
# Device roofs and analytic minimum-traffic bounds.
#
# Single home for the numbers ``tools/roofline.py`` derives utilisation
# from (the tool imports these back): v5e public roofs
# (jax-ml.github.io/scaling-book: 16 GB HBM at 819 GB/s, 197 TFLOP/s
# bf16) and the fusion-perfect byte counts — every live input read once,
# every output written once.  Utilisation derived from these is a true
# LOWER bound on achieved bandwidth; the XLA cost model's per-fusion
# byte accounting is NOT used (it produced impossible >100%-of-roof
# numbers in earlier rounds — see the tool's docstring).
# ---------------------------------------------------------------------------

HBM_GBPS = 819.0
PEAK_TFLOPS_BF16 = 197.0

_F32 = 4  # bytes; the device paths are float32 throughout (kafkalint
#           implicit-f64 enforces it)


def min_traffic_linearize(n_pix: int, p: int, n_bands: int) -> int:
    """Batched value+Jacobian: reads x ``(n, p)``, writes h0 ``(B, n)``
    + jac ``(B, n, p)``."""
    return n_pix * _F32 * (p + n_bands * (1 + p))


def min_traffic_update(n_pix: int, p: int, n_bands: int) -> int:
    """One packed normal-equations update: linearisation + observations
    + states in, solution + packed A out."""
    return n_pix * _F32 * (
        n_bands * (1 + p)          # h0 + jac
        + 3 * n_bands              # y, r_inv, mask (bool rounded up)
        + 2 * p                    # x_lin, x_f
        + p * p                    # p_inv_f (dense as stored)
        + p                        # x out
        + p * p                    # A out
    )


def min_traffic_gn_full(n_pix: int, p: int, n_bands: int) -> int:
    """The WHOLE per-date Gauss-Newton solve, fusion-perfect: inputs
    once, outputs once — iterations live in VMEM/registers in the ideal
    kernel (the bound both ``gn_full`` and ``gn_full_pallas`` are
    measured against in ``tools/roofline.py``)."""
    return n_pix * _F32 * (
        3 * n_bands + 2 * p + p * p   # obs + x_f(+x_lin=x_f) + p_inv_f
        + p + p * p                   # x out + A out
    )


def min_traffic_gn_inkernel(n_pix: int, p: int, n_bands: int) -> int:
    """The in-kernel-linearise generation's re-derived bound: packed
    prior/information triangles instead of dense ``(p, p)`` batches, and
    the diagnostic outputs (fwd, innovations, per-block counters) the
    kernel actually emits are COUNTED (``gn_full``'s bound
    conservatively omits them)."""
    tri = p * (p + 1) // 2
    return n_pix * _F32 * (
        3 * n_bands        # y, r_inv, mask in
        + p                # x_f lane rows in
        + tri              # P_f^-1 packed rows in
        + p + tri          # x out + packed A out
        + 2 * n_bands      # fwd + innovation diagnostics out
        + 2                # per-block iteration/norm rows out
    )


#: solve-generation component -> its analytic bound (the runtime gauge's
#: label values; ``tools/roofline.py`` components carry the same names).
TRAFFIC_BOUNDS = {
    "gn_full": min_traffic_gn_full,
    "gn_full_pallas": min_traffic_gn_full,
    "gn_inkernel": min_traffic_gn_inkernel,
}


def component_for(solver_options: Optional[dict]) -> str:
    """Which solve generation a window ran, from the engine's solver
    options — the ``component=`` label of the utilization gauge."""
    so = solver_options or {}
    if so.get("use_pallas"):
        if so.get("inkernel_linearize", False):
            return "gn_inkernel"
        return "gn_full_pallas"
    return "gn_full"


def roofline_utilization(component: str, n_pix: int, p: int,
                         n_bands: int, device_s: float,
                         ) -> Optional[float]:
    """Fraction of the HBM roof the window's solve provably sustained:
    ``min_traffic / (device_s * roof)``.  None when untimeable."""
    bound = TRAFFIC_BOUNDS.get(component, min_traffic_gn_full)
    if device_s <= 0:
        return None
    return bound(n_pix, p, n_bands) / (device_s * HBM_GBPS * 1e9)


# ---------------------------------------------------------------------------
# Always-on steady-state attribution.
#
# Per-registry rolling state: a deque of (ts, cumulative px-steps,
# cumulative device seconds) samples, one per recorded window.  The
# rolling rate over the deque span smooths per-window jitter without
# hiding a sustained slowdown; a fused block's k records share one
# arrival timestamp, which the cumulative form handles for free.
# ---------------------------------------------------------------------------

#: windows in the rolling attribution window.
ROLL_WINDOW = 32

#: phase -> (histogram metric, label kv) whose cumulative sum feeds the
#: phase-fraction gauge (the PR 2/3 span histograms; ``solve`` comes
#: from the attribution state's own device-seconds accumulator).
PHASE_SOURCES: Dict[str, Tuple[str, Dict[str, str]]] = {
    "fetch": ("kafka_prefetch_read_seconds", {}),
    "advance": ("kafka_engine_phase_seconds", {"phase": "advance"}),
    "dump": ("kafka_engine_phase_seconds", {"phase": "dump"}),
    "write": ("kafka_io_write_seconds", {}),
}


class _PerfState:
    """Rolling attribution state for one registry."""

    def __init__(self):
        self.lock = threading.Lock()
        # (arrival perf_counter ts, cumulative px-steps, cumulative
        # device seconds) — maxlen+1 so a full deque still spans
        # ROLL_WINDOW inter-sample intervals.
        self.samples: Deque[Tuple[float, float, float]] = \
            collections.deque(maxlen=ROLL_WINDOW + 1)
        self.t_origin: Optional[float] = None
        self.px_total = 0.0
        self.device_total = 0.0
        # (n_pad, n_params, n_bands, component) of the last recorded
        # window — the problem dims devprof's measured-vs-analytic
        # roofline cross-check needs.
        self.last_dims: Optional[Tuple[int, int, int, str]] = None


_states: "weakref.WeakKeyDictionary[MetricsRegistry, _PerfState]" = \
    weakref.WeakKeyDictionary()
_states_lock = threading.Lock()


def _state_for(reg: MetricsRegistry) -> _PerfState:
    with _states_lock:
        st = _states.get(reg)
        if st is None:
            st = _states[reg] = _PerfState()
        return st


def _hist_sum(reg: MetricsRegistry, name: str,
              labels: Dict[str, str]) -> float:
    val = reg.value(name, **labels)
    if isinstance(val, dict):
        return float(val.get("sum") or 0.0)
    return 0.0


def record_window(rec: dict, *, n_valid: int, n_pad: int, n_params: int,
                  n_bands: int, solver_options: Optional[dict] = None,
                  registry: Optional[MetricsRegistry] = None) -> None:
    """Fold one assimilated window into the live perf gauges.

    Called by the engine from ``_record_window`` with the record the
    packed diagnostic read already produced — attribution adds ZERO
    device->host transfers.  ``rec["wall_s"]`` is the device-inclusive
    dispatch wall the diagnostics log has always carried (a fused
    block's records each carry ``wall/k``), which is exactly the
    quantity bench.py's ``e2e_device_fraction`` sums — the live gauge
    and the bench row are the same arithmetic.
    """
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    now = time.perf_counter()
    device_s = float(rec.get("wall_s") or 0.0)
    px_steps = float(n_valid)
    with st.lock:
        if st.t_origin is None:
            # The first record's dispatch covered the whole first block:
            # anchor the wall-time origin at its start so the very first
            # device fraction is 1.0, not a division by ~zero.
            st.t_origin = now - max(
                device_s * float(rec.get("fused", 1)), 1e-9
            )
        st.px_total += px_steps
        st.device_total += device_s
        st.samples.append((now, st.px_total, st.device_total))
        t_old, px_old, dev_old = st.samples[0]
        dt = now - t_old
        if dt < 1e-6:
            # Rolling window collapsed to one instant (a fused block's
            # records arrive together): fall back to run-cumulative.
            t_old, px_old, dev_old = st.t_origin, 0.0, 0.0
            dt = max(now - st.t_origin, 1e-9)
        px_rate = (st.px_total - px_old) / dt
        dev_frac = min(1.0, (st.device_total - dev_old) / dt)
        elapsed = max(now - st.t_origin, 1e-9)
        device_total = st.device_total

    reg.gauge(
        "kafka_perf_px_steps_per_s",
        "rolling assimilation throughput (valid pixels x window steps "
        "per wall second) over the last windows — the live form of the "
        "bench e2e row",
    ).set(px_rate)
    reg.gauge(
        "kafka_perf_device_fraction",
        "rolling fraction of wall time spent in device-inclusive solve "
        "dispatch — the live form of bench e2e_device_fraction",
    ).set(dev_frac)

    # Phase busy fractions: cumulative span-histogram seconds over
    # cumulative run wall.  Overlapped phases (prefetch threads, the
    # async writer) legitimately make these sum past 1.0.
    phase_gauge = reg.gauge(
        "kafka_perf_phase_fraction",
        "per-phase busy fraction of run wall time (fetch/advance/solve/"
        "dump/write, from the span histograms; overlapped phases may "
        "sum past 1)",
    )
    for phase, (metric, labels) in PHASE_SOURCES.items():
        phase_gauge.set(_hist_sum(reg, metric, labels) / elapsed,
                        phase=phase)
    phase_gauge.set(device_total / elapsed, phase="solve")

    component = component_for(solver_options)
    with st.lock:
        st.last_dims = (int(n_pad), int(n_params), int(n_bands),
                        component)
    util = roofline_utilization(
        component, n_pad, n_params, n_bands, device_s
    )
    if util is not None:
        reg.gauge(
            "kafka_perf_roofline_utilization",
            "fraction of the HBM roof the latest window's solve "
            "provably sustained (analytic minimum traffic / measured "
            "device time; lower bound — only meaningful on TPU)",
        ).set(util, component=component)

    _tick_windowed_capture(reg)


def last_window_dims(registry: Optional[MetricsRegistry] = None,
                     ) -> Optional[Tuple[int, int, int, str]]:
    """``(n_pad, n_params, n_bands, component)`` of the last recorded
    window, or None before any window landed — the analytic side of
    ``devprof.roofline_crosscheck``."""
    reg = registry if registry is not None else get_registry()
    st = _state_for(reg)
    with st.lock:
        return st.last_dims


def summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """Compact perf state for ``/statusz``, live snapshots and the BENCH
    artifact: the throughput/device-fraction gauges, the per-component
    roofline utilization, and the phase breakdown."""
    reg = registry if registry is not None else get_registry()
    roofline: Dict[str, float] = {}
    phases: Dict[str, float] = {}
    for m in reg.metrics():
        if m.name == "kafka_perf_roofline_utilization":
            for key, val in m._series():
                roofline[dict(key).get("component", "?")] = val
        elif m.name == "kafka_perf_phase_fraction":
            for key, val in m._series():
                phases[dict(key).get("phase", "?")] = round(val, 6)
    return {
        "px_steps_per_s": reg.value("kafka_perf_px_steps_per_s"),
        "device_fraction": reg.value("kafka_perf_device_fraction"),
        "roofline_utilization": roofline,
        "phases": phases,
    }


# ---------------------------------------------------------------------------
# On-demand profiler capture (jax.profiler programmatic API).
# ---------------------------------------------------------------------------

class CaptureUnavailable(RuntimeError):
    """``jax.profiler`` missing or refusing to start — the caller (the
    httpd endpoint) degrades to a clean 503, never a crash."""


class CaptureBusy(RuntimeError):
    """A capture is already running; one at a time by design (two
    concurrent profiler sessions corrupt each other's dumps)."""


#: maximum /profilez capture length — a handler thread is held for the
#: duration, so the knob is bounded.
MAX_CAPTURE_S = 60.0

#: capture dirs kept under the retention root (<telemetry>/profile) —
#: the keep-N bound on /profilez / --profile-windows accumulation, same
#: policy family as the flight recorder's 16-dump cap.  Evictions are
#: counted and evented, never silent.
CAPTURE_KEEP = 8

_capture_lock = threading.Lock()
_windowed = {"remaining": 0, "directory": None}
_windowed_lock = threading.Lock()


def _start_trace(directory: str) -> None:
    try:
        import jax.profiler
    except Exception as exc:  # noqa: BLE001 — any import failure = no profiler
        raise CaptureUnavailable(f"jax.profiler unavailable: {exc!r}")
    os.makedirs(directory, exist_ok=True)
    try:
        jax.profiler.start_trace(directory)
    except Exception as exc:  # noqa: BLE001 — backend-specific refusals all mean "cannot capture here"
        raise CaptureUnavailable(f"profiler refused to start: {exc!r}")
    # Epoch sidecar: the profiler's own timestamps are monotonic ticks
    # with no wall-clock anchor, so record NOW — devprof pins the
    # capture's earliest device event to this epoch when folding device
    # lanes into the stitched fleet trace (aggregate.stitch_traces).
    try:
        with open(os.path.join(directory, "capture_meta.json"),
                  "w") as f:
            json.dump({"epoch_unix_s": time.time(),
                       "pid": os.getpid()}, f)
    except OSError:
        pass  # alignment degrades; the capture itself is the artifact


def _stop_trace() -> None:
    try:
        import jax.profiler

        jax.profiler.stop_trace()
    except Exception:  # a failed stop must not kill the run being observed
        pass


def capture(seconds: float, directory: str,
            registry: Optional[MetricsRegistry] = None) -> dict:
    """Run one bounded profiler capture into ``directory`` and block
    until it finishes.  Raises :class:`CaptureBusy` when another capture
    (including a windowed one) is active, :class:`CaptureUnavailable`
    when the profiler cannot run here.  Returns a summary dict the
    ``/profilez`` endpoint answers with."""
    seconds = max(0.05, min(float(seconds), MAX_CAPTURE_S))
    reg = registry if registry is not None else get_registry()
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a profiler capture is already running")
    t0 = time.perf_counter()
    try:
        _start_trace(directory)
        # Bounded wait, not time.sleep: the run under observation keeps
        # going on its own threads while this handler thread idles.
        threading.Event().wait(seconds)
        _stop_trace()
    finally:
        _capture_lock.release()
    files = sum(len(fs) for _, _, fs in os.walk(directory))
    _captures_total(reg).inc()
    reg.emit(
        "profile_capture", directory=directory, seconds=seconds,
        files=files, wall_s=round(time.perf_counter() - t0, 3),
    )
    _finish_capture(directory, reg)
    return {"directory": directory, "seconds": seconds, "files": files}


def _captures_total(reg: MetricsRegistry):
    """Single registration site (metric-name lint: one owner per name)."""
    return reg.counter(
        "kafka_perf_profile_captures_total",
        "completed on-demand jax.profiler captures (/profilez or "
        "--profile-windows)",
    )


def start_windowed_capture(n_windows: int, directory: str,
                           registry: Optional[MetricsRegistry] = None,
                           ) -> None:
    """Drivers' ``--profile-windows N``: start a capture now and stop it
    automatically after the next ``n_windows`` assimilated windows (the
    attribution path ticks it).  ``stop_windowed_capture`` is the
    end-of-run safety net for short runs."""
    if n_windows <= 0:
        return
    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusy("a profiler capture is already running")
    try:
        _start_trace(directory)
    except CaptureUnavailable:
        _capture_lock.release()
        raise
    with _windowed_lock:
        _windowed["remaining"] = int(n_windows)
        _windowed["directory"] = directory
    reg = registry if registry is not None else get_registry()
    reg.emit("profile_windows_started", directory=directory,
             windows=int(n_windows))


def _tick_windowed_capture(reg: MetricsRegistry) -> None:
    with _windowed_lock:
        if not _windowed["directory"]:
            return
        _windowed["remaining"] -= 1
        if _windowed["remaining"] > 0:
            return
    stop_windowed_capture(registry=reg)


def stop_windowed_capture(registry: Optional[MetricsRegistry] = None,
                          ) -> Optional[dict]:
    """Stop an active windowed capture (idempotent; returns the capture
    summary, or None when no windowed capture was running)."""
    with _windowed_lock:
        directory = _windowed["directory"]
        if not directory:
            return None
        _windowed["directory"] = None
        _windowed["remaining"] = 0
    _stop_trace()
    _capture_lock.release()
    reg = registry if registry is not None else get_registry()
    files = sum(len(fs) for _, _, fs in os.walk(directory))
    _captures_total(reg).inc()
    reg.emit("profile_capture", directory=directory, files=files,
             windowed=True)
    _finish_capture(directory, reg)
    return {"directory": directory, "files": files}


def _finish_capture(directory: str, reg: MetricsRegistry) -> None:
    """Post-capture hooks, both capture paths: parse the fresh capture
    into devprof's kernel table (so /kernelz is live immediately) and
    enforce keep-N retention.  Best-effort — the windowed stop runs in
    the engine's ``finally``, where a telemetry bug must never mask the
    run's own outcome."""
    try:
        from . import devprof

        devprof.ingest_capture(directory, registry=reg)
        prune_captures(_retention_root(directory), registry=reg)
    except Exception as exc:  # noqa: BLE001 — post-capture hygiene, never fatal
        reg.emit("devprof_ingest_failed", directory=directory,
                 error=repr(exc)[:200])


def _retention_root(directory: str) -> str:
    """The keep-N scope for a capture dir.  ``/profilez`` captures land
    in ``<telemetry>/profile/<ts>`` (prune across the sibling
    timestamps); ``--profile-windows`` captures go straight into
    ``<telemetry>/profile`` (prune inside it)."""
    directory = directory.rstrip(os.sep)
    if os.path.basename(directory) == "profile":
        return directory
    return os.path.dirname(directory) or directory


def prune_captures(root: str, keep: Optional[int] = None,
                   registry: Optional[MetricsRegistry] = None) -> int:
    """Keep only the newest ``keep`` profiler capture sessions under
    ``root``, deleting the oldest beyond the cap (plus their emptied
    ancestor dirs and epoch sidecars) — a long-lived daemon answering
    ``/profilez`` must not grow captures without bound.  Every eviction
    increments ``kafka_perf_capture_evictions_total`` and emits a
    ``profile_capture_evicted`` event.  Returns the eviction count."""
    from . import devprof

    reg = registry if registry is not None else get_registry()
    if keep is None:
        keep = CAPTURE_KEEP
    sessions = devprof.find_capture_sessions(root)
    if keep < 0 or len(sessions) <= keep:
        return 0

    def mtime(d: str) -> float:
        try:
            return os.path.getmtime(d)
        except OSError:
            return 0.0

    sessions.sort(key=lambda d: (mtime(d), d))
    evicted = 0
    root_abs = os.path.abspath(root)
    for session in sessions[:len(sessions) - keep]:
        try:
            shutil.rmtree(session)
        except OSError:
            continue
        evicted += 1
        reg.emit("profile_capture_evicted", directory=session,
                 keep=keep)
        # Collapse emptied ancestors (the plugins/profile scaffolding
        # and per-capture roots), stopping at the retention root; an
        # orphaned epoch sidecar goes with its capture.
        parent = os.path.dirname(os.path.abspath(session))
        while parent != root_abs and parent.startswith(root_abs):
            try:
                left = os.listdir(parent)
                if left == ["capture_meta.json"]:
                    os.unlink(os.path.join(parent, "capture_meta.json"))
                    left = []
                if left:
                    break
                os.rmdir(parent)
            except OSError:
                break
            parent = os.path.dirname(parent)
    if evicted:
        reg.counter(
            "kafka_perf_capture_evictions_total",
            "profiler capture sessions evicted by keep-N retention "
            "(oldest first; default keep=8)",
        ).inc(evicted)
    return evicted
