"""Stdlib-only live metrics HTTP endpoint (the fleet plane's pull side).

The live snapshots (``telemetry.live``) cover fleets that share a
filesystem; a Prometheus server, a load balancer's health check or a
human with ``curl`` want HTTP.  This module is that surface with ZERO
new dependencies — ``http.server`` from the stdlib, threaded, bound to
loopback by default:

``/metrics``
    the registry's Prometheus text exposition (v0.0.4) — scrape a live
    run instead of waiting for ``metrics.prom`` at exit.
``/healthz``
    the health verdict, backed by ``telemetry.health.probe_health``:
    by default it reads the LAST probe verdict through the shared
    ``health.latest_verdict`` sampling path (cheap enough for a load
    balancer's 1 Hz check); ``/healthz?probe=1`` runs a fresh probe
    round inline.  200 when healthy or unprobed, 503 when the verdict
    is off-band — or when a PAGE-severity SLO alert is firing
    (``telemetry.slo``; the objective is named in the body), so an
    external load balancer inherits SLO awareness for free.
``/statusz``
    one JSON page of process state: pid/host/uptime, TraceContext run
    id, session/queue facts from the status provider, solver-health
    counters, perf attribution (throughput / device fraction / roofline
    utilization — ``telemetry.perf``), and the crash-dump index (which
    forensics file to read when something already died).
``/profilez?seconds=N``
    on-demand ``jax.profiler`` capture (``telemetry.perf.capture``):
    records N seconds (default 2, capped) of the LIVE run into
    ``<telemetry dir>/profile/`` and answers with the capture summary.
    One capture at a time (409 while busy); 503 with a reason when the
    profiler cannot run here (no telemetry dir, profiler unavailable) —
    never a crash of the run being observed.
``/requestz``
    the serving layer's last-N request view (``telemetry.request_log``):
    in-flight requests with their stage, plus completed ones with
    status / served_from / phase durations — human text by default,
    JSON via ``?json=1``, ``?n=K`` bounds the list.  Served on both
    ``kafka-serve`` and ``kafka-route``.
``/alertz``
    the SLO engine's alert + error-budget view (``telemetry.slo``):
    per-objective status (ok/pending/firing), burn rates over the
    fast/slow windows, budget consumed/remaining and time to
    exhaustion — human text by default, JSON via ``?json=1``.  Present
    on every instrumented process; shows the stable disabled shape
    when no evaluator was started.
``/kernelz``
    the device-plane kernel view (``telemetry.devprof``): the ranked
    XLA kernel table from the newest parsed profiler capture
    (fusion/collective/transfer buckets, % device time), the
    collective-time fraction and the measured-vs-analytic roofline
    cross-check — human text by default, JSON via ``?json=1``, ``?n=K``
    bounds the table.  Answers 200 with ``captures_parsed: 0`` before
    any capture exists — a live probe, never a 404.
``/meshz``
    mesh/sharding introspection (``telemetry.devprof``): backend,
    device topology (id/platform/kind/process), registered mesh axes,
    partition specs of compiled solve programs, per-device
    utilization split and collective fraction — text by default,
    ``?json=1`` for machines.

**Port 0 = disabled** at the CLI layer (:func:`maybe_start`): the
endpoint is opt-in, a batch run should not open sockets.  The class
itself treats port 0 as "any free port" (`.port` reports the bound
one) — the form tests and embedded scrapers use.

Handler threads serve READS of the registry only — no sockets out, no
subprocesses (kafkalint rule 13 enforces this for the telemetry tree).
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlparse

from . import devprof, perf, quality, slo, tracing
from .live import build_snapshot, crash_dump_index
from .registry import MetricsRegistry, get_registry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class TelemetryHTTPd:
    """One process's live metrics endpoint.  ``port=0`` binds any free
    port (read it back from ``.port``); use :func:`maybe_start` for the
    CLI convention where 0 means disabled."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 status_provider: Optional[Callable[[], dict]] = None,
                 registry: Optional[MetricsRegistry] = None,
                 role: str = "engine"):
        self.host = host
        self.status_provider = status_provider
        self.role = role
        self._registry = registry
        self._t0 = time.time()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                outer._handle(self)

            def log_message(self, fmt, *args):
                pass  # the registry counter is the access log

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        # Cross-thread trace propagation (PR 3 convention): capture the
        # constructing thread's context, re-install it on the worker.
        self._ctx = tracing.current_context()
        self._thread = threading.Thread(
            target=self._serve, name="telemetry-httpd", daemon=True,
        )

    # -- lifecycle ------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    def start(self) -> "TelemetryHTTPd":
        self._thread.start()
        self._reg().emit(
            "httpd_started", host=self.host, port=self.port,
            role=self.role,
        )
        return self

    def _serve(self) -> None:
        tracing.set_context(self._ctx)
        tracing.set_lane("telemetry")
        self._server.serve_forever(poll_interval=0.1)

    def close(self) -> None:
        self._server.shutdown()
        self._thread.join(timeout=5.0)
        self._server.server_close()

    # -- request handling ----------------------------------------------

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/") or "/"
        reg = self._reg()
        reg.counter(
            "kafka_httpd_requests_total",
            "live-endpoint requests served, labelled by endpoint",
        ).inc(endpoint=path)
        try:
            if path == "/metrics":
                self._send(req, 200, reg.prom_text(),
                           content_type=PROM_CONTENT_TYPE)
            elif path == "/healthz":
                self._healthz(req, reg, parse_qs(parsed.query))
            elif path == "/statusz":
                self._statusz(req, reg)
            elif path == "/profilez":
                self._profilez(req, reg, parse_qs(parsed.query))
            elif path == "/requestz":
                self._requestz(req, reg, parse_qs(parsed.query))
            elif path == "/alertz":
                self._alertz(req, reg, parse_qs(parsed.query))
            elif path == "/kernelz":
                self._kernelz(req, reg, parse_qs(parsed.query))
            elif path == "/meshz":
                self._meshz(req, reg, parse_qs(parsed.query))
            elif path == "/":
                self._send_json(req, 200, {
                    "endpoints": ["/metrics", "/healthz", "/statusz",
                                  "/profilez", "/requestz", "/alertz",
                                  "/kernelz", "/meshz"],
                })
            else:
                self._send_json(req, 404, {"error": f"no such endpoint "
                                                    f"{path!r}"})
        except BrokenPipeError:
            pass  # client went away mid-response — nothing to answer
        except Exception as exc:  # noqa: BLE001 — a handler bug must 500, not kill the serving thread
            reg.emit("httpd_error", path=path, error=repr(exc)[:200])
            try:
                self._send_json(req, 500, {"error": repr(exc)[:200]})
            except OSError:  # socket already torn down — response lost
                pass

    def _healthz(self, req, reg, query: Dict[str, list]) -> None:
        from .health import latest_verdict

        verdict: Optional[dict] = None
        if query.get("probe", ["0"])[0] in ("1", "true"):
            from .health import probe_health

            verdict = probe_health(retry_wait_s=0.0, registry=reg)
            unhealthy: Optional[float] = float(verdict["unhealthy"])
            last = latest_verdict(reg)
        else:
            # The shared sampling path (health.latest_verdict): the
            # gauges probe_health maintains, no probing here.
            last = latest_verdict(reg)
            unhealthy = last["unhealthy"]
        # SLO integration: a firing PAGE-severity alert flips the
        # verdict to 503 with the objective named, so external load
        # balancers inherit SLO awareness for free.
        slo_firing = slo.firing_pages(reg)
        ok = not unhealthy and not slo_firing
        body = {
            "ok": ok,
            "verdict": (
                "slo_burn" if slo_firing and not unhealthy
                else "unprobed" if unhealthy is None
                else "unhealthy" if unhealthy else "healthy"
            ),
            "probe_host_ms": last["probe_host_ms"],
            "probe_device_ms": last["probe_device_ms"],
            "slo_firing": slo_firing,
        }
        if verdict is not None:
            body["unhealthy_reasons"] = verdict["unhealthy_reasons"]
        self._send_json(req, 200 if ok else 503, body)

    def _run_context(self):
        """The run's TraceContext, best source first: handler threads
        don't inherit contextvars, and the endpoint may be constructed
        before the driver pushes its run id — the live publisher
        (started inside the push) then carries the authoritative one."""
        ctx = tracing.current_context() or self._ctx
        if ctx is None:
            from .live import active_publisher

            pub = active_publisher()
            if pub is not None:
                ctx = pub._ctx
        return ctx

    def _profilez(self, req, reg, query: Dict[str, list]) -> None:
        """On-demand profiler capture into the telemetry dir.  Blocks
        THIS handler thread for the capture length (the server is
        threaded, other endpoints keep answering)."""
        try:
            seconds = float(query.get("seconds", ["2"])[0])
        except ValueError:
            self._send_json(req, 400, {
                "error": "seconds must be a number",
            })
            return
        if not reg.directory:
            self._send_json(req, 503, {
                "error": "no telemetry directory configured — start the "
                         "run with --telemetry-dir to give captures a "
                         "home",
            })
            return
        directory = os.path.join(
            reg.directory, "profile", time.strftime("%Y%m%dT%H%M%S")
        )
        try:
            result = perf.capture(seconds, directory, registry=reg)
        except perf.CaptureBusy as exc:
            self._send_json(req, 409, {"error": str(exc)})
            return
        except perf.CaptureUnavailable as exc:
            self._send_json(req, 503, {"error": str(exc)})
            return
        self._send_json(req, 200, {"ok": True, **result})

    def _requestz(self, req, reg, query: Dict[str, list]) -> None:
        """Last-N in-flight and completed requests (the serving
        layer's per-request view, ``telemetry.request_log``)."""
        from . import request_log

        try:
            n = int(query.get("n", ["32"])[0])
        except ValueError:
            self._send_json(req, 400, {"error": "n must be an integer"})
            return
        payload = request_log.requestz(n, registry=reg)
        if query.get("json", ["0"])[0] in ("1", "true"):
            self._send_json(req, 200, payload)
            return
        lines = [f"{len(payload['inflight'])} in flight, "
                 f"{len(payload['recent'])} recent"]
        for r in payload["inflight"]:
            lines.append(
                f"  INFLIGHT {r.get('request_id')} "
                f"tile={r.get('tile')} stage={r.get('stage')}"
                + (f" replica={r['replica']}" if r.get("replica")
                   else "")
            )
        for r in payload["recent"]:
            phases = r.get("phases") or {}
            worst = max(phases, key=phases.get) if phases else None
            e2e = r.get("e2e_ms")
            lines.append(
                f"  {r.get('request_id')} {r.get('status')}"
                + (f" {r['served_from']}" if r.get("served_from")
                   else "")
                + (f" {e2e:.1f}ms" if isinstance(e2e, (int, float))
                   else "")
                + (f" worst={worst}({phases[worst]:.1f}ms)"
                   if worst else "")
            )
        self._send(req, 200, "\n".join(lines) + "\n")

    def _alertz(self, req, reg, query: Dict[str, list]) -> None:
        """SLO alert + error-budget state (``telemetry.slo``): text by
        default, full summary via ``?json=1``."""
        payload = slo.summary(reg)
        if query.get("json", ["0"])[0] in ("1", "true"):
            self._send_json(req, 200, payload)
            return
        if not payload.get("enabled"):
            self._send(req, 200, "slo engine not running\n")
            return
        firing = payload["firing"]
        lines = [
            f"slo: {len(firing)} alert(s) firing, "
            f"{payload['alerts_fired']} fired / "
            f"{payload['alerts_resolved']} resolved this run "
            f"(windows {payload['fast_window_s']:g}s/"
            f"{payload['slow_window_s']:g}s)"
        ]
        for a in firing:
            lines.append(
                f"  FIRING [{a['severity']}] {a['objective']} "
                f"burn fast={a['burn_fast']} slow={a['burn_slow']}"
            )
        for name, o in payload["objectives"].items():
            b = o["budget"]
            tte = "-" if b.get("tte_s") is None else f"{b['tte_s']:g}s"
            lines.append(
                f"  {name}: {o['status']} target={o['target']:g} "
                f"burn={o['burn_fast'] if o['burn_fast'] is not None else '-'}"
                f"/{o['burn_slow'] if o['burn_slow'] is not None else '-'} "
                f"budget consumed={b['consumed']:g} "
                f"remaining={b['remaining']:g} tte={tte}"
            )
        self._send(req, 200, "\n".join(lines) + "\n")

    def _kernelz(self, req, reg, query: Dict[str, list]) -> None:
        """Ranked XLA kernel table from the newest parsed capture
        (``telemetry.devprof``): text by default, ``?json=1`` for the
        full payload, ``?n=K`` bounds the table.  200 even before any
        capture was parsed — the empty shape IS the answer."""
        try:
            n = int(query.get("n", ["16"])[0])
        except ValueError:
            self._send_json(req, 400, {"error": "n must be an integer"})
            return
        payload = devprof.kernel_summary(reg, n=n)
        if query.get("json", ["0"])[0] in ("1", "true"):
            self._send_json(req, 200, payload)
            return
        cf = payload.get("collective_fraction")
        lines = [
            f"kernels: {payload['captures_parsed']} capture(s) parsed, "
            f"device {payload['device_ms']:.3f}ms"
            + (f", collective {cf:.1%}" if cf is not None else "")
        ]
        if not payload["kernels"]:
            lines.append(
                "  (no capture parsed yet — trigger one via /profilez "
                "or --profile-windows)"
            )
        for k in payload["kernels"]:
            lines.append(
                f"  {k['ms']:10.3f}ms {k['fraction']:6.1%} "
                f"[{k['bucket']:10s}] x{k['count']} {k['name']}"
            )
        rc = payload.get("roofline_crosscheck")
        if rc:
            lines.append(
                f"  roofline: measured {rc['measured_device_ms']:.3f}ms "
                f"vs analytic floor "
                f"{rc['analytic_min_ms_per_window']:.4f}ms/window "
                f"({rc['component']}-bound)"
            )
        self._send(req, 200, "\n".join(lines) + "\n")

    def _meshz(self, req, reg, query: Dict[str, list]) -> None:
        """Mesh/sharding introspection (``telemetry.devprof``): device
        topology, registered mesh axes, compiled-program partition
        specs, per-device time split.  Text by default, ``?json=1``."""
        payload = devprof.mesh_summary(reg)
        if query.get("json", ["0"])[0] in ("1", "true"):
            self._send_json(req, 200, payload)
            return
        mesh = payload.get("mesh")
        lines = [
            f"mesh: backend={payload['backend']} "
            f"n_devices={payload['n_devices']}"
            + (f" axes={mesh['axes']}" if mesh else " (no mesh registered)")
        ]
        for d in payload["devices"]:
            lines.append(
                f"  device {d['id']}: {d['platform']}"
                + (f" {d['kind']}" if d.get("kind") else "")
                + f" process={d['process_index']}"
            )
        for name, prog in (payload.get("programs") or {}).items():
            lines.append(
                f"  program {name}: in={prog.get('in')} "
                f"out={prog.get('out')}"
            )
        split = payload.get("device_time_split") or {}
        for track, frac in sorted(split.items()):
            lines.append(f"  time {track}: {frac:.1%}")
        cf = payload.get("collective_fraction")
        if cf is not None:
            lines.append(f"  collective fraction: {cf:.1%}")
        self._send(req, 200, "\n".join(lines) + "\n")

    def _statusz(self, req, reg) -> None:
        ctx = self._run_context()
        solver = {
            k: v for k, v in reg.flat().items()
            if k.startswith("kafka_solver_")
        }
        status = {}
        if self.status_provider is not None:
            status = dict(self.status_provider() or {})
        snap = build_snapshot(reg, role=self.role)
        self._send_json(req, 200, {
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "role": self.role,
            "uptime_s": round(time.time() - self._t0, 3),
            "run_id": None if ctx is None else ctx.run_id,
            "telemetry_dir": reg.directory,
            "events_buffered": len(reg.events),
            "metric_series": (len(snap["counters"]) + len(snap["gauges"])
                              + len(snap["histograms"])),
            "solver_health": solver,
            # Assimilation-quality verdicts (telemetry.quality): the
            # science-side health next to the process-side one.
            "quality": quality.summary(reg),
            # Performance attribution (telemetry.perf): live throughput,
            # device fraction, phase breakdown, roofline utilization.
            "perf": perf.summary(reg),
            # SLO alert + budget state (telemetry.slo): the /alertz
            # payload inline, so one /statusz read answers "is anything
            # firing" too.
            "slo": slo.summary(reg),
            # Device-plane state (telemetry.devprof): captures parsed,
            # top kernel, mesh facts, live-buffer bytes.
            "devprof": devprof.summary(reg),
            "crash_dumps": crash_dump_index(reg.directory),
            "status": status,
        })

    # -- response plumbing ---------------------------------------------

    @staticmethod
    def _send(req, code: int, body: str,
              content_type: str = "text/plain; charset=utf-8") -> None:
        payload = body.encode("utf-8")
        req.send_response(code)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(payload)))
        req.end_headers()
        req.wfile.write(payload)

    @classmethod
    def _send_json(cls, req, code: int, body: dict) -> None:
        cls._send(req, code, json.dumps(body, default=str, indent=2),
                  content_type="application/json")


def maybe_start(port: Optional[int], **kwargs) -> Optional[TelemetryHTTPd]:
    """The CLI convention: ``--http-port 0`` (the default) means
    DISABLED — a batch run must not open listening sockets unasked.
    Any nonzero port starts the endpoint and returns it."""
    if not port:
        return None
    return TelemetryHTTPd(port=int(port), **kwargs).start()
