"""Live telemetry snapshots: the per-process heartbeat of the fleet plane.

PRs 2-3 made every run observable POST-MORTEM: ``metrics.prom`` /
``metrics.json`` / ``trace.json`` are written at ``registry.dump()``
time, so an operator watching a live fleet (queue workers, the serving
daemon) has nothing to look at until the processes exit — and a
SIGKILLed worker never writes anything at all.  This module closes that
gap with the cheapest possible live surface, in the repo's
coordinator-free idiom (the shared filesystem is the wire, like the
PR 7 lease markers):

- every instrumented process runs one tracked background
  :class:`LivePublisher` thread that atomically writes a bounded
  ``live_<host>_<pid>.json`` snapshot into its telemetry directory
  every ``interval_s`` seconds (unique tmp + ``os.replace`` — a reader
  can never observe a torn snapshot);
- the snapshot carries the flat counters/gauges, histogram bucket state
  (mergeable into fleet quantiles by ``telemetry.aggregate``), the
  latest health verdict, the :class:`~.tracing.TraceContext` run/chunk
  ids, a crash-dump index, and — critically — a heartbeat timestamp:
  a snapshot whose heartbeat goes stale without a ``final`` marker IS
  the dead-host signal ``tools/fleet_status.py`` flags;
- role-specific facts (queue outdir, serve root, worker id) are
  contributed through :func:`update_status` so fleet aggregation can
  discover the queue a worker serves without extra configuration.

The publisher thread must never block the process it observes: no
sockets, no subprocesses, no unbounded waits — kafkalint rule 13
(``blocking-call-in-publisher``) enforces this statically for the
whole ``kafka_tpu/telemetry/`` tree.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from . import devprof, perf, quality, slo, tracing
from .registry import MetricsRegistry, _label_text, get_registry

#: snapshot schema version (bumped on breaking changes; consumers skip
#: snapshots they do not understand instead of crashing the fleet view).
SCHEMA_VERSION = 1

#: default publish cadence; override per process via the environment so
#: one knob reaches every subprocess of a fleet command.
DEFAULT_INTERVAL_S = 2.0
INTERVAL_ENV = "KAFKA_TPU_LIVE_INTERVAL_S"

#: bounded snapshot: at most this many metric series are embedded (the
#: overflow is counted, never silently dropped) — a runaway label
#: cardinality must not turn the heartbeat file into a disk hog.
MAX_SERIES = 512


def snapshot_name(host: Optional[str] = None,
                  pid: Optional[int] = None) -> str:
    return f"live_{host or socket.gethostname()}_{pid or os.getpid()}.json"


def crash_dump_index(directory: Optional[str]) -> List[str]:
    """Sorted ``crash_*.json`` filenames in ``directory`` — the forensics
    pointer a fleet view shows next to a dead host."""
    if not directory:
        return []
    try:
        return sorted(
            n for n in os.listdir(directory)
            if n.startswith("crash_") and n.endswith(".json")
        )
    except OSError:
        return []


# ---------------------------------------------------------------------------
# Role-specific status: processes contribute facts (queue outdir, serve
# root, worker id) that ride every subsequent snapshot.
# ---------------------------------------------------------------------------

_status_lock = threading.Lock()
_status: Dict[str, Any] = {}


def update_status(**fields) -> None:
    """Merge JSON-serialisable facts into this process's snapshots
    (``None`` values are ignored)."""
    with _status_lock:
        _status.update(
            {k: v for k, v in fields.items() if v is not None}
        )


def current_status() -> Dict[str, Any]:
    with _status_lock:
        return dict(_status)


def build_snapshot(registry: Optional[MetricsRegistry] = None,
                   role: str = "engine", seq: int = 0,
                   interval_s: float = DEFAULT_INTERVAL_S,
                   final: bool = False) -> dict:
    """One process snapshot as a dict (the publisher writes it; tests
    and ``/statusz`` read it directly)."""
    reg = registry if registry is not None else get_registry()
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    histograms: Dict[str, dict] = {}
    n_series = truncated = 0
    for m in reg.metrics():
        for key, val in m._series():
            if n_series >= MAX_SERIES:
                truncated += 1
                continue
            n_series += 1
            tag = m.name + _label_text(key)
            if m.kind == "counter":
                counters[tag] = val
            elif m.kind == "gauge":
                gauges[tag] = val
            else:
                histograms[tag] = {
                    "le": list(m.buckets),
                    "buckets": list(val["buckets"]),
                    "sum": round(val["sum"], 6),
                    "count": val["count"],
                }
    ctx = tracing.current_context()
    unhealthy = reg.value("kafka_health_unhealthy")
    return {
        "schema": SCHEMA_VERSION,
        "ts": round(time.time(), 6),
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "role": role,
        "seq": seq,
        "interval_s": interval_s,
        "final": final,
        "run_id": None if ctx is None else ctx.run_id,
        "chunk_id": None if ctx is None else ctx.chunk_id,
        "health": {
            "unhealthy": None if unhealthy is None else bool(unhealthy),
        },
        # Assimilation-quality verdicts (telemetry.quality): the fleet
        # view folds these into per-host quality columns.
        "quality": quality.summary(reg),
        # Performance attribution (telemetry.perf): throughput / device
        # fraction / roofline utilization, per host in the fleet view.
        "perf": perf.summary(reg),
        # SLO alert state (telemetry.slo): aggregate_fleet folds the
        # firing alerts into the deduped fleet alert view.
        "slo": slo.summary(reg),
        # Device-plane state (telemetry.devprof): captures parsed, top
        # kernel, collective fraction, mesh axes, live-buffer bytes —
        # the fleet view's mesh column.
        "devprof": devprof.summary(reg),
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "series_truncated": truncated,
        "crash_dumps": crash_dump_index(reg.directory),
        "status": current_status(),
    }


class LivePublisher:
    """Tracked background thread publishing ``live_<host>_<pid>.json``
    atomically every ``interval_s`` into ``directory``."""

    def __init__(self, directory: str, role: str = "engine",
                 interval_s: Optional[float] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.directory = directory
        self.role = role
        env = os.environ.get(INTERVAL_ENV)
        self.interval_s = float(
            interval_s if interval_s is not None
            else (env if env else DEFAULT_INTERVAL_S)
        )
        self.path = os.path.join(directory, snapshot_name())
        self._registry = registry
        self._lock = threading.Lock()
        self._seq = 0
        self._trace_len = -1
        self._stop = threading.Event()
        # Cross-thread trace propagation (PR 3 convention): capture the
        # constructing thread's context, re-install it on the worker.
        self._ctx = tracing.current_context()
        self._thread = threading.Thread(
            target=self._run, name="live-publisher", daemon=True,
        )

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    def start(self) -> "LivePublisher":
        os.makedirs(self.directory, exist_ok=True)
        self._thread.start()
        return self

    def _run(self) -> None:
        tracing.set_context(self._ctx)
        tracing.set_lane("telemetry")
        self.publish_now()
        while not self._stop.wait(self.interval_s):
            self.publish_now()

    def publish_now(self, final: bool = False) -> Optional[str]:
        """Write one snapshot immediately (also the flight recorder's
        hook: a crash dump refreshes the live file so the fleet view
        points at the forensics without waiting out the interval).
        Returns the snapshot path, or None when the write failed —
        a full disk must degrade the heartbeat, never kill the run."""
        reg = self._reg()
        with self._lock:
            self._seq += 1
            snap = build_snapshot(
                reg, role=self.role, seq=self._seq,
                interval_s=self.interval_s, final=final,
            )
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(snap, f, default=str)
                os.replace(tmp, self.path)
            except (OSError, TypeError) as exc:
                reg.counter(
                    "kafka_live_publish_errors_total",
                    "live snapshot writes that failed (disk full, "
                    "unserialisable status) — the heartbeat degrades, "
                    "the run survives",
                ).inc()
                reg.emit("live_publish_failed", error=repr(exc)[:200])
                try:
                    os.unlink(tmp)
                except OSError:  # tmp never materialised — nothing held
                    pass
                return None
            # Trace persistence rides the heartbeat: re-export the
            # span timeline whenever it grew, so a SIGKILLed process
            # (which never reaches registry.dump()) still leaves its
            # last-beat trace.json behind for per-request stitching —
            # the victim track of a failover forensics session.
            n_trace = len(reg.trace)
            if n_trace != self._trace_len and n_trace and \
                    reg.directory:
                try:
                    reg.trace.export(
                        os.path.join(reg.directory, "trace.json")
                    )
                    self._trace_len = n_trace
                except OSError:
                    pass  # same contract as the snapshot: degrade, never kill
        reg.counter(
            "kafka_live_snapshots_total",
            "live telemetry snapshots published by this process",
        ).inc()
        return self.path

    def stop(self) -> None:
        """Stop the thread and publish one FINAL snapshot (the clean-
        shutdown marker that distinguishes an exited worker from a dead
        one in the fleet view)."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        self.publish_now(final=True)


# ---------------------------------------------------------------------------
# Process-level publisher: one per process, started by the CLI drivers.
# ---------------------------------------------------------------------------

_active: Optional[LivePublisher] = None


def start_publisher(directory: Optional[str] = None, role: str = "engine",
                    interval_s: Optional[float] = None,
                    ) -> Optional[LivePublisher]:
    """Start (or return) the process publisher.  ``directory`` defaults
    to the registry's telemetry directory; with neither configured this
    is a no-op returning None — a run without ``--telemetry-dir`` opted
    out of run artifacts, heartbeats included."""
    global _active
    if _active is not None:
        return _active
    directory = directory or get_registry().directory
    if not directory:
        return None
    _active = LivePublisher(
        directory, role=role, interval_s=interval_s
    ).start()
    return _active


def active_publisher() -> Optional[LivePublisher]:
    return _active


def publish_now(final: bool = False) -> Optional[str]:
    """Best-effort immediate publish through the process publisher
    (no-op when none is running)."""
    p = _active
    return None if p is None else p.publish_now(final=final)


def stop_publisher() -> None:
    """Stop the process publisher, writing the final snapshot."""
    global _active
    if _active is not None:
        _active.stop()
        _active = None
