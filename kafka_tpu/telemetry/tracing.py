"""Distributed trace timeline: one Perfetto-openable ``trace.json`` per run.

PR 2's metrics registry can say *that* a phase was slow
(``kafka_engine_phase_seconds``) but not *why*: the prefetch thread, the
jitted solve, the async GeoTIFF writer and the chunk scheduler overlap on
separate threads, and no single artifact correlated them.  This module is
that artifact's source:

- :class:`TraceContext` — ``run_id`` / ``chunk_id`` / ``window_id`` /
  parent span ids, carried in a ``contextvars.ContextVar``.  Threads do
  NOT inherit context vars, so thread owners (prefetcher, writer, chunk
  worker) capture :func:`current_context` at construction and re-install
  it on their worker threads — the cross-thread propagation the timeline
  needs to stitch one run together.  ``KAFKA_TPU_RUN_ID`` carries the
  run id into chunk-worker subprocesses.
- :class:`TraceBuffer` — a bounded, thread-safe store of completed spans
  and counter samples.  One buffer lives on every
  :class:`~.registry.MetricsRegistry` (``registry.trace``), so tracing
  follows the registry's configure/use lifecycle and tests isolate it the
  same way.
- Chrome trace-event export (:meth:`TraceBuffer.export`): ``ph: "X"``
  complete spans on one named pid/tid track per thread lane (engine /
  prefetch / writer / scheduler), ``ph: "C"`` counter tracks (queue
  depth, writer backlog, device-memory watermarks), ``ph: "M"`` metadata
  naming the tracks.  Open the file at https://ui.perfetto.dev or
  ``chrome://tracing``.

This timeline complements — does not replace — the ``jax.profiler``
TraceAnnotations the same spans already emit (``utils.profiling``): the
profiler trace shows device internals when you capture one; ``trace.json``
is always on once a telemetry directory is configured, and cheap enough
to leave on in production.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import itertools
import json
import os
import threading
import time
import uuid
from typing import Any, Dict, Iterator, List, Optional

#: process-wide monotonically increasing span ids (unique within a run's
#: process; the crash dump and span args carry them for parentage).
_SPAN_IDS = itertools.count(1)


def new_run_id() -> str:
    """A fresh run id, or the one handed down by a parent process
    (``KAFKA_TPU_RUN_ID`` — how chunk-worker subprocesses join their
    scheduler's trace)."""
    return os.environ.get("KAFKA_TPU_RUN_ID") or uuid.uuid4().hex[:12]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """Correlation ids attached to every span/event recorded under it.

    ``request_id`` is the serving layer's per-request trace key (ISSUE
    14): minted once at admission (``serve.request.new_request_id``, the
    sanctioned origin) and propagated on the filesystem wire — request
    payloads, journal entries, response bodies — so every span the
    router, the replica and the engine record for one request stitches
    into one cross-process waterfall (``aggregate.stitch_traces``).
    """

    run_id: str
    chunk_id: Optional[str] = None
    window_id: Optional[int] = None
    request_id: Optional[str] = None
    parent_span: Optional[int] = None

    def fields(self) -> Dict[str, Any]:
        """Non-empty id fields, for span args / crash dumps."""
        return {
            k: v for k, v in dataclasses.asdict(self).items()
            if v is not None
        }


_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "kafka_trace_ctx", default=None
)


def current_context() -> Optional[TraceContext]:
    return _CTX.get()


def set_context(ctx: Optional[TraceContext]) -> None:
    """Install ``ctx`` for the CURRENT thread — the re-install half of
    cross-thread propagation (threads start with an empty context)."""
    _CTX.set(ctx)


@contextlib.contextmanager
def push(**fields) -> Iterator[TraceContext]:
    """Enter a child context with ``fields`` overridden (``chunk_id=...``,
    ``window_id=...``).  With no context active, starts a new one (fresh
    ``run_id`` unless given)."""
    fields = {k: v for k, v in fields.items() if v is not None}
    base = _CTX.get()
    if base is None:
        base = TraceContext(run_id=fields.pop("run_id", None) or new_run_id())
    ctx = dataclasses.replace(base, **fields)
    token = _CTX.set(ctx)
    try:
        yield ctx
    finally:
        _CTX.reset(token)


# ---------------------------------------------------------------------------
# Thread lanes: the named tracks of the timeline.
# ---------------------------------------------------------------------------

def next_span_id() -> int:
    return next(_SPAN_IDS)


def push_parent(span_id: int):
    """Mark ``span_id`` as the parent of spans opened until :func:`pop`.
    Returns a reset token (None when no context is active)."""
    base = _CTX.get()
    if base is None:
        return None
    return _CTX.set(dataclasses.replace(base, parent_span=span_id))


def pop(token) -> None:
    if token is not None:
        _CTX.reset(token)


# ---------------------------------------------------------------------------
# Thread lanes: the named tracks of the timeline.
# ---------------------------------------------------------------------------

_LANE = threading.local()


def set_lane(name: str) -> None:
    """Name the current thread's track (``prefetch``, ``writer``, ...).
    Unnamed threads fall back to ``engine`` for the main thread and the
    thread's own name otherwise."""
    _LANE.name = name


def _current_lane() -> str:
    name = getattr(_LANE, "name", None)
    if name:
        return name
    t = threading.current_thread()
    return "engine" if t is threading.main_thread() else t.name


class TraceBuffer:
    """Bounded, thread-safe span/counter store with Chrome export.

    Timestamps are ``time.perf_counter()`` anchored at buffer creation
    (monotonic — wall-clock steps cannot fold the timeline); the anchor's
    wall time is exported in ``otherData.epoch_unix_s`` so consumers can
    line the trace up with ``events.jsonl``.
    """

    def __init__(self, max_events: int = 65536):
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self.epoch = time.time()
        self._spans: collections.deque = collections.deque(maxlen=max_events)
        self._counters: collections.deque = collections.deque(
            maxlen=max_events
        )
        #: lane name -> tid (assigned in first-seen order; engine first
        #: so the run's driving thread sorts to the top in Perfetto).
        self._lanes: Dict[str, int] = {}

    def _us(self, t: float) -> float:
        return round((t - self._t0) * 1e6, 1)

    def _tid(self, lane: str) -> int:
        tid = self._lanes.get(lane)
        if tid is None:
            tid = self._lanes[lane] = len(self._lanes) + 1
        return tid

    def add_span(self, name: str, t_start: float, t_end: float,
                 lane: Optional[str] = None, cat: str = "span",
                 span_id: Optional[int] = None, **args) -> int:
        """Record one completed span (``t_start``/``t_end`` are
        ``time.perf_counter()`` readings).  The active :class:`TraceContext`
        ids land in the span args automatically."""
        ctx = current_context()
        if span_id is None:
            span_id = next(_SPAN_IDS)
        if ctx is not None:
            args = {**ctx.fields(), **args}
        rec = {
            "name": name, "cat": cat,
            "ts": self._us(t_start),
            "dur": max(0.0, round((t_end - t_start) * 1e6, 1)),
            "lane": lane or _current_lane(),
            "span_id": span_id,
            "args": args,
        }
        with self._lock:
            rec["tid"] = self._tid(rec["lane"])
            self._spans.append(rec)
        return span_id

    def add_counter(self, name: str, value: float) -> None:
        """Record one counter sample (queue depth, backlog, memory
        watermark) — a ``ph: "C"`` track in the exported timeline."""
        with self._lock:
            self._counters.append(
                {"name": name, "ts": self._us(time.perf_counter()),
                 "value": float(value)}
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans) + len(self._counters)

    # -- export ---------------------------------------------------------

    def to_chrome(self) -> dict:
        """The full artifact as a Chrome trace-event JSON object."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._spans)
            counters = list(self._counters)
            lanes = dict(self._lanes)
        events: List[dict] = [{
            "name": "process_name", "ph": "M", "ts": 0.0,
            "pid": pid, "tid": 0, "args": {"name": "kafka_tpu"},
        }]
        for lane, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
            events.append({
                "name": "thread_name", "ph": "M", "ts": 0.0,
                "pid": pid, "tid": tid, "args": {"name": lane},
            })
            events.append({
                "name": "thread_sort_index", "ph": "M", "ts": 0.0,
                "pid": pid, "tid": tid, "args": {"sort_index": tid},
            })
        for s in spans:
            events.append({
                "name": s["name"], "cat": s["cat"], "ph": "X",
                "ts": s["ts"], "dur": s["dur"],
                "pid": pid, "tid": s["tid"],
                "args": {**s["args"], "span_id": s["span_id"]},
            })
        for c in counters:
            events.append({
                "name": c["name"], "ph": "C", "ts": c["ts"],
                "pid": pid, "tid": 0, "args": {"value": c["value"]},
            })
        run_ids = sorted({
            s["args"].get("run_id") for s in spans
            if s["args"].get("run_id")
        })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix_s": round(self.epoch, 6),
                "run_ids": run_ids,
            },
        }

    def export(self, path: str) -> str:
        """Write the Perfetto-openable ``trace.json`` atomically (unique
        tmp + ``os.replace``): the live publisher re-exports it every
        heartbeat so a SIGKILLed process leaves its last-beat trace
        behind, and a stitching reader must never see a torn file."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome(), f, default=str)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# Default-registry conveniences: instrumented code records through these so
# the active registry's buffer (swapped by configure()/use()) is the sink.
# ---------------------------------------------------------------------------

def _buffer() -> TraceBuffer:
    from .registry import get_registry

    return get_registry().trace


@contextlib.contextmanager
def trace_span(name: str, lane: Optional[str] = None, cat: str = "span",
               **args) -> Iterator[None]:
    """Time the enclosed block as one span in the default registry's
    buffer; nested ``trace_span``s see this span as their
    ``parent_span``."""
    span_id = next_span_id()
    token = push_parent(span_id)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        t1 = time.perf_counter()
        pop(token)
        _buffer().add_span(
            name, t0, t1, lane=lane, cat=cat, span_id=span_id, **args
        )


def counter(name: str, value: float) -> None:
    """Record one counter sample into the default registry's buffer."""
    _buffer().add_counter(name, value)
