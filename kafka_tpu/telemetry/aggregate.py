"""Fleet aggregation: many per-process telemetry surfaces, one view.

The live plane's write side is per-process by design (``telemetry.live``
snapshots, per-process ``trace.json`` fragments, the PR 7 lease queue's
marker files).  This module is the read side — pure functions, no
daemon, rendered by ``tools/fleet_status.py``:

- :func:`load_live_snapshots` / :func:`aggregate_fleet` — merge every
  ``live_<host>_<pid>.json`` under a telemetry root into one fleet
  view: counters SUMMED across processes (with the per-worker breakdown
  kept for forensics), gauges PER-HOST (summing a queue-depth gauge
  across hosts would be a lie), histograms merged bucket-wise into
  fleet p50/p99, and hosts whose heartbeat went stale without a
  ``final`` marker flagged DEAD;
- :func:`worker_liveness` — the (host:pid -> liveness) join
  ``tools/queue_status.py`` uses to print heartbeat age next to lease
  ownership;
- :func:`stitch_traces` — merge per-process Chrome-trace fragments for
  one ``run_id`` into a single timeline: each source file becomes its
  own pid track (named after its telemetry subdirectory), timestamps
  are aligned on the shared wall-clock epoch every ``TraceBuffer``
  exports (``otherData.epoch_unix_s``), so the scheduler's reclaim and
  the victim's last span line up in one Perfetto window; profiler
  capture dirs under the same root contribute DEVICE lanes
  (``telemetry.devprof`` — XLA kernel spans aligned on the
  ``capture_meta.json`` epoch sidecar) beside the host phase spans;
- :func:`parse_prom_text` — the mini Prometheus text-format parser the
  exposition round-trip test and the loadgen mid-run scraper use.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Prometheus text-format parsing (v0.0.4, the subset the registry emits).
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)\s*$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(value: str) -> str:
    return (value.replace("\\n", "\n")
                 .replace('\\"', '"')
                 .replace("\\\\", "\\"))


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def parse_prom_text(text: str) -> Dict[str, dict]:
    """Parse a text exposition into ``{name: {"type", "help",
    "samples": [{"labels": {...}, "value": float}]}}``.

    Histogram/summary child series (``_bucket``/``_sum``/``_count``)
    appear under their own sample names, exactly as scraped — the
    round-trip test reassembles them.  Raises ``ValueError`` on a line
    that is neither a comment nor a well-formed sample, which is the
    point: the parser doubles as the conformance check.
    """
    out: Dict[str, dict] = {}

    def family(name: str) -> dict:
        return out.setdefault(
            name, {"type": None, "help": None, "samples": []}
        )

    for lineno, line in enumerate(text.splitlines(), start=1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                fam = family(parts[2])
                if parts[1] == "TYPE":
                    fam["type"] = parts[3] if len(parts) > 3 else None
                else:
                    fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(
                f"line {lineno} is not valid Prometheus text "
                f"exposition: {line!r}"
            )
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL_RE.finditer(raw):
                labels[lm.group(1)] = _unescape_label(lm.group(2))
                consumed = lm.end()
            rest = raw[consumed:].strip().strip(",")
            if rest:
                raise ValueError(
                    f"line {lineno} has malformed labels: {raw!r}"
                )
        family(m.group("name"))["samples"].append(
            {"labels": labels, "value": _parse_value(m.group("value"))}
        )
    return out


# ---------------------------------------------------------------------------
# Live-snapshot loading and fleet aggregation.
# ---------------------------------------------------------------------------

_LIVE_RE = re.compile(r"^live_.+_\d+\.json$")


def load_live_snapshots(root: str) -> List[dict]:
    """Every parseable ``live_*.json`` under ``root`` (recursive).  Each
    snapshot gains ``_path``/``_rel`` so the fleet view can point back
    at its source; unreadable files are skipped — a torn write (there
    should be none: writes are atomic) must not kill the fleet view."""
    snaps: List[dict] = []
    if not os.path.isdir(root):
        return snaps
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        for fn in sorted(filenames):
            if not _LIVE_RE.match(fn):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                continue
            if not isinstance(snap, dict) or "ts" not in snap:
                continue
            snap["_path"] = path
            snap["_rel"] = os.path.relpath(path, root).replace(
                os.sep, "/"
            )
            snaps.append(snap)
    return snaps


def _worker_key(snap: dict) -> str:
    return f"{snap.get('host', '?')}:{snap.get('pid', '?')}"


def _dedupe_newest(snapshots: List[dict]) -> List[dict]:
    newest: Dict[str, dict] = {}
    for snap in snapshots:
        key = _worker_key(snap)
        if key not in newest or snap.get("ts", 0) > \
                newest[key].get("ts", 0):
            newest[key] = snap
    return [newest[k] for k in sorted(newest)]


def _is_dead(snap: dict, now: float, ttl_s: Optional[float]) -> bool:
    """Stale heartbeat + no final marker = presumed dead.  The TTL
    defaults to 3x the snapshot's own publish interval (miss three
    beats, same policy as the lease heartbeat)."""
    if snap.get("final"):
        return False
    ttl = ttl_s if ttl_s is not None else \
        3.0 * float(snap.get("interval_s") or 2.0)
    return (now - float(snap.get("ts", 0))) > ttl


def quantile_from_buckets(le: List[float], cumulative: List[int],
                          count: int, q: float) -> Optional[float]:
    """``histogram_quantile``-style linear interpolation over cumulative
    buckets.  Observations beyond the last finite bucket resolve to that
    bucket's bound (the standard Prometheus convention)."""
    if count <= 0 or not le:
        return None
    rank = q * count
    prev_le, prev_cum = 0.0, 0
    for bound, cum in zip(le, cumulative):
        if cum >= rank:
            if cum == prev_cum:
                return bound
            return prev_le + (bound - prev_le) * \
                (rank - prev_cum) / (cum - prev_cum)
        prev_le, prev_cum = bound, cum
    return le[-1]


def aggregate_fleet(snapshots: List[dict], now: Optional[float] = None,
                    ttl_s: Optional[float] = None) -> dict:
    """Merge per-process live snapshots into the fleet view (see module
    docstring for the counter/gauge/histogram semantics)."""
    now = time.time() if now is None else now
    snaps = _dedupe_newest(snapshots)
    workers: List[dict] = []
    counters: Dict[str, float] = {}
    counters_by_worker: Dict[str, Dict[str, float]] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    hist_acc: Dict[str, dict] = {}
    crash_dumps: List[dict] = []
    run_ids = set()
    for snap in snaps:
        key = _worker_key(snap)
        age = now - float(snap.get("ts", 0))
        dead = _is_dead(snap, now, ttl_s)
        if snap.get("run_id"):
            run_ids.add(snap["run_id"])
        for name in snap.get("crash_dumps") or ():
            crash_dumps.append({"worker": key, "file": name})
        workers.append({
            "key": key,
            "host": snap.get("host"),
            "pid": snap.get("pid"),
            "role": snap.get("role"),
            "run_id": snap.get("run_id"),
            "age_s": round(age, 3),
            "final": bool(snap.get("final")),
            "dead": dead,
            "unhealthy": (snap.get("health") or {}).get("unhealthy"),
            # Per-host assimilation-quality summary (telemetry.quality;
            # absent on pre-quality snapshots).
            "quality": snap.get("quality"),
            # Per-host performance attribution (telemetry.perf; absent
            # on pre-perf snapshots).
            "perf": snap.get("perf"),
            # Per-host SLO alert state (telemetry.slo; absent on
            # pre-SLO snapshots).
            "slo": snap.get("slo"),
            # Per-host device-plane state (telemetry.devprof; absent
            # on pre-devprof snapshots).
            "devprof": snap.get("devprof"),
            "crash_dumps": list(snap.get("crash_dumps") or ()),
            "status": snap.get("status") or {},
            "path": snap.get("_rel") or snap.get("_path"),
        })
        for tag, val in (snap.get("counters") or {}).items():
            counters[tag] = counters.get(tag, 0) + val
            counters_by_worker.setdefault(tag, {})[key] = val
        for tag, val in (snap.get("gauges") or {}).items():
            gauges.setdefault(tag, {})[key] = val
        for tag, h in (snap.get("histograms") or {}).items():
            acc = hist_acc.get(tag)
            le = list(h.get("le") or ())
            if acc is None:
                hist_acc[tag] = {
                    "le": le,
                    "buckets": list(h.get("buckets") or ()),
                    "sum": float(h.get("sum") or 0.0),
                    "count": int(h.get("count") or 0),
                    "mergeable": True,
                }
            else:
                acc["sum"] += float(h.get("sum") or 0.0)
                acc["count"] += int(h.get("count") or 0)
                if acc["le"] == le and le:
                    acc["buckets"] = [
                        a + b for a, b in
                        zip(acc["buckets"], h.get("buckets") or ())
                    ]
                else:
                    # Bucket layouts disagree (different registry
                    # configs): count/sum still merge, quantiles don't.
                    acc["mergeable"] = False
    histograms: Dict[str, dict] = {}
    for tag, acc in hist_acc.items():
        entry = {
            "count": acc["count"],
            "sum": round(acc["sum"], 6),
            "p50": None,
            "p99": None,
        }
        if acc["mergeable"] and acc["count"]:
            for q, field in ((0.5, "p50"), (0.99, "p99")):
                v = quantile_from_buckets(
                    acc["le"], acc["buckets"], acc["count"], q
                )
                entry[field] = None if v is None else round(v, 6)
        histograms[tag] = entry
    # Fleet quality roll-up: which hosts' drift sentinels are alarming
    # and the distribution of last verdicts — the science-side health
    # column of the fleet view (dead_hosts is the process-side one).
    verdict_counts: Dict[str, int] = {}
    drifting_workers = []
    for w in workers:
        q = w.get("quality") or {}
        if q.get("drift_active"):
            drifting_workers.append(w["key"])
        v = q.get("last_verdict")
        if v:
            verdict_counts[v] = verdict_counts.get(v, 0) + 1
    # Fleet SLO roll-up: an objective firing on ANY worker fires
    # fleet-wide, deduped to one (objective, severity) entry carrying
    # the workers it fires on — the fleet alert line fleet_status
    # renders above the per-worker rows.
    slo_firing: Dict[Tuple[str, str], List[str]] = {}
    n_alerts_fired = 0
    for w in workers:
        s = w.get("slo") or {}
        n_alerts_fired += int(s.get("alerts_fired") or 0)
        for a in s.get("firing") or ():
            key = (str(a.get("objective")), str(a.get("severity")))
            slo_firing.setdefault(key, []).append(w["key"])
    return {
        "generated_ts": round(now, 6),
        "n_workers": len(workers),
        "workers": workers,
        "dead_hosts": sorted(w["key"] for w in workers if w["dead"]),
        "run_ids": sorted(run_ids),
        "counters": counters,
        "counters_by_worker": counters_by_worker,
        "gauges": gauges,
        "histograms": histograms,
        "crash_dumps": crash_dumps,
        "quality": {
            "drifting_workers": sorted(drifting_workers),
            "last_verdicts": verdict_counts,
        },
        "slo": {
            "firing": [
                {
                    "objective": obj, "severity": sev,
                    "workers": sorted(wkeys),
                }
                for (obj, sev), wkeys in sorted(slo_firing.items())
            ],
            "alerts_fired": n_alerts_fired,
        },
    }


def worker_liveness(snapshots: List[dict], now: Optional[float] = None,
                    ttl_s: Optional[float] = None) -> Dict[str, dict]:
    """``host:pid -> {age_s, dead, final, role, path}`` — the join key
    is exactly the queue's default worker id, so lease ownership lines
    match up with heartbeats for free."""
    now = time.time() if now is None else now
    out: Dict[str, dict] = {}
    for snap in _dedupe_newest(snapshots):
        out[_worker_key(snap)] = {
            "age_s": round(now - float(snap.get("ts", 0)), 3),
            "dead": _is_dead(snap, now, ttl_s),
            "final": bool(snap.get("final")),
            "role": snap.get("role"),
            "path": snap.get("_rel") or snap.get("_path"),
        }
    return out


def discover_queue_outdir(snapshots: List[dict]) -> Optional[str]:
    """The queue outdir the fleet serves, read from worker status
    contributions (``shard.queue.run_queue`` publishes it) — so
    ``fleet_status`` needs no ``--queue-dir`` when snapshots carry it."""
    for snap in _dedupe_newest(snapshots):
        outdir = (snap.get("status") or {}).get("queue_outdir")
        if outdir:
            return outdir
    return None


# ---------------------------------------------------------------------------
# Trace stitching: per-process fragments -> one Chrome trace.
# ---------------------------------------------------------------------------

def find_trace_files(root: str) -> List[str]:
    """Every ``trace.json`` under ``root`` (recursive, sorted)."""
    found: List[str] = []
    if not os.path.isdir(root):
        return found
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames.sort()
        if "trace.json" in filenames:
            found.append(os.path.join(dirpath, "trace.json"))
    return sorted(found)


def _trace_matches(doc: dict, run_id: Optional[str]) -> bool:
    if run_id is None:
        return True
    other = doc.get("otherData") or {}
    if run_id in (other.get("run_ids") or ()):
        return True
    return any(
        (e.get("args") or {}).get("run_id") == run_id
        for e in doc.get("traceEvents") or ()
    )


def _request_events(doc: dict, request_id: str) -> Optional[dict]:
    """``doc`` filtered to one request's events (metadata kept so the
    track names survive); None when the fragment never saw the
    request — that process plays no part in this waterfall."""
    matched = [
        e for e in doc.get("traceEvents") or ()
        if (e.get("args") or {}).get("request_id") == request_id
    ]
    if not matched:
        return None
    meta = [e for e in doc.get("traceEvents") or ()
            if e.get("ph") == "M"]
    return {**doc, "traceEvents": meta + matched}


def request_flow_events(events: List[dict]) -> List[dict]:
    """Chrome flow events (``ph: "s"``/``"f"``) threading one request's
    spans across process boundaries: every time the request's timeline
    hops pids (router -> replica -> router), an arrow binds the last
    span on the old track to the first span on the new one — the
    forward/relay hops read as one path in Perfetto, not three
    disconnected tracks."""
    spans = sorted(
        (e for e in events if e.get("ph") == "X"),
        key=lambda e: (e.get("ts", 0), -(e.get("dur") or 0)),
    )
    flows: List[dict] = []
    flow_id = 1
    for prev, nxt in zip(spans, spans[1:]):
        if prev.get("pid") == nxt.get("pid"):
            continue
        base = {"name": "request", "cat": "flow", "id": flow_id}
        flows.append({
            **base, "ph": "s",
            "ts": round(prev.get("ts", 0) + (prev.get("dur") or 0), 1),
            "pid": prev.get("pid"), "tid": prev.get("tid"),
        })
        flows.append({
            **base, "ph": "f", "bp": "e",
            "ts": nxt.get("ts", 0),
            "pid": nxt.get("pid"), "tid": nxt.get("tid"),
        })
        flow_id += 1
    return flows


def stitch_traces(root: str, run_id: Optional[str] = None,
                  request_id: Optional[str] = None) -> dict:
    """Merge every per-process ``trace.json`` under ``root`` (optionally
    only fragments carrying ``run_id``) into ONE Chrome trace document.

    Each source fragment gets its own remapped pid track named after its
    telemetry subdirectory, and its timestamps are shifted onto the
    shared wall-clock axis via the ``epoch_unix_s`` anchor every
    ``TraceBuffer`` exports — cross-process ordering (claim, crash,
    reclaim) is real, not per-process-relative.

    ``request_id`` stitches ONE request's waterfall instead (ISSUE 14):
    only spans carrying that id survive (plus track metadata), only
    processes that touched the request contribute a track, and flow
    events thread the forward/relay hops across the pid boundaries.
    """
    sources: List[Tuple[str, dict]] = []
    for path in find_trace_files(root):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict) or "traceEvents" not in doc:
            continue
        if not _trace_matches(doc, run_id):
            continue
        if request_id is not None:
            filtered = _request_events(doc, request_id)
            if filtered is None:
                continue
            doc = filtered
        sources.append((path, doc))
    epoch0 = min(
        (float((doc.get("otherData") or {}).get("epoch_unix_s") or 0)
         for _, doc in sources),
        default=0.0,
    )
    events: List[dict] = []
    out_sources: List[dict] = []
    run_ids = set()
    for idx, (path, doc) in enumerate(sources):
        pid = idx + 1
        other = doc.get("otherData") or {}
        epoch = float(other.get("epoch_unix_s") or 0)
        shift_us = (epoch - epoch0) * 1e6
        rel_dir = os.path.relpath(os.path.dirname(path), root).replace(
            os.sep, "/"
        )
        label = rel_dir if rel_dir != "." else os.path.basename(root)
        run_ids.update(other.get("run_ids") or ())
        named = False
        for e in doc.get("traceEvents") or ():
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") == "M" and e.get("name") == "process_name":
                e = {**e, "args": {"name": f"kafka_tpu {label}"}}
                named = True
            elif isinstance(e.get("ts"), (int, float)):
                e["ts"] = round(e["ts"] + shift_us, 1)
            events.append(e)
        if not named:
            events.append({
                "name": "process_name", "ph": "M", "ts": 0.0,
                "pid": pid, "tid": 0,
                "args": {"name": f"kafka_tpu {label}"},
            })
        out_sources.append({
            "pid": pid,
            "path": os.path.relpath(path, root).replace(os.sep, "/"),
            "epoch_unix_s": epoch,
        })
    if request_id is not None:
        events.extend(request_flow_events(events))
    else:
        # Device lanes (telemetry.devprof): every profiler capture
        # session under the root joins as its own pid track, XLA kernel
        # spans aligned on the capture_meta.json epoch sidecar — the
        # host phase spans and the kernels they dispatched share one
        # Perfetto window.  Request waterfalls skip this: kernels carry
        # no request_id.  Late import keeps aggregate importable
        # standalone (it has no other kafka_tpu dependencies).
        from . import devprof

        dev_events, dev_sources = devprof.device_lane_tracks(
            root, epoch0, first_pid=len(sources) + 1
        )
        events.extend(dev_events)
        out_sources.extend(dev_sources)
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "stitched": True,
            "run_id_filter": run_id,
            "request_id_filter": request_id,
            "run_ids": sorted(run_ids),
            "sources": out_sources,
        },
    }
