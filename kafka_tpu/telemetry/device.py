"""Device-side diagnostics, host-side accounting.

The engine's convergence scalars (GN iterations, innovation chi^2 per
band, bounds-clip counts, nodata counts) are computed ON DEVICE inside the
solve/scan programs (``core.solvers``) and travel to the host as ONE
packed vector per window — the same single device->host round-trip the
diagnostics log always paid (~0.2 s of latency each on a tunneled chip),
now carrying four more scalars instead of costing extra syncs.

``fetch_scalars`` is the one funnel for those packed reads: every call
increments ``kafka_engine_device_reads_total``, which is how the test
suite PROVES telemetry adds zero device->host transfers per window (the
counter equals the number of solve dispatches whether or not a telemetry
directory is configured).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .registry import MetricsRegistry, get_registry


def fetch_scalars(packed) -> np.ndarray:
    """Materialise one packed device vector of diagnostic scalars.

    The ONLY sanctioned device->host read for engine diagnostics: callers
    concatenate every scalar they need into ``packed`` first, so the
    counter below is an exact census of diagnostic round-trips.
    """
    get_registry().counter(
        "kafka_engine_device_reads_total",
        "packed diagnostic device->host reads (one per solve dispatch)",
    ).inc()
    return np.asarray(packed)


def record_memory_watermark(
    registry: Optional[MetricsRegistry] = None,
) -> None:
    """Per-device HBM gauges from ``Device.memory_stats()`` — a HOST-side
    PJRT query, so this rides the engine's per-window host code with zero
    device->host transfers (the zero-extra-transfer invariant above is
    untouched).  Degrades to a no-op where the backend reports nothing
    (CPU returns None).  Each reading also lands as a trace counter
    track, so HBM pressure lines up with the phase spans in
    ``trace.json``.

    The watermark doubles as the per-window MEMORY LEDGER tick: besides
    the in-use/peak gauges it publishes the per-device headroom
    (``bytes_limit - bytes_in_use`` — the distance to an OOM) and
    refreshes the devprof buffer census (``jax.live_arrays()`` grouped
    by shape/dtype/sharding; host-side array metadata, still zero
    transfers), so an OOM's flight-recorder forensics can name the
    buffers that were resident one window earlier.
    """
    import jax

    reg = registry if registry is not None else get_registry()
    try:
        devices = jax.local_devices()
    except RuntimeError:  # backend not initialisable (stripped build)
        return
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — per-backend API, optional
            stats = None
        if not stats:
            continue
        in_use = stats.get("bytes_in_use")
        peak = stats.get("peak_bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is not None:
            reg.gauge(
                "kafka_device_memory_bytes_in_use",
                "device memory currently allocated (bytes, per device)",
            ).set(float(in_use), device=d.id)
            reg.trace.add_counter(f"device{d.id}_bytes_in_use", in_use)
        if peak is not None:
            reg.gauge(
                "kafka_device_memory_peak_bytes",
                "high-water mark of device memory allocation (bytes, "
                "per device)",
            ).set(float(peak), device=d.id)
            reg.trace.add_counter(f"device{d.id}_peak_bytes", peak)
        if limit is not None and in_use is not None:
            reg.gauge(
                "kafka_device_memory_headroom_bytes",
                "device memory still allocatable (bytes_limit - "
                "bytes_in_use, per device) — the distance to an OOM",
            ).set(float(limit) - float(in_use), device=d.id)
    # Memory-ledger tick (late import: devprof builds on this module's
    # conventions, no cycle at import time).
    from . import devprof

    devprof.update_ledger(reg)
