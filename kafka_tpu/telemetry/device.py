"""Device-side diagnostics, host-side accounting.

The engine's convergence scalars (GN iterations, innovation chi^2 per
band, bounds-clip counts, nodata counts) are computed ON DEVICE inside the
solve/scan programs (``core.solvers``) and travel to the host as ONE
packed vector per window — the same single device->host round-trip the
diagnostics log always paid (~0.2 s of latency each on a tunneled chip),
now carrying four more scalars instead of costing extra syncs.

``fetch_scalars`` is the one funnel for those packed reads: every call
increments ``kafka_engine_device_reads_total``, which is how the test
suite PROVES telemetry adds zero device->host transfers per window (the
counter equals the number of solve dispatches whether or not a telemetry
directory is configured).
"""

from __future__ import annotations

import numpy as np

from .registry import get_registry


def fetch_scalars(packed) -> np.ndarray:
    """Materialise one packed device vector of diagnostic scalars.

    The ONLY sanctioned device->host read for engine diagnostics: callers
    concatenate every scalar they need into ``packed`` first, so the
    counter below is an exact census of diagnostic round-trips.
    """
    get_registry().counter(
        "kafka_engine_device_reads_total",
        "packed diagnostic device->host reads (one per solve dispatch)",
    ).inc()
    return np.asarray(packed)
