"""Shared host/device health layer (grown out of ``bench.py``'s probes).

Rounds 3-5 archived 35.7k / 72.8k / 44.0k e2e px-steps/s with NO code
change — tunnel congestion and host load, not the software under test.
The probes measure both noise sources; PR 2 moves them here so the bench
and production runs share ONE health layer: every probe records its
reading into the telemetry registry, and ``probe_health`` *sources its
readings back from the registry* — the registry is the single source of
truth a dashboard, the bench JSON and a production health endpoint all
read.
"""

from __future__ import annotations

import sys
import time
from typing import Optional

import numpy as np

from .registry import MetricsRegistry, get_registry

# Queued-device-rate reference: the XLA GN solve at 2^19 px measures
# ~6.4 ms on a healthy v5e window (BASELINE.md "Roofline", held +-1%
# across rounds 3-5).  A probe outside +-60% of that means the tunnel or
# chip is not in its healthy regime.
HEALTHY_DEVICE_MS = 6.4
DEVICE_BAND = (0.4, 1.6)
# Host probe: a 256x256 float32 matmul medians ~0.27 ms on this bench
# host when idle; >1.0 ms means the (one-core) host is sharing cycles
# with something else and every e2e row is suspect.
HEALTHY_HOST_MS = 1.0


def latest_verdict(registry: Optional[MetricsRegistry] = None) -> dict:
    """The LAST probe round's verdict, read back from the registry
    gauges — no probing.

    This is the ONE shared health-sampling path for every consumer
    that wants the verdict without paying for a probe: ``/healthz``
    (non-``?probe=1``), admission's ``shed_when_unhealthy``, and the
    SLO evaluator's health context all read it, and only
    :func:`probe_health` itself runs probes (and fires the flight
    recorder's persistent-unhealthy trigger) — so no process ever
    grows a second background prober.  ``unhealthy`` is None while
    nothing probed yet."""
    reg = registry if registry is not None else get_registry()
    unhealthy = reg.value("kafka_health_unhealthy")
    return {
        "probed": unhealthy is not None,
        "unhealthy": None if unhealthy is None else bool(unhealthy),
        "probe_host_ms": reg.value("kafka_health_probe_host_ms"),
        "probe_device_ms": reg.value("kafka_health_probe_device_ms"),
    }


def _dump_unhealthy_forensics() -> None:
    """The flight recorder's persistent-unhealthy trigger, owned HERE
    (next to the one probing site) so the verdict-reading consumers
    above never re-arm it."""
    from .flight_recorder import active_recorder

    recorder = active_recorder()
    if recorder is not None:
        recorder.dump("unhealthy_probe")


def probe_host(reps: int = 9,
               registry: Optional[MetricsRegistry] = None) -> float:
    """Median ms of a fixed host-side CPU workload (256^2 f32 matmul);
    recorded as ``kafka_health_probe_host_ms``."""
    a = np.random.default_rng(0).normal(size=(256, 256)).astype(np.float32)
    a @ a  # warm the BLAS thread pool / caches out of the measurement
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        a @ a
        times.append(time.perf_counter() - t0)
    ms = float(np.median(times)) * 1e3
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "kafka_health_probe_host_ms",
        "median ms of the fixed host CPU probe (healthy <= 1.0)",
    ).set(ms)
    return ms


def probe_device(n_pix: int = 1 << 19, ks=(5, 25), reps: int = 3,
                 registry: Optional[MetricsRegistry] = None) -> float:
    """Queued-slope ms/solve of the standard XLA GN solve at the bench
    operating size — the quantity whose healthy value (~6.4 ms on v5e)
    BASELINE.md pins; recorded as ``kafka_health_probe_device_ms``.
    Same methodology as ``bench.bench_device_sizes`` but with fixed k's:
    a probe must be cheap, and at 2^19 px the per-solve work already
    dominates the flush round-trip."""
    import jax.numpy as jnp

    from ..core.solvers import assimilate_date_jit
    from ..testing.synthetic import make_tip_problem

    op, bands, x0, p_inv0 = make_tip_problem(n_pix)
    opts = {"state_bounds": (
        jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
    )}
    args = (op.linearize, bands, x0, p_inv0, None, opts)
    x, _, _ = assimilate_date_jit(*args)
    np.asarray(x[0][:1])

    def run_k(k):
        t0 = time.perf_counter()
        for _ in range(k):
            r, _, _ = assimilate_date_jit(*args)
        np.asarray(r[0][:1])
        return time.perf_counter() - t0

    k1, k2 = ks
    slopes = [(run_k(k2) - run_k(k1)) / (k2 - k1) for _ in range(reps)]
    ms = float(np.median(slopes)) * 1e3
    reg = registry if registry is not None else get_registry()
    reg.gauge(
        "kafka_health_probe_device_ms",
        "queued-slope ms/solve of the XLA GN probe at 2^19 px "
        "(healthy v5e ~6.4)",
    ).set(ms)
    return ms


def probe_health(retry_wait_s: float = 15.0,
                 registry: Optional[MetricsRegistry] = None) -> dict:
    """Probe the two noise sources; retry once on an off-band reading.

    Returns ``{"probe_device_ms", "probe_host_ms", "probe_retried",
    "unhealthy", "unhealthy_reasons"}`` — the PR 1 bench health schema,
    unchanged.  The values are read BACK from the registry gauges the
    probes set (not from the probes' return values), so any consumer of
    the registry — bench JSON, metrics.prom, a dashboard — sees exactly
    the readings this verdict was made from.  The device band only
    applies on a real TPU (interpret/CPU timings measure the interpreter,
    not the chip); the host band always applies.  ``unhealthy`` also
    lands in the registry as ``kafka_health_unhealthy``.
    """
    import jax

    reg = registry if registry is not None else get_registry()
    on_tpu = jax.default_backend() == "tpu"

    def read():
        probe_host(registry=reg)
        if on_tpu:
            probe_device(registry=reg)
        # Registry-sourced readings: the gauges are the single source of
        # truth this verdict and every other consumer share.
        host_ms = reg.value("kafka_health_probe_host_ms")
        device_ms = reg.value("kafka_health_probe_device_ms") \
            if on_tpu else None
        reasons = []
        if host_ms > HEALTHY_HOST_MS:
            reasons.append(
                f"host probe {host_ms:.2f} ms > {HEALTHY_HOST_MS} ms"
            )
        if device_ms is not None:
            lo, hi = (HEALTHY_DEVICE_MS * b for b in DEVICE_BAND)
            if not lo <= device_ms <= hi:
                reasons.append(
                    f"device probe {device_ms:.2f} ms outside "
                    f"[{lo:.1f}, {hi:.1f}] ms"
                )
        return host_ms, device_ms, reasons

    host_ms, device_ms, reasons = read()
    retried = False
    if reasons:
        # Retry-or-flag: transient congestion (a test suite finishing, a
        # tunnel hiccup) often clears within seconds; a persistent reading
        # is real weather and the run is flagged, not silently trusted.
        print(f"bench health: {'; '.join(reasons)} — retrying in "
              f"{retry_wait_s:.0f}s", file=sys.stderr)
        # kafkalint: disable=ad-hoc-retry — single bounded re-read of an
        # environment probe (no failure to classify, no backoff series);
        # a RetryPolicy would add machinery without changing behaviour.
        time.sleep(retry_wait_s)
        host_ms, device_ms, reasons = read()
        retried = True
    unhealthy = bool(reasons)
    reg.gauge(
        "kafka_health_unhealthy",
        "1 when the latest health probe round was off-band",
    ).set(float(unhealthy))
    reg.emit(
        "health_probe", probe_host_ms=round(host_ms, 3),
        probe_device_ms=None if device_ms is None else round(device_ms, 3),
        retried=retried, unhealthy=unhealthy, reasons=reasons,
    )
    if unhealthy:
        # A persistent off-band verdict is a forensics moment: snapshot
        # the run state NOW (probe event included), while the weather
        # that flagged it is live — the run may still die later with no
        # better evidence.
        _dump_unhealthy_forensics()
    return {
        "probe_device_ms": None if device_ms is None
        else round(device_ms, 3),
        "probe_host_ms": round(host_ms, 3),
        "probe_retried": retried,
        "unhealthy": unhealthy,
        "unhealthy_reasons": reasons,
    }
