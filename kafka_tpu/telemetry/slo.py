"""SLO engine: declarative objectives, burn-rate alerts, error budgets.

The fleet emits every signal a production assimilation service needs —
admission rejections by reason, serve latency histograms, quality-drift
gauges, solver quarantine counters, device-fraction attribution — but
until this module nothing *watched* them: ``fleet_status --watch``
requires a human.  This is the layer between the metrics plane and the
operators, in the SRE idiom:

- **Declarative objectives** (:func:`default_objectives`): each
  :class:`Objective` names a target and a *signal* over the local
  :class:`~.registry.MetricsRegistry` —

  =============== ====================================================
  ``availability`` ok / (ok + rejected + error) from the admission /
                   service counters (``kafka_serve_latency_seconds``
                   count vs ``kafka_serve_rejected_total`` +
                   ``kafka_serve_errors_total``)
  ``latency``      fraction of served requests under the p99 bar,
                   from the serve latency histogram buckets (the
                   window p99 itself is derived with the fleet view's
                   ``quantile_from_buckets`` machinery)
  ``quality``      clean fraction of evaluations with
                   ``kafka_quality_drift_active`` == 0
  ``solver``       non-quarantined pixel fraction
                   (``kafka_solver_quarantined_pixels_total`` over
                   ``kafka_engine_pixels_total``)
  ``perf``         fraction of evaluations with
                   ``kafka_perf_device_fraction`` at or above the
                   floor
  =============== ====================================================

- **Multi-window multi-burn-rate rules**: the burn rate is the window
  error rate over the error budget (``1 - target``).  A burn above
  ``FAST_BURN_THRESHOLD`` over the FAST window raises a ``page``; a
  burn above ``SLOW_BURN_THRESHOLD`` over the SLOW window raises a
  ``warn`` — fast catastrophic burn pages in minutes, slow budget leak
  warns before the budget is gone.  Window lengths are constructor
  knobs so tier-1 chaos tests run in seconds.
- **Alert state machine** per (objective, severity):
  ``ok -> pending -> firing -> resolved(-> ok)``; transitions append to
  the ``alerts.jsonl`` ledger (events.jsonl rotation discipline), emit
  ``slo_alert`` / ``slo_resolved`` events and drive the
  ``kafka_slo_alerts_firing{severity=}`` gauges the admission layer
  (``shed_on_slo`` -> reason ``slo_burn``) and ``/healthz`` read.
- **Error-budget ledger** per objective: budget consumed so far
  (cumulative error rate over the error budget), remaining fraction,
  and a time-to-exhaustion estimate at the current slow burn rate.

Evaluation runs on ONE tracked background thread per process
(:func:`start_engine`, next to the live publisher); the evaluator
READS the health gauges through :func:`~.health.latest_verdict` — the
shared sampling path ``probe_health`` maintains — instead of probing
itself, so no second background prober exists per process.  Surfaces:
``/alertz`` (telemetry.httpd), the live snapshots / ``aggregate_fleet``
/ ``fleet_status`` fleet alert view, ``tools/slo_report.py`` over the
``alerts.jsonl`` ledgers, and the BENCH ``"slo"`` snapshot.  See
BASELINE.md "SLOs & alerting".
"""

from __future__ import annotations

import collections
import dataclasses
import json
import os
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from . import tracing
from .aggregate import quantile_from_buckets
from .registry import MetricsRegistry, get_registry

# ---------------------------------------------------------------------------
# SLO config block — the ONE sanctioned home for objective targets,
# burn-rate thresholds, window lengths and budget literals (kafkalint
# rule 18 ``magic-slo-threshold`` flags numeric SLO literals anywhere
# else).  Everything below is overridable per engine/objective; these
# are the fleet defaults BASELINE.md documents.
# ---------------------------------------------------------------------------

#: availability target: fraction of decided requests (ok + rejected +
#: error) that must be served ok.  Error budget = 1 - target.
AVAILABILITY_TARGET = 0.999
#: latency objective: at least this fraction of OK-served requests must
#: land under the bar below.
LATENCY_TARGET = 0.99
#: the latency bar (ms).  Warm serves measure ~30 ms; the bar leaves
#: room for queueing before the objective burns.
LATENCY_BAR_MS = 250.0
#: quality objective: fraction of evaluation ticks with NO drift
#: sentinel alarming (``kafka_quality_drift_active`` == 0).
CLEAN_FRACTION_TARGET = 0.99
#: solver objective: fraction of assimilated pixels NOT quarantined.
SOLVER_TARGET = 0.999
#: perf objective: fraction of evaluation ticks with the device
#: fraction at or above the floor.  With a 0.90 target the maximum
#: possible burn is 10: the perf objective can WARN (slow threshold 6)
#: but never page — throughput regressions are an operator concern,
#: not a wake-up call.
PERF_TARGET = 0.90
#: ``kafka_perf_device_fraction`` floor below which an evaluation tick
#: counts against the perf objective.
PERF_DEVICE_FRACTION_FLOOR = 0.05

#: multi-window burn-rate rule defaults (the SRE workbook shape): the
#: FAST window catches catastrophic burn and PAGES, the SLOW window
#: catches sustained budget leak and WARNS.  At burn 14.4 a 30-day
#: budget lasts ~2 days; at burn 6 it lasts 5 days.
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0
FAST_BURN_THRESHOLD = 14.4
SLOW_BURN_THRESHOLD = 6.0
#: evaluation cadence of the background thread.
EVAL_INTERVAL_S = 5.0
#: a breached rule sits PENDING this long before it FIRES (0 = the
#: next evaluation after the breach confirms it — two consecutive
#: breached evaluations, well inside one fast window).
PENDING_FOR_S = 0.0
#: the error-budget accounting period (time-to-exhaustion horizon).
BUDGET_WINDOW_S = 30 * 24 * 3600.0

#: alerts.jsonl rotation (events.jsonl discipline: size-capped
#: segments, newest ``keep`` survive).
ALERTS_FILENAME = "alerts.jsonl"
ALERTS_ROTATE_BYTES = 8 * 1024 * 1024
ALERTS_KEEP = 3
# -- end of the sanctioned SLO config block ---------------------------------

#: alert severities (the ``kafka_slo_alerts_firing`` label values).
SEVERITY_PAGE = "page"
SEVERITY_WARN = "warn"
SEVERITIES = (SEVERITY_PAGE, SEVERITY_WARN)

#: alert states.
OK = "ok"
PENDING = "pending"
FIRING = "firing"

LEDGER_SCHEMA = 1

#: bounded per-objective sample retention: the budget ledger is
#: computed over at most this many evaluation samples (the slow window
#: at the default cadence fits easily; a 30-day budget window is
#: approximated by the retained horizon on very long runs).
MAX_SAMPLES = 4096

_EPS = 1e-12


# ---------------------------------------------------------------------------
# Registry-reading helpers (the signals' vocabulary).
# ---------------------------------------------------------------------------

def _metric(reg: MetricsRegistry, name: str):
    for m in reg.metrics():
        if m.name == name:
            return m
    return None


def _sum_series(reg: MetricsRegistry, name: str) -> Optional[float]:
    """Sum a counter/gauge over ALL its label series (e.g. every
    rejection reason); None when the metric was never registered."""
    m = _metric(reg, name)
    if m is None:
        return None
    total = 0.0
    for _key, val in m._series():
        total += float(val)
    return total


def _hist_totals(reg: MetricsRegistry, name: str
                 ) -> Optional[Tuple[Tuple[float, ...], List[int], int]]:
    """Histogram state merged over label series: ``(le, cumulative
    buckets, count)``; None when absent or empty."""
    m = _metric(reg, name)
    if m is None or m.kind != "histogram":
        return None
    buckets = [0] * len(m.buckets)
    count = 0
    for _key, st in m._series():
        count += int(st["count"])
        for i, b in enumerate(st["buckets"]):
            buckets[i] += int(b)
    if count == 0 and not any(buckets):
        return m.buckets, buckets, 0
    return m.buckets, buckets, count


# ---------------------------------------------------------------------------
# Objectives.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective.

    ``kind`` is ``"counter"`` (``signal(reg)`` returns CUMULATIVE
    ``(good, bad)`` totals — zeros while the feeding subsystem has
    registered nothing, since in-process counters start at zero) or
    ``"gauge"`` (``signal(reg)`` returns the instantaneous bad
    fraction in [0, 1] — each evaluation tick is one good/bad event —
    or None while the gauge is unset, which reads as ``no_data``).
    ``detail`` optionally contributes display-only context to the
    summary (the latency objective's window p99)."""

    name: str
    kind: str
    target: float
    description: str
    signal: Callable[[MetricsRegistry], Optional[object]]
    detail: Optional[Callable[[MetricsRegistry], dict]] = None

    @property
    def error_budget(self) -> float:
        return max(1.0 - float(self.target), _EPS)


#: the serving path's OK-latency histograms: a replica observes
#: kafka_serve_latency_seconds, the front door kafka_route_latency_
#: seconds — one objective set covers both roles (absent metrics read
#: as zero, see below).
_LATENCY_HISTS = (
    "kafka_serve_latency_seconds",
    "kafka_route_latency_seconds",
)


def _merged_latency(reg: MetricsRegistry
                    ) -> Optional[Tuple[Tuple[float, ...],
                                        List[int], int]]:
    """The serving-path latency histograms merged bucket-wise (they
    share the registry's default layout); the non-empty one when
    layouts ever diverge."""
    merged = None
    for name in _LATENCY_HISTS:
        tot = _hist_totals(reg, name)
        if tot is None:
            continue
        if merged is None:
            merged = (tot[0], list(tot[1]), tot[2])
        elif merged[0] == tot[0]:
            merged = (
                merged[0],
                [a + b for a, b in zip(merged[1], tot[1])],
                merged[2] + tot[2],
            )
        elif tot[2] > merged[2]:
            merged = (tot[0], list(tot[1]), tot[2])
    return merged


def _availability_signal(reg: MetricsRegistry):
    # Counters start at zero in-process, so unregistered metrics read
    # as zero totals — the first evaluation's baseline then predates
    # any traffic instead of swallowing events that land between the
    # first evaluation and the serve layer's first registration.
    ok = _merged_latency(reg)
    bad = 0.0
    for name in ("kafka_serve_rejected_total",
                 "kafka_serve_errors_total",
                 "kafka_route_rejected_total"):
        bad += _sum_series(reg, name) or 0.0
    good = 0.0 if ok is None else float(ok[2])
    return good, bad


def _latency_signal(bar_ms: float):
    def signal(reg: MetricsRegistry):
        tot = _merged_latency(reg)
        if tot is None:
            return 0.0, 0.0
        le, buckets, count = tot
        good = count  # bar beyond the last finite bucket: all good
        for bound, cum in zip(le, buckets):
            if bound * 1e3 >= bar_ms:
                good = cum
                break
        return float(good), float(count - good)
    return signal


def _latency_detail(bar_ms: float):
    def detail(reg: MetricsRegistry) -> dict:
        tot = _merged_latency(reg)
        if tot is None or tot[2] == 0:
            return {"bar_ms": bar_ms, "p99_ms": None}
        le, buckets, count = tot
        p99 = quantile_from_buckets(list(le), buckets, count, 0.99)
        return {
            "bar_ms": bar_ms,
            "p99_ms": None if p99 is None else round(p99 * 1e3, 3),
        }
    return detail


def _quality_signal(reg: MetricsRegistry):
    drifting = reg.value("kafka_quality_drift_active")
    if drifting is None:
        return None
    return 1.0 if drifting else 0.0


def _solver_signal(reg: MetricsRegistry):
    pixels = _sum_series(reg, "kafka_engine_pixels_total") or 0.0
    quarantined = _sum_series(
        reg, "kafka_solver_quarantined_pixels_total"
    ) or 0.0
    return max(0.0, pixels - quarantined), quarantined


def _perf_signal(floor: float):
    def signal(reg: MetricsRegistry):
        frac = reg.value("kafka_perf_device_fraction")
        if frac is None:
            return None
        return 1.0 if float(frac) < floor else 0.0
    return signal


def default_objectives(
    availability_target: float = AVAILABILITY_TARGET,
    latency_target: float = LATENCY_TARGET,
    latency_bar_ms: float = LATENCY_BAR_MS,
    clean_target: float = CLEAN_FRACTION_TARGET,
    solver_target: float = SOLVER_TARGET,
    perf_target: float = PERF_TARGET,
    perf_floor: float = PERF_DEVICE_FRACTION_FLOOR,
) -> List[Objective]:
    """The five fleet objectives over the standard metric vocabulary.
    Targets/bars are keyword-overridable (a CPU test fleet's latency
    bar is not a TPU serving fleet's), defaults from the config block."""
    return [
        Objective(
            "availability", "counter", availability_target,
            "fraction of decided requests served ok "
            "(vs rejected + error)",
            _availability_signal,
        ),
        Objective(
            "latency", "counter", latency_target,
            f"fraction of OK-served requests under {latency_bar_ms:g} "
            "ms (serve latency histogram)",
            _latency_signal(latency_bar_ms),
            detail=_latency_detail(latency_bar_ms),
        ),
        Objective(
            "quality", "gauge", clean_target,
            "fraction of evaluations with no quality drift sentinel "
            "alarming",
            _quality_signal,
        ),
        Objective(
            "solver", "counter", solver_target,
            "fraction of assimilated pixels not quarantined",
            _solver_signal,
        ),
        Objective(
            "perf", "gauge", perf_target,
            f"fraction of evaluations with device fraction >= "
            f"{perf_floor:g}",
            _perf_signal(perf_floor),
        ),
    ]


# ---------------------------------------------------------------------------
# The alerts.jsonl sink (events.jsonl rotation discipline).
# ---------------------------------------------------------------------------

class _AlertLedger:
    """Append-only JSONL ledger with size-capped keep-N rotation —
    the same discipline as the registry's events.jsonl, so a resident
    daemon's alert history stays bounded on disk.  Thread-safe; in
    memory only (ring) when no directory is configured."""

    MAX_RECORDS = 1024

    def __init__(self, directory: Optional[str],
                 rotate_bytes: int = ALERTS_ROTATE_BYTES,
                 keep: int = ALERTS_KEEP):
        self.directory = directory
        self.path = os.path.join(directory, ALERTS_FILENAME) \
            if directory else None
        self.rotate_bytes = int(rotate_bytes)
        self.keep = int(keep)
        self._lock = threading.Lock()
        self.records: collections.deque = collections.deque(
            maxlen=self.MAX_RECORDS
        )
        self._bytes = 0
        if self.path is not None:
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                self._bytes = 0

    def append(self, rec: dict) -> None:
        with self._lock:
            self.records.append(rec)
            if self.path is None:
                return
            line = json.dumps(rec, default=str) + "\n"
            try:
                with open(self.path, "a") as f:
                    f.write(line)
                self._bytes += len(line)
                if self._bytes >= self.rotate_bytes:
                    self._rotate_locked()
            except OSError:
                # The ledger degrades, the run survives (the in-memory
                # ring still backs /alertz).
                pass

    def _rotate_locked(self) -> None:
        path = self.path
        for i in range(self.keep - 1, 0, -1):
            src = f"{path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{path}.{i + 1}")
        if self.keep > 0:
            os.replace(path, f"{path}.1")
        else:
            os.unlink(path)
        # Leave an empty live segment behind (the registry's events
        # rotation reopens its handle; we open per append): readers
        # looking for alerts.jsonl must always find it after activity.
        open(path, "a").close()
        self._bytes = 0


# ---------------------------------------------------------------------------
# Per-objective evaluator state.
# ---------------------------------------------------------------------------

class _AlertState:
    """One (objective, severity) rule's state machine."""

    def __init__(self):
        self.state = OK
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None

    def update(self, breached: bool, now: float,
               pending_for_s: float) -> Optional[str]:
        """Fold one evaluation in; returns the transition that happened
        (``"pending"`` / ``"firing"`` / ``"resolved"``) or None."""
        if breached:
            if self.state == OK:
                self.state = PENDING
                self.pending_since = now
                return PENDING
            if self.state == PENDING and \
                    now - self.pending_since >= pending_for_s:
                self.state = FIRING
                self.firing_since = now
                return FIRING
            return None
        if self.state == FIRING:
            self.state = OK
            self.pending_since = None
            return "resolved"
        if self.state == PENDING:
            # A breach that clears before confirmation never alerted —
            # back to ok silently (the SRE pending semantics).
            self.state = OK
            self.pending_since = None
        return None


class _ObjectiveState:
    def __init__(self):
        #: (ts, good_total, bad_total) cumulative samples.
        self.samples: collections.deque = collections.deque(
            maxlen=MAX_SAMPLES
        )
        #: first-ever sample — the budget ledger's origin (kept even
        #: after the deque slides).
        self.origin: Optional[Tuple[float, float, float]] = None
        #: gauge-kind objectives accumulate tick counts here.
        self.gauge_good = 0.0
        self.gauge_bad = 0.0
        self.alerts: Dict[str, _AlertState] = {
            SEVERITY_PAGE: _AlertState(),
            SEVERITY_WARN: _AlertState(),
        }
        self.has_data = False

    def window_rate(self, now: float, window_s: float
                    ) -> Tuple[float, float]:
        """(error_rate, total_events) over the trailing window: the
        baseline is the newest sample at or before ``now - window_s``
        (the first sample when the engine is younger than the window)."""
        if not self.samples:
            return 0.0, 0.0
        cutoff = now - window_s
        base = self.samples[0]
        for s in self.samples:
            if s[0] <= cutoff:
                base = s
            else:
                break
        newest = self.samples[-1]
        good_d = newest[1] - base[1]
        bad_d = newest[2] - base[2]
        total = good_d + bad_d
        if total <= 0:
            return 0.0, 0.0
        return bad_d / total, total


# ---------------------------------------------------------------------------
# The engine.
# ---------------------------------------------------------------------------

def _slo_metrics(reg: MetricsRegistry) -> dict:
    """Single registration site for the SLO metric vocabulary."""
    return {
        "firing": reg.gauge(
            "kafka_slo_alerts_firing",
            "SLO alerts currently firing, by severity — the admission "
            "layer sheds reason slo_burn off the page series and "
            "/healthz flips 503 while it is nonzero",
        ),
        "fired": reg.counter(
            "kafka_slo_alerts_fired_total",
            "SLO alert episodes that reached firing, by severity",
        ),
        "evals": reg.counter(
            "kafka_slo_evaluations_total",
            "SLO evaluation rounds run by the background evaluator",
        ),
    }


class SLOEngine:
    """Evaluates the objectives against one registry on a tracked
    background thread (or via :meth:`evaluate_once` under test
    control).  Window lengths, burn thresholds and the evaluation
    cadence are constructor knobs; defaults from the config block."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 directory: Optional[str] = None,
                 objectives: Optional[List[Objective]] = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 fast_burn: float = FAST_BURN_THRESHOLD,
                 slow_burn: float = SLOW_BURN_THRESHOLD,
                 interval_s: float = EVAL_INTERVAL_S,
                 pending_for_s: float = PENDING_FOR_S,
                 budget_window_s: float = BUDGET_WINDOW_S,
                 alerts_rotate_bytes: int = ALERTS_ROTATE_BYTES,
                 alerts_keep: int = ALERTS_KEEP):
        self._registry = registry
        if directory is None:
            reg = registry if registry is not None else get_registry()
            directory = reg.directory
        self.objectives = list(
            objectives if objectives is not None else default_objectives()
        )
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.interval_s = float(interval_s)
        self.pending_for_s = float(pending_for_s)
        self.budget_window_s = float(budget_window_s)
        self.ledger = _AlertLedger(
            directory, rotate_bytes=alerts_rotate_bytes,
            keep=alerts_keep,
        )
        self._lock = threading.Lock()
        self._state: Dict[str, _ObjectiveState] = {
            o.name: _ObjectiveState() for o in self.objectives
        }
        self._last_eval: Dict[str, dict] = {}
        self.fired_total = 0
        self.resolved_total = 0
        self._stop = threading.Event()
        self._started = False
        # PR 3 thread-tracing convention: capture the constructing
        # thread's context, re-install it on the worker.
        self._ctx = tracing.current_context()
        self._thread = threading.Thread(
            target=self._run, name="slo-evaluator", daemon=True,
        )

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "SLOEngine":
        if self._started:
            return self
        self._started = True
        self._thread.start()
        self._reg().emit(
            "slo_engine_started",
            objectives=[o.name for o in self.objectives],
            fast_window_s=self.fast_window_s,
            slow_window_s=self.slow_window_s,
            interval_s=self.interval_s,
        )
        return self

    @property
    def started(self) -> bool:
        return self._started

    def _run(self) -> None:
        tracing.set_context(self._ctx)
        tracing.set_lane("telemetry")
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as exc:  # noqa: BLE001 — the evaluator must outlive a bad signal; the error is counted and the next round retries
                self._reg().emit(
                    "slo_eval_failed", error=repr(exc)[:200],
                )

    def stop(self) -> None:
        """Stop the evaluator thread after one final evaluation (so the
        ledger carries the end-of-run state)."""
        if not self._started:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        try:
            self.evaluate_once()
        except Exception as exc:  # noqa: BLE001 — best-effort final round; shutdown must not raise
            self._reg().emit("slo_eval_failed", error=repr(exc)[:200])

    # -- evaluation -----------------------------------------------------

    def evaluate_once(self, now: Optional[float] = None) -> dict:
        """One evaluation round (the background thread's body and the
        tests' deterministic hook — inject ``now`` to control window
        arithmetic without sleeping).  Returns :meth:`summary`."""
        now = time.time() if now is None else float(now)
        reg = self._reg()
        m = _slo_metrics(reg)
        transitions: List[dict] = []
        with self._lock:
            for obj in self.objectives:
                st = self._state[obj.name]
                totals = self._sample(obj, st, reg)
                if totals is not None:
                    st.has_data = True
                    sample = (now, float(totals[0]), float(totals[1]))
                    if st.origin is None:
                        st.origin = sample
                    st.samples.append(sample)
                self._evaluate_objective(obj, st, now, transitions)
            firing_by_sev = {sev: 0 for sev in SEVERITIES}
            for name, st in self._state.items():
                for sev, al in st.alerts.items():
                    if al.state == FIRING:
                        firing_by_sev[sev] += 1
        for sev in SEVERITIES:
            m["firing"].set(firing_by_sev[sev], severity=sev)
        m["evals"].inc()
        for t in transitions:
            self.ledger.append(t)
            if t["kind"] == FIRING:
                m["fired"].inc(severity=t["severity"])
                reg.emit(
                    "slo_alert", objective=t["objective"],
                    severity=t["severity"], burn_fast=t["burn_fast"],
                    burn_slow=t["burn_slow"],
                )
            elif t["kind"] == "resolved":
                reg.emit(
                    "slo_resolved", objective=t["objective"],
                    severity=t["severity"],
                    duration_s=t.get("duration_s"),
                )
        return self.summary()

    def _sample(self, obj: Objective, st: _ObjectiveState,
                reg: MetricsRegistry):
        raw = obj.signal(reg)
        if raw is None:
            return None
        if obj.kind == "gauge":
            bad = max(0.0, min(1.0, float(raw)))
            st.gauge_good += 1.0 - bad
            st.gauge_bad += bad
            return st.gauge_good, st.gauge_bad
        return raw

    def _evaluate_objective(self, obj: Objective, st: _ObjectiveState,
                            now: float,
                            transitions: List[dict]) -> None:
        budget = obj.error_budget
        rate_fast, n_fast = st.window_rate(now, self.fast_window_s)
        rate_slow, n_slow = st.window_rate(now, self.slow_window_s)
        burn_fast = rate_fast / budget
        burn_slow = rate_slow / budget
        ledger = self._budget_ledger(obj, st, now, burn_slow)
        ev = {
            "error_rate_fast": round(rate_fast, 6),
            "error_rate_slow": round(rate_slow, 6),
            "burn_fast": round(burn_fast, 3),
            "burn_slow": round(burn_slow, 3),
            "events_fast": n_fast,
            "budget": ledger,
        }
        self._last_eval[obj.name] = ev
        for severity, breached in (
            (SEVERITY_PAGE, burn_fast > self.fast_burn),
            (SEVERITY_WARN, burn_slow > self.slow_burn),
        ):
            al = st.alerts[severity]
            was_firing_since = al.firing_since
            kind = al.update(breached, now, self.pending_for_s)
            if kind is None:
                continue
            rec = {
                "schema": LEDGER_SCHEMA,
                "ts": round(now, 6),
                "kind": kind,
                "objective": obj.name,
                "severity": severity,
                "target": obj.target,
                "burn_fast": ev["burn_fast"],
                "burn_slow": ev["burn_slow"],
                "error_rate_fast": ev["error_rate_fast"],
                "error_rate_slow": ev["error_rate_slow"],
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "budget": ledger,
            }
            if kind == FIRING:
                self.fired_total += 1
            elif kind == "resolved":
                self.resolved_total += 1
                if was_firing_since is not None:
                    rec["duration_s"] = round(
                        now - was_firing_since, 6
                    )
            transitions.append(rec)

    def _budget_ledger(self, obj: Objective, st: _ObjectiveState,
                       now: float, burn_slow: float) -> dict:
        """Budget consumed so far (cumulative error rate over the
        error budget), remaining fraction, and the time-to-exhaustion
        estimate at the current slow burn rate."""
        if st.origin is None or not st.samples:
            return {"consumed": 0.0, "remaining": 1.0, "tte_s": None}
        newest = st.samples[-1]
        good_d = newest[1] - st.origin[1]
        bad_d = newest[2] - st.origin[2]
        total = good_d + bad_d
        rate = bad_d / total if total > 0 else 0.0
        consumed = rate / obj.error_budget
        remaining = max(0.0, 1.0 - consumed)
        tte = None
        if burn_slow > 0 and remaining > 0:
            tte = round(
                self.budget_window_s * remaining / burn_slow, 3
            )
        elif remaining <= 0:
            tte = 0.0
        return {
            "consumed": round(consumed, 6),
            "remaining": round(remaining, 6),
            "tte_s": tte,
        }

    # -- read side ------------------------------------------------------

    def firing(self) -> List[dict]:
        """Currently-firing alerts, page first."""
        out: List[dict] = []
        with self._lock:
            for obj in self.objectives:
                st = self._state[obj.name]
                for sev in SEVERITIES:
                    al = st.alerts[sev]
                    if al.state == FIRING:
                        ev = self._last_eval.get(obj.name) or {}
                        out.append({
                            "objective": obj.name,
                            "severity": sev,
                            "since": al.firing_since,
                            "burn_fast": ev.get("burn_fast"),
                            "burn_slow": ev.get("burn_slow"),
                        })
        return out

    def summary(self) -> dict:
        """The /alertz, live-snapshot and BENCH surface."""
        objectives: Dict[str, dict] = {}
        with self._lock:
            for obj in self.objectives:
                st = self._state[obj.name]
                ev = self._last_eval.get(obj.name) or {}
                states = {
                    sev: st.alerts[sev].state for sev in SEVERITIES
                }
                if FIRING in states.values():
                    status = FIRING
                elif PENDING in states.values():
                    status = PENDING
                elif st.has_data:
                    status = OK
                else:
                    status = "no_data"
                entry = {
                    "target": obj.target,
                    "kind": obj.kind,
                    "status": status,
                    "alerts": states,
                    "burn_fast": ev.get("burn_fast"),
                    "burn_slow": ev.get("burn_slow"),
                    "error_rate_fast": ev.get("error_rate_fast"),
                    "budget": ev.get("budget")
                    or {"consumed": 0.0, "remaining": 1.0,
                        "tte_s": None},
                }
                if obj.detail is not None:
                    try:
                        entry["detail"] = obj.detail(self._reg())
                    except Exception:  # noqa: BLE001 — display-only context must not take /alertz down
                        entry["detail"] = None
                objectives[obj.name] = entry
        return {
            "enabled": True,
            "started": self._started,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn": self.fast_burn,
            "slow_burn": self.slow_burn,
            "interval_s": self.interval_s,
            "objectives": objectives,
            "firing": self.firing(),
            "alerts_fired": self.fired_total,
            "alerts_resolved": self.resolved_total,
            # Health context from the SHARED sampling path (the gauges
            # probe_health maintains) — the evaluator never probes.
            "health": _health_context(self._reg()),
            "ledger_path": self.ledger.path,
        }


def _health_context(reg: MetricsRegistry) -> dict:
    from .health import latest_verdict

    v = latest_verdict(reg)
    return {"probed": v["probed"], "unhealthy": v["unhealthy"]}


# ---------------------------------------------------------------------------
# Per-registry engine binding (the quality.get_ledger idiom) + the
# process-level start/stop hooks the CLI drivers call next to
# live.start_publisher.
# ---------------------------------------------------------------------------

_engines: "weakref.WeakKeyDictionary[MetricsRegistry, SLOEngine]" = \
    weakref.WeakKeyDictionary()
_engines_lock = threading.Lock()

#: the summary shape for a process with no engine (live snapshots and
#: /alertz stay schema-stable either way).
DISABLED_SUMMARY = {
    "enabled": False,
    "started": False,
    "objectives": {},
    "firing": [],
    "alerts_fired": 0,
    "alerts_resolved": 0,
}


def get_engine(registry: Optional[MetricsRegistry] = None,
               **kwargs) -> SLOEngine:
    """The engine bound to ``registry`` (default: the process
    registry), created NOT-started on first use with the registry's
    telemetry directory as the ledger home.  ``kwargs`` configure a
    newly-created engine and are ignored for an existing one."""
    reg = registry if registry is not None else get_registry()
    with _engines_lock:
        eng = _engines.get(reg)
        if eng is None:
            eng = _engines[reg] = SLOEngine(registry=reg, **kwargs)
        return eng


def bound_engine(registry: Optional[MetricsRegistry] = None
                 ) -> Optional[SLOEngine]:
    """The engine bound to ``registry`` if one exists — never creates."""
    reg = registry if registry is not None else get_registry()
    with _engines_lock:
        return _engines.get(reg)


def start_engine(registry: Optional[MetricsRegistry] = None,
                 **kwargs) -> SLOEngine:
    """Create-if-needed and start the tracked background evaluator for
    ``registry`` (the CLI drivers' hook, next to live.start_publisher).
    Idempotent."""
    return get_engine(registry, **kwargs).start()


def stop_engine(registry: Optional[MetricsRegistry] = None) -> None:
    """Stop the bound evaluator (final evaluation included); no-op
    when none exists."""
    eng = bound_engine(registry)
    if eng is not None:
        eng.stop()


def summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """The bound engine's summary, or the stable disabled shape."""
    eng = bound_engine(registry)
    if eng is None:
        return dict(DISABLED_SUMMARY)
    return eng.summary()


def firing(registry: Optional[MetricsRegistry] = None) -> List[dict]:
    eng = bound_engine(registry)
    return [] if eng is None else eng.firing()


def firing_pages(registry: Optional[MetricsRegistry] = None
                 ) -> List[str]:
    """Objective names with a PAGE-severity alert firing — the
    /healthz 503 trigger and the admission layer's shed signal."""
    return sorted(
        a["objective"] for a in firing(registry)
        if a["severity"] == SEVERITY_PAGE
    )


# ---------------------------------------------------------------------------
# Ledger loading (tools/slo_report.py, tests).
# ---------------------------------------------------------------------------

def load_alerts(path: str) -> Tuple[List[dict], int]:
    """Parse one ``alerts.jsonl`` (+ its rotated ``.N`` segments,
    oldest first); returns ``(records, skipped)``.  Torn or non-record
    lines are skipped, not fatal."""
    paths: List[str] = []
    directory, base = os.path.split(path)
    try:
        segments = sorted(
            (int(n[len(base) + 1:]), os.path.join(directory or ".", n))
            for n in os.listdir(directory or ".")
            if n.startswith(base + ".")
            and n[len(base) + 1:].isdigit()
        )
    except OSError:
        segments = []
    paths.extend(p for _, p in sorted(segments, reverse=True))
    paths.append(path)
    records: List[dict] = []
    skipped = 0
    for p in paths:
        try:
            f = open(p, encoding="utf-8", errors="replace")
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    skipped += 1
                    continue
                if not isinstance(rec, dict) or "kind" not in rec \
                        or "objective" not in rec:
                    skipped += 1
                    continue
                records.append(rec)
    return records, skipped


def episodes_from(records: List[dict]) -> List[dict]:
    """Alert episodes reconstructed from ledger records alone: each
    firing record opens an episode for its (objective, severity), the
    matching resolved record closes it (open episodes have
    ``resolved_ts: None``).  Pending records annotate the episode's
    lead time."""
    open_eps: Dict[Tuple[str, str], dict] = {}
    pending_ts: Dict[Tuple[str, str], float] = {}
    episodes: List[dict] = []
    for rec in records:
        key = (rec["objective"], rec.get("severity", "?"))
        kind = rec.get("kind")
        ts = float(rec.get("ts") or 0.0)
        if kind == PENDING:
            pending_ts[key] = ts
        elif kind == FIRING:
            ep = {
                "objective": key[0],
                "severity": key[1],
                "pending_ts": pending_ts.pop(key, None),
                "firing_ts": ts,
                "resolved_ts": None,
                "duration_s": None,
                "burn_fast": rec.get("burn_fast"),
                "burn_slow": rec.get("burn_slow"),
                "budget": rec.get("budget"),
            }
            open_eps[key] = ep
            episodes.append(ep)
        elif kind == "resolved":
            ep = open_eps.pop(key, None)
            if ep is None:
                # A resolve whose firing rotated away still reports.
                ep = {
                    "objective": key[0], "severity": key[1],
                    "pending_ts": None, "firing_ts": None,
                    "burn_fast": rec.get("burn_fast"),
                    "burn_slow": rec.get("burn_slow"),
                }
                episodes.append(ep)
            ep["resolved_ts"] = ts
            ep["duration_s"] = rec.get("duration_s") if rec.get(
                "duration_s"
            ) is not None else (
                round(ts - ep["firing_ts"], 6)
                if ep.get("firing_ts") else None
            )
            ep["budget"] = rec.get("budget", ep.get("budget"))
    return episodes
