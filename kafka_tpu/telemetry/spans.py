"""Timed phase spans: one context manager that lands in BOTH sinks.

``utils.profiling.annotate`` labels host work inside ``jax.profiler``
traces (TensorBoard/Perfetto timelines); the registry records the same
span as a wall-time histogram and a JSONL event.  The engine's phases
(advance / assimilate / dump / fused_scan) use this so a run's phase
breakdown is readable from the metrics snapshot without ever capturing a
profiler trace — and when a trace IS captured, the two views carry the
same names.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from ..utils.profiling import annotate
from .registry import MetricsRegistry, get_registry


@contextlib.contextmanager
def span(phase: str, registry: Optional[MetricsRegistry] = None,
         **fields) -> Iterator[None]:
    """Time the enclosed block as engine phase ``phase``.

    Shows up as a ``kafka/<phase>`` TraceAnnotation in profiler traces, a
    ``kafka_engine_phase_seconds{phase=...}`` histogram observation, and a
    ``phase`` JSONL event (with any extra ``fields`` attached).
    """
    reg = registry if registry is not None else get_registry()
    with annotate(f"kafka/{phase}"):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            reg.histogram(
                "kafka_engine_phase_seconds",
                "wall seconds per engine phase (advance/assimilate/"
                "dump/fused_scan)",
            ).observe(dt, phase=phase)
            reg.emit("phase", phase=phase, seconds=round(dt, 6), **fields)
