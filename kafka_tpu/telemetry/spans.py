"""Timed phase spans: one context manager that lands in THREE sinks.

``utils.profiling.annotate`` labels host work inside ``jax.profiler``
traces (TensorBoard/Perfetto timelines); the registry records the same
span as a wall-time histogram and a JSONL event; and the registry's
:class:`~.tracing.TraceBuffer` records it as a timeline span for the
run's ``trace.json``.  The engine's phases (advance / assimilate / dump /
fused_scan) use this so a run's phase breakdown is readable from the
metrics snapshot without ever capturing a profiler trace — and when a
trace IS captured, all views carry the same names.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

from ..utils.profiling import annotate
from . import tracing
from .registry import MetricsRegistry, get_registry


class Stopwatch:
    """The sanctioned raw timer for device-adjacent host code.

    kafkalint rule 15 (``ad-hoc-timing``) bans bare
    ``time.perf_counter``/``time.monotonic`` timing in ``core/``,
    ``engine/``, ``shard/`` and ``obsops/`` so every measured interval
    flows through the telemetry layer — either a :func:`span` (which
    also lands in the histograms and the trace timeline) or, where the
    caller needs the raw readings (metric observations with labels,
    ``TraceBuffer.add_span`` endpoints), this stopwatch.  ``t0`` and
    :meth:`now` are ``time.perf_counter`` readings, directly usable as
    trace-span endpoints.
    """

    __slots__ = ("t0",)

    def __init__(self):
        self.t0 = time.perf_counter()

    @staticmethod
    def now() -> float:
        """Current ``perf_counter`` reading (a span endpoint)."""
        return time.perf_counter()

    def elapsed(self) -> float:
        """Seconds since construction."""
        return time.perf_counter() - self.t0


def stopwatch() -> Stopwatch:
    """Start a :class:`Stopwatch` (the device-adjacent timing funnel)."""
    return Stopwatch()


@contextlib.contextmanager
def span(phase: str, registry: Optional[MetricsRegistry] = None,
         **fields) -> Iterator[None]:
    """Time the enclosed block as engine phase ``phase``.

    Shows up as a ``kafka/<phase>`` TraceAnnotation in profiler traces, a
    ``kafka_engine_phase_seconds{phase=...}`` histogram observation, a
    ``phase`` JSONL event (with any extra ``fields`` attached), and a
    ``cat: "phase"`` span on the recording thread's track in
    ``trace.json``.  Nested spans see this one as their ``parent_span``.
    All sinks record on the exception path too — a phase that dies still
    leaves its wall time and its place on the timeline.
    """
    reg = registry if registry is not None else get_registry()
    span_id = tracing.next_span_id()
    token = tracing.push_parent(span_id)
    with annotate(f"kafka/{phase}"):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            t1 = time.perf_counter()
            tracing.pop(token)
            dt = t1 - t0
            reg.histogram(
                "kafka_engine_phase_seconds",
                "wall seconds per engine phase (advance/assimilate/"
                "dump/fused_scan)",
            ).observe(dt, phase=phase)
            reg.emit("phase", phase=phase, seconds=round(dt, 6), **fields)
            reg.trace.add_span(
                phase, t0, t1, cat="phase", span_id=span_id, **fields
            )
