"""Assimilation-quality observability: the innovation-consistency ledger.

The fleet has full *process* observability (metrics, traces, live
endpoints) but none of it watches the *science*: a Kalman filter can
become statistically inconsistent — biased observations, mis-specified
R/Q, a drifting sensor — while every ``/healthz`` stays green.  The raw
signal already exists: the per-band innovation chi-square is computed
INSIDE the jitted solve and rides the engine's single packed
device->host read per window (PAPER.md's ``||y - H(x)||^2_{R^-1}``
term, normalised per valid observation so E[ratio] ~= 1 for a
consistent filter).  This module turns that evaporating histogram
sample into a monitored, persisted, alertable product surface:

- :func:`verdict_for` — the textbook consistency check: the normalised
  chi-square ratio against configurable bands yields ``CONSISTENT`` /
  ``OVERCONFIDENT`` (residuals larger than the assumed R admits — the
  filter trusts itself too much) / ``UNDERCONFIDENT`` (residuals
  implausibly small — R is inflated);
- :class:`DriftSentinel` — rolling EWMA + two-sided CUSUM over one
  per-band ratio series; a CUSUM excursion past its decision threshold
  (or a sustained EWMA departure) flags the date as DRIFTING, emits a
  ``quality_drift`` event and raises the
  ``kafka_quality_drift_active`` gauge;
- :class:`QualityLedger` — the durable per-window record: every
  assimilated window appends one JSON line to ``quality.jsonl`` in the
  telemetry directory (date, tile/chunk prefix, per-band ratios,
  valid-pixel count, solver-health counts, degraded flag, verdict,
  sentinel state) with ZERO added device reads — the scalars were
  already on the host;
- :func:`observation_bias` — the ``obs.bias`` chaos site: scripted
  additive bias on armed observation dates (``KAFKA_TPU_FAULTS``
  grammar, call numbers = 1-based fetch-order date numbers), ``None``
  when disarmed so the production fetch path adds nothing.

``tools/quality_report.py`` renders per-tile scorecards from one or
many ledgers; ``tools/fleet_status.py`` folds per-host verdicts into
the fleet view; kafka-serve responses carry the request's verdict next
to ``solver_health`` and admission can shed reason ``quality_degraded``
while drift is active.  See BASELINE.md "Assimilation quality".
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from .registry import MetricsRegistry, get_registry

# ---------------------------------------------------------------------------
# Quality thresholds — the ONE sanctioned home for consistency / drift
# threshold literals (kafkalint rule 14 ``magic-quality-threshold``
# flags numeric quality-threshold literals anywhere else).  Everything
# below is overridable per-ledger/per-sentinel; these are the fleet
# defaults BASELINE.md documents.
# ---------------------------------------------------------------------------

#: consistency band on the ABSOLUTE ratio: worst band ratio above HI ->
#: OVERCONFIDENT (residuals bigger than the assumed R admits), best
#: band ratio below LO -> UNDERCONFIDENT (residuals implausibly small —
#: R inflated by an order of magnitude or more).  The band is
#: deliberately loose: the engine's chi^2 is computed on POSTERIOR
#: innovations, which sit well below 1 for strongly-informed priors
#: (the TIP problem idles near 0.05), so absolute verdicts only flag
#: gross inconsistency; the self-baselining drift sentinels below catch
#: the subtle sustained departures the band cannot.
CONSISTENT_LO = 0.01
CONSISTENT_HI = 2.5
#: sentinel baseline window: each (prefix, band) series' reference is
#: the geometric mean of its last N NON-ALARMING samples.  The first N
#: samples are pure calibration (no alarms can fire), and the window
#: keeps sliding afterwards so a smooth spin-up decay — posterior chi^2
#: starts high while the filter digests its first observations — is
#: absorbed as the series' own moving level instead of read as drift.
#: Alarming samples never enter the window: a fault cannot poison its
#: own reference.
BASELINE_WINDOW = 4
#: EWMA smoothing factor over the log-deviation-from-baseline series.
EWMA_ALPHA = 0.2
#: |EWMA of log-deviation| beyond this flags sustained departure
#: (log units: 1.5 ~= a sustained 4.5x ratio shift; decays back after
#: the cause clears).
EWMA_DRIFT_BAND = 1.5
#: two-sided CUSUM slack on the log-deviation (per-date departures
#: below ~e^0.25 ~= 1.3x are noise).  A date back within the slack
#: flushes BOTH accumulators — suspicion does not linger once the
#: series is back on baseline.
CUSUM_K = 0.25
#: CUSUM decision thresholds (log units), asymmetric by direction: an
#: UPWARD excursion (residuals exceeding what R admits — the filter is
#: shipping overtight uncertainties RIGHT NOW) alarms fast, while the
#: DOWNWARD direction (residuals shrinking — R conservatively inflated,
#: and the shape of benign spin-up decay) gets more accumulation room
#: before alarming.  No reset-after-alarm: a sustained fault keeps the
#: statistic above threshold (every armed date flags) even as the
#: filter partially absorbs the bias, and the flush-on-return rule
#: above ends the episode the first clean date.
CUSUM_H_HIGH = 2.0
CUSUM_H_LOW = 3.5
#: additive observation bias injected by the ``obs.bias`` chaos site
#: (reflectance units).  Deliberately LARGE against the synthetic
#: sigmas: the filter absorbs much of a small bias into the posterior
#: (the chi^2 rides POSTERIOR innovations), so the chaos site injects a
#: bias big enough that the un-absorbed residual still departs by an
#: order of magnitude.
OBS_BIAS_VALUE = 0.25
#: tolerance on the smoother's per-parameter sigma-shrink ratio
#: (``mean(sigma_smoothed / sigma_filter)``).  Smoothing can only add
#: information, so the ratio is <= 1 by construction (the RTS pass
#: clamps float32 roundoff); a ratio above 1 + tol means the backward
#: pass is reporting LESS certainty than the filter it conditions on —
#: a broken reanalysis, scored OVERCONFIDENT.
SMOOTH_SHRINK_TOL = 1e-3
# -- end of the sanctioned threshold block ----------------------------------

#: verdict vocabulary (severity order for :func:`worst_verdict`).
CONSISTENT = "CONSISTENT"
UNDERCONFIDENT = "UNDERCONFIDENT"
OVERCONFIDENT = "OVERCONFIDENT"
NO_OBS = "NO_OBS"
VERDICTS = (CONSISTENT, NO_OBS, UNDERCONFIDENT, OVERCONFIDENT)

#: severity: a window that is OVERCONFIDENT outranks everything (it is
#: shipping overtight uncertainties); UNDERCONFIDENT outranks a missing
#: window; NO_OBS outranks CONSISTENT only in the sense of "not known
#: good".
_SEVERITY = {CONSISTENT: 0, NO_OBS: 1, UNDERCONFIDENT: 2, OVERCONFIDENT: 3}

LEDGER_FILENAME = "quality.jsonl"
LEDGER_SCHEMA = 1

#: the obs.bias chaos fault site (resilience.faults registry).
FAULT_SITE = "obs.bias"


def _finite_ratios(chi2_per_band: Sequence[float]) -> List[Tuple[int, float]]:
    """(band, ratio) pairs carrying signal: finite and strictly positive
    (a fully-masked band reports 0 — no observations, no verdict)."""
    out = []
    for b, v in enumerate(chi2_per_band):
        v = float(v)
        if math.isfinite(v) and v > 0.0:
            out.append((b, v))
    return out


def verdict_for(chi2_per_band: Sequence[float],
                lo: float = CONSISTENT_LO,
                hi: float = CONSISTENT_HI) -> str:
    """The filter-consistency verdict for one window's per-band
    normalised chi^2 ratios (worst band wins; bands without
    observations carry no signal)."""
    ratios = _finite_ratios(chi2_per_band)
    if not ratios:
        return NO_OBS
    values = [v for _, v in ratios]
    if max(values) > hi:
        return OVERCONFIDENT
    if min(values) < lo:
        return UNDERCONFIDENT
    return CONSISTENT


def smoothed_verdict_for(sigma_shrink: Sequence[float],
                         tol: float = SMOOTH_SHRINK_TOL) -> str:
    """The reanalysis verdict for one smoothed window's per-parameter
    sigma-shrink ratios: any finite ratio above ``1 + tol`` means the
    smoothed sigma exceeds the filter's (impossible for a correct RTS
    pass) -> OVERCONFIDENT; no finite signal -> NO_OBS."""
    ratios = _finite_ratios(sigma_shrink)
    if not ratios:
        return NO_OBS
    if max(v for _, v in ratios) > 1.0 + tol:
        return OVERCONFIDENT
    return CONSISTENT


def worst_verdict(verdicts) -> Optional[str]:
    """The most severe verdict of a collection (None when empty)."""
    worst = None
    for v in verdicts:
        if v in _SEVERITY and (worst is None
                               or _SEVERITY[v] > _SEVERITY[worst]):
            worst = v
    return worst


class DriftSentinel:
    """Self-baselining EWMA + two-sided CUSUM over one chi^2-ratio
    series, in log space.

    A filter's posterior chi^2 ratio has a problem-dependent operating
    level (a tight prior idles near 0.05, a diffuse one near 1) AND a
    spin-up transient (the first dates run high while the filter
    digests its first observations), so any fixed absolute target — or
    a baseline frozen over a transient head — false-alarms on healthy
    runs.  The sentinel instead tracks each series against the
    geometric mean of its last ``window`` NON-ALARMING samples (the
    first ``window`` samples are pure calibration) and watches the
    log-deviation ``d = log(ratio) - log(baseline)``:

    - CUSUM (Page's test): ``S+ <- max(0, S+ + d - k)``,
      ``S- <- max(0, S- - d - k)``.  ``S+ > h_high`` or ``S- > h_low``
      alarms (asymmetric: upward — overconfident — is the dangerous
      direction).  No reset after an alarm: a sustained fault stays
      above threshold on every affected date even as the filter
      partially absorbs it.  A date back within the slack
      (``|d| <= k``) flushes both sides — the episode ends the first
      clean date.
    - EWMA over ``d``: ``|ewma| > ewma_band`` flags sustained moderate
      departure and decays naturally after the cause clears.

    Alarming samples never enter the baseline window, so a fault
    cannot poison its own reference; non-alarming ones slide it, so
    smooth level changes (spin-up decay) are absorbed.
    """

    def __init__(self, alpha: float = EWMA_ALPHA,
                 ewma_band: float = EWMA_DRIFT_BAND,
                 k: float = CUSUM_K,
                 h_high: float = CUSUM_H_HIGH,
                 h_low: float = CUSUM_H_LOW,
                 window: int = BASELINE_WINDOW):
        self.alpha = float(alpha)
        self.ewma_band = float(ewma_band)
        self.k = float(k)
        self.h_high = float(h_high)
        self.h_low = float(h_low)
        self.window = max(1, int(window))
        self.n = 0
        self._logs: collections.deque = collections.deque(
            maxlen=self.window
        )
        self.ewma = 0.0
        self.cusum_pos = 0.0
        self.cusum_neg = 0.0

    @property
    def baseline_log(self) -> Optional[float]:
        if not self._logs:
            return None
        return sum(self._logs) / len(self._logs)

    def update(self, ratio: float) -> dict:
        """Fold one per-date ratio in; returns the sentinel state
        (``drifting`` True when any statistic alarmed on this date)."""
        z = math.log(max(float(ratio), 1e-300))  # log-domain guard, not a threshold
        self.n += 1
        if self.n <= self.window:
            # Calibration: the first ``window`` samples seed the
            # baseline unconditionally, no alarms.
            self._logs.append(z)
            return {
                "phase": "calibrating",
                "baseline": round(math.exp(self.baseline_log), 6),
                "ewma": None, "cusum_pos": 0.0, "cusum_neg": 0.0,
                "drifting": False, "trigger": None,
            }
        baseline = self.baseline_log
        d = z - baseline
        self.ewma = self.alpha * d + (1.0 - self.alpha) * self.ewma
        if abs(d) <= self.k:
            # Back on baseline: there is no drift NOW, whatever was
            # accumulated — the episode ends on the first clean date.
            self.cusum_pos = 0.0
            self.cusum_neg = 0.0
        else:
            self.cusum_pos = max(0.0, self.cusum_pos + d - self.k)
            self.cusum_neg = max(0.0, self.cusum_neg - d - self.k)
        trigger = None
        if self.cusum_pos > self.h_high:
            trigger = "cusum_high"
        elif self.cusum_neg > self.h_low:
            trigger = "cusum_low"
        elif abs(self.ewma) > self.ewma_band:
            trigger = "ewma"
        state = {
            "phase": "armed",
            "baseline": round(math.exp(baseline), 6),
            "ewma": round(self.ewma, 6),
            "cusum_pos": round(self.cusum_pos, 6),
            "cusum_neg": round(self.cusum_neg, 6),
            "drifting": trigger is not None,
            "trigger": trigger,
        }
        if trigger is None:
            # Healthy sample: it slides the baseline window (alarming
            # ones are excluded — a fault must not poison its own
            # reference).
            self._logs.append(z)
        return state


class QualityLedger:
    """Per-process quality ledger + drift sentinels.

    One record per assimilated (or degraded) window, appended to
    ``quality.jsonl`` under ``directory`` (in-memory only when no
    telemetry directory is configured — same contract as the metrics
    registry).  Sentinel streams are keyed by ``(prefix, band)`` so a
    chunked run or a multi-tile serving daemon keeps one independent
    series per tile/chunk per band.  Thread-safe; the file is opened
    per append so long-lived daemons hold no extra handles.
    """

    MAX_RECORDS = 4096

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 directory: Optional[str] = None,
                 lo: float = CONSISTENT_LO, hi: float = CONSISTENT_HI,
                 alpha: float = EWMA_ALPHA,
                 ewma_band: float = EWMA_DRIFT_BAND,
                 k: float = CUSUM_K,
                 h_high: float = CUSUM_H_HIGH,
                 h_low: float = CUSUM_H_LOW,
                 window: int = BASELINE_WINDOW):
        self._registry = registry
        self.directory = directory
        self.path = os.path.join(directory, LEDGER_FILENAME) \
            if directory else None
        self.lo, self.hi = float(lo), float(hi)
        self._sentinel_kw = dict(alpha=alpha, ewma_band=ewma_band,
                                 k=k, h_high=h_high, h_low=h_low,
                                 window=window)
        self._lock = threading.Lock()
        self.records: collections.deque = collections.deque(
            maxlen=self.MAX_RECORDS
        )
        self._sentinels: Dict[Tuple[Optional[str], int], DriftSentinel] = {}
        self._drifting: set = set()
        self._verdict_counts: Dict[str, int] = {}
        self._last_verdict: Optional[str] = None

    def _reg(self) -> MetricsRegistry:
        return self._registry if self._registry is not None \
            else get_registry()

    # -- recording ------------------------------------------------------

    def record_window(self, date, chi2_per_band: Sequence[float],
                      n_valid: int,
                      solver_health: Optional[dict] = None,
                      prefix: Optional[str] = None,
                      fused: Optional[int] = None,
                      smoothed: bool = False) -> dict:
        """Land one assimilated window in the ledger.  ``chi2_per_band``
        is the engine's normalised per-band innovation chi^2 (already on
        the host via the packed diagnostic read — this call adds zero
        device transfers).  ``smoothed`` marks reanalysis-pass records
        (``quality_report`` scores the passes separately).  Returns the
        appended record."""
        ratios = [round(float(v), 6) for v in chi2_per_band]
        verdict = verdict_for(ratios, self.lo, self.hi)
        with self._lock:
            drift_bands: List[int] = []
            states: List[Optional[dict]] = [None] * len(ratios)
            for b, x in _finite_ratios(ratios):
                key = (prefix, b)
                sent = self._sentinels.get(key)
                if sent is None:
                    sent = self._sentinels[key] = DriftSentinel(
                        **self._sentinel_kw
                    )
                st = sent.update(x)
                states[b] = st
                if st["drifting"]:
                    drift_bands.append(b)
                    self._drifting.add(key)
                else:
                    self._drifting.discard(key)
            rec = self._append_locked({
                "schema": LEDGER_SCHEMA,
                "ts": round(time.time(), 6),
                "date": str(date),
                "prefix": prefix,
                "degraded": False,
                "chi2_per_band": ratios,
                "n_valid": int(n_valid),
                "verdict": verdict,
                "solver_health": solver_health,
                "fused": fused,
                "smoothed": bool(smoothed),
                "drift": {
                    "active": bool(drift_bands),
                    "bands": drift_bands,
                    "state": states,
                },
            })
            n_drifting = len(self._drifting)
        self._publish(rec, n_drifting)
        return rec

    def record_smoothed(self, date, sigma_shrink: Sequence[float],
                        n_valid: int,
                        prefix: Optional[str] = None) -> dict:
        """Land one REANALYSIS window: the RTS smoother's per-parameter
        sigma-shrink ratios (``mean(sigma_smoothed / sigma_filter)``,
        <= 1 for a correct pass) take the place of innovation chi^2 —
        the backward pass never touches observations, so it has no
        innovations to score.  Smoothed records never feed the drift
        sentinels (those watch the FORWARD filter's consistency)."""
        shrink = [round(float(v), 6) for v in sigma_shrink]
        with self._lock:
            rec = self._append_locked({
                "schema": LEDGER_SCHEMA,
                "ts": round(time.time(), 6),
                "date": str(date),
                "prefix": prefix,
                "degraded": False,
                "chi2_per_band": [],
                "sigma_shrink": shrink,
                "n_valid": int(n_valid),
                "verdict": smoothed_verdict_for(shrink),
                "solver_health": None,
                "fused": None,
                "smoothed": True,
                "drift": {"active": False, "bands": [], "state": []},
            })
            n_drifting = len(self._drifting)
        self._publish(rec, n_drifting)
        return rec

    def record_missing(self, date, reason: str = "degraded",
                       prefix: Optional[str] = None) -> dict:
        """Land one DEGRADED/MISSING window (a date whose read exhausted
        its retries and was assimilated as predict-only): the quality
        record keeps the hole visible instead of silently thinning the
        series the sentinels watch."""
        with self._lock:
            rec = self._append_locked({
                "schema": LEDGER_SCHEMA,
                "ts": round(time.time(), 6),
                "date": str(date),
                "prefix": prefix,
                "degraded": True,
                "reason": reason,
                "chi2_per_band": [],
                "n_valid": 0,
                "verdict": NO_OBS,
                "solver_health": None,
                "fused": None,
                "drift": {"active": False, "bands": [], "state": []},
            })
            n_drifting = len(self._drifting)
        self._publish(rec, n_drifting)
        return rec

    def _append_locked(self, rec: dict) -> dict:
        self.records.append(rec)
        self._last_verdict = rec["verdict"]
        self._verdict_counts[rec["verdict"]] = \
            self._verdict_counts.get(rec["verdict"], 0) + 1
        return rec

    def _publish(self, rec: dict, n_drifting: int) -> None:
        """Metrics + events + the JSONL append for one record (outside
        the ledger lock; the registry has its own)."""
        reg = self._reg()
        reg.counter(
            "kafka_quality_windows_total",
            "quality-ledger window records by filter-consistency "
            "verdict (normalised innovation chi^2 against the "
            "CONSISTENT_LO..HI band)",
        ).inc(verdict=rec["verdict"])
        reg.gauge(
            "kafka_quality_drift_active",
            "per-(prefix, band) chi^2-ratio series currently in a "
            "drift-sentinel alarm — nonzero means the filter's "
            "innovation statistics departed from consistency "
            "(admission can shed on it: reason quality_degraded)",
        ).set(n_drifting)
        drift = rec["drift"]
        if drift["active"]:
            c = reg.counter(
                "kafka_quality_drift_events_total",
                "drift-sentinel alarms (EWMA departure or CUSUM "
                "excursion) over per-band chi^2-ratio series",
            )
            for b in drift["bands"]:
                st = drift["state"][b] or {}
                c.inc(band=b)
                reg.emit(
                    "quality_drift", date=rec["date"],
                    prefix=rec["prefix"], band=b,
                    ratio=rec["chi2_per_band"][b],
                    trigger=st.get("trigger"),
                    ewma=st.get("ewma"),
                    cusum_pos=st.get("cusum_pos"),
                    cusum_neg=st.get("cusum_neg"),
                )
        if self.path is not None:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec, default=str) + "\n")
            except (OSError, TypeError) as exc:
                reg.counter(
                    "kafka_quality_ledger_errors_total",
                    "quality.jsonl appends that failed (disk full, "
                    "unserialisable record) — the ledger degrades, "
                    "the run survives",
                ).inc()
                reg.emit("quality_ledger_write_failed",
                         error=repr(exc)[:200])

    # -- read side ------------------------------------------------------

    def summary(self) -> dict:
        """Compact process-quality summary (the /statusz, live-snapshot
        and serve-response surface)."""
        with self._lock:
            drifting = sorted(
                f"{key[0] or '-'}:band{key[1]}" for key in self._drifting
            )
            return {
                "last_verdict": self._last_verdict,
                "windows": dict(self._verdict_counts),
                "drift_active": len(self._drifting),
                "drifting": drifting[:16],
                "records": len(self.records),
                "ledger_path": self.path,
            }


# ---------------------------------------------------------------------------
# Per-registry ledger binding: instrumented code calls ``get_ledger()``
# at record time (the registry.get_registry idiom), so test isolation
# (``telemetry.use``) and ``configure(--telemetry-dir)`` both work with
# no extra plumbing.
# ---------------------------------------------------------------------------

_ledgers: "weakref.WeakKeyDictionary[MetricsRegistry, QualityLedger]" = \
    weakref.WeakKeyDictionary()
_ledgers_lock = threading.Lock()


def get_ledger(registry: Optional[MetricsRegistry] = None) -> QualityLedger:
    """The quality ledger bound to ``registry`` (default: the process
    registry), created on first use with the registry's telemetry
    directory as the ledger home."""
    reg = registry if registry is not None else get_registry()
    with _ledgers_lock:
        led = _ledgers.get(reg)
        if led is None:
            led = _ledgers[reg] = QualityLedger(
                registry=reg, directory=reg.directory
            )
        return led


def summary(registry: Optional[MetricsRegistry] = None) -> dict:
    """The bound ledger's compact summary (see
    :meth:`QualityLedger.summary`)."""
    return get_ledger(registry).summary()


# ---------------------------------------------------------------------------
# Ledger loading (tools/quality_report.py, tests).
# ---------------------------------------------------------------------------

def load_ledger(path: str) -> Tuple[List[dict], int]:
    """Parse one ``quality.jsonl``; returns ``(records, skipped)``.
    Unparseable or non-record lines are SKIPPED, not fatal — a torn
    tail (the process died mid-append) must not take the scorecard
    down with it."""
    records: List[dict] = []
    skipped = 0
    with open(path, encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(rec, dict) or "verdict" not in rec:
                skipped += 1
                continue
            records.append(rec)
    return records, skipped


# ---------------------------------------------------------------------------
# The obs.bias chaos site.
# ---------------------------------------------------------------------------

def observation_bias(date_no: int) -> Optional[float]:
    """Host-side: the additive observation bias for fetch-order date
    number ``date_no`` (1-based) when an armed ``obs.bias`` fault spec
    matches it, else ``None`` — the disarmed path adds NOTHING to the
    fetched observation or the compiled program (the bias rides the
    traced ``y`` data, so the jitted solve is byte-identical either
    way).  The calls grammar addresses date numbers, mirroring
    ``solver.pixel``'s pixel ranges."""
    # Lazy import: resilience.faults imports the telemetry package, so
    # a top-level import here would be a cycle.
    from ..resilience import faults

    if not faults.active():
        return None
    specs = [s for s in faults.specs_for(FAULT_SITE)
             if s.matches(date_no)]
    if not specs:
        return None
    faults.record_injection(
        FAULT_SITE, date_no=date_no, bias=OBS_BIAS_VALUE,
    )
    return OBS_BIAS_VALUE
