"""Load generator for the assimilation-as-a-service daemon.

Makes the serving story measurable like the solve story: fires
concurrent tile requests at a serving target, measures per-request
submit-to-response wall time, and emits the BENCH JSON serving rows —

    serve_p50_ms / serve_p99_ms   latency percentiles over OK responses
    serve_smoothed_p50/p99_ms     same, over smoothed=true (reanalysis)
                                  requests when --smoothed mixes them in
    serve_rejected_total          requests shed at admission
    (+ serve_ok/cancelled/error/requests totals and serve_cold_ms, the
     one cold-start solve paid before the timed phase)

Two targets:

- ``--root DIR`` drives a RUNNING ``kafka-serve`` daemon over its
  filesystem inbox/responses transport (cross-process: what production
  looks like);
- ``--synthetic`` (default when no --root) builds an in-process
  ``AssimilationService`` over synthetic tiles and drives it directly —
  the self-contained mode ``bench.py`` embeds off-TPU.

Usage:
    python -m tools.loadgen --root /tmp/serve --requests 64 --concurrency 8
    python -m tools.loadgen --synthetic --requests 32

Exit codes: 0 ok, 1 when any request timed out or errored hard.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import List, Optional

import numpy as np


class _MetricsScraper:
    """Background ``/metrics`` sampler: scrapes a live telemetry
    endpoint (``kafka_tpu.telemetry.httpd``) every ``interval_s`` while
    the load runs and keeps the ``kafka_serve_*`` series as a time
    series — the BENCH JSON's ``live_telemetry`` block, so an artifact
    shows HOW the queue depth and admission counters moved under load,
    not just the final totals."""

    PREFIX = "kafka_serve_"

    def __init__(self, url: str, interval_s: float = 0.25,
                 max_samples: int = 240):
        self.url = url.rstrip("/") + "/metrics"
        self.interval_s = interval_s
        self.max_samples = max_samples
        self.samples: List[dict] = []
        self.errors = 0
        self._stop = threading.Event()
        # Client-side thread by design, like the loadgen workers: it
        # models an external Prometheus scraper, not daemon internals.
        # kafkalint: disable=untracked-thread — external-scraper model;
        # must not join the daemon's trace timeline.
        self._thread = threading.Thread(
            target=self._run, name="loadgen-scraper", daemon=True,
        )

    def scrape_once(self) -> Optional[dict]:
        import urllib.request

        from kafka_tpu.telemetry.aggregate import parse_prom_text

        try:
            with urllib.request.urlopen(self.url, timeout=2.0) as resp:
                families = parse_prom_text(
                    resp.read().decode("utf-8")
                )
        except (OSError, ValueError):
            self.errors += 1
            return None
        sample = {"t": round(time.time(), 3)}
        for name, fam in families.items():
            if not name.startswith(self.PREFIX):
                continue
            for s in fam["samples"]:
                labels = s["labels"]
                tag = name
                if labels:
                    tag += "{" + ",".join(
                        f"{k}={v}" for k, v in sorted(labels.items())
                    ) + "}"
                sample[tag] = s["value"]
        return sample

    def _run(self) -> None:
        while not self._stop.is_set():
            sample = self.scrape_once()
            if sample is not None and len(self.samples) < \
                    self.max_samples:
                self.samples.append(sample)
            self._stop.wait(self.interval_s)

    def start(self) -> "_MetricsScraper":
        self._thread.start()
        return self

    def stop(self) -> dict:
        """Stop sampling (one final scrape included) and return the
        ``live_telemetry`` block."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        final = self.scrape_once()
        if final is not None and len(self.samples) < self.max_samples:
            self.samples.append(final)
        series: dict = {}
        for sample in self.samples:
            for key, v in sample.items():
                if key == "t":
                    continue
                series.setdefault(key, []).append(v)
        return {
            "scrape_url": self.url,
            "samples": len(self.samples),
            "scrape_errors": self.errors,
            "series": series,
        }


def _percentiles(latencies_ms: List[float]) -> tuple:
    if not latencies_ms:
        return None, None
    arr = np.asarray(latencies_ms, np.float64)
    return (
        round(float(np.percentile(arr, 50)), 3),
        round(float(np.percentile(arr, 99)), 3),
    )


class _Target:
    """Uniform submit/result face over the two transports."""

    def __init__(self, root: Optional[str] = None, service=None,
                 poll_interval_s: float = 0.01):
        if (root is None) == (service is None):
            raise ValueError("exactly one of root/service")
        self.root = root
        self.service = service
        self.poll = poll_interval_s

    def submit(self, payload: dict) -> dict:
        if self.service is not None:
            return self.service.submit(payload)
        from kafka_tpu.serve import submit_request

        rid = submit_request(self.root, payload)
        return {"request_id": rid, "status": "queued"}

    def result(self, request_id: str, timeout_s: float) -> Optional[dict]:
        if self.service is not None:
            return self.service.result(request_id, timeout_s=timeout_s)
        from kafka_tpu.serve import read_response

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = read_response(self.root, request_id)
            if got is not None:
                return got
            # kafkalint: disable=ad-hoc-retry — client-side poll of a
            # cross-process filesystem response file: there is no failure
            # to classify and no backoff series, just a wait for another
            # process; a RetryPolicy would add machinery without
            # changing behaviour.
            time.sleep(self.poll)
        return None


def run_load(
    target: _Target,
    requests: List[dict],
    concurrency: int = 8,
    timeout_s: float = 300.0,
    backoff_budget: int = 0,
    backoff_cap_s: float = 5.0,
) -> dict:
    """Fire ``requests`` with ``concurrency`` client threads; returns
    the serving rows.  A rejected submission is terminal immediately
    (that IS the response — fast rejection is the overload contract) —
    UNLESS the rejection carries a ``retry_after_s`` backoff hint and
    ``backoff_budget`` > 0, in which case the client waits the hinted
    time and resubmits (each wait counted into ``serve_backoff_total``,
    at most ``backoff_budget`` waits per request) instead of hammering
    a shedding replica."""
    results = []
    health_totals: dict = {}
    backoff_total = [0]
    lock = threading.Lock()
    it = iter(list(enumerate(requests)))

    def worker():
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            i, payload = nxt
            payload = dict(payload)
            payload.setdefault("request_id", f"load{i:05d}")
            base_id = payload["request_id"]
            is_smoothed = bool(payload.get("smoothed"))
            t0 = time.perf_counter()
            backoffs = 0
            while True:
                ack = target.submit(payload)
                got = None
                if ack.get("status") != "rejected":
                    got = target.result(payload["request_id"],
                                        timeout_s=timeout_s)
                rejected = ack if ack.get("status") == "rejected" else (
                    got if got is not None
                    and got.get("status") == "rejected" else None
                )
                if rejected is not None:
                    hint = rejected.get("retry_after_s")
                    if hint and backoffs < backoff_budget:
                        backoffs += 1
                        # Fresh id per retry: in the filesystem
                        # transport a stale rejected response file must
                        # not alias the resubmission's answer.
                        payload["request_id"] = f"{base_id}b{backoffs}"
                        # kafkalint: disable=ad-hoc-retry — honouring
                        # the server's retry_after_s hint IS the backoff
                        # protocol; the wait length is the server's
                        # decision, not a client policy.
                        time.sleep(min(float(hint), backoff_cap_s))
                        continue
                    with lock:
                        backoff_total[0] += backoffs
                        results.append(
                            ("rejected", rejected.get("reason"),
                             0.0, None, None, is_smoothed)
                        )
                    break
                wall_ms = (time.perf_counter() - t0) * 1e3
                status = "timeout" if got is None \
                    else got.get("status", "?")
                health = (got or {}).get("solver_health") or {}
                # Per-request tracing attribution (ISSUE 14): the
                # server's trace block carries the named phases and
                # the server-side e2e — covered means the named spans
                # explain the request's wall time (request_log's
                # fraction bar with the absolute noise floor).
                from kafka_tpu.telemetry import request_log

                trace = (got or {}).get("trace") or {}
                server_ms = trace.get("e2e_ms")
                covered = request_log.is_covered(trace)
                with lock:
                    backoff_total[0] += backoffs
                    results.append(
                        (status, None, wall_ms, covered, server_ms,
                         is_smoothed)
                    )
                    for key, v in health.items():
                        health_totals[key] = \
                            health_totals.get(key, 0) + int(v or 0)
                break

    threads = [
        # kafkalint: disable=untracked-thread — loadgen threads are the
        # CLIENT side of the wire: they model independent external users
        # and must not join the daemon's trace timeline.
        threading.Thread(target=worker, name=f"loadgen-{k}", daemon=True)
        for k in range(max(1, concurrency))
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start
    # Forward and reanalysis latencies are DIFFERENT products under the
    # same roof: serve_p50/p99 keep meaning "forward analysis latency"
    # even when --smoothed mixes reanalysis reads into the load.
    ok_lat = [w for s, _, w, _, _, sm in results
              if s == "ok" and not sm]
    smoothed_lat = [w for s, _, w, _, _, sm in results
                    if s == "ok" and sm]
    p50, p99 = _percentiles(ok_lat)
    smoothed_p50, smoothed_p99 = _percentiles(smoothed_lat)
    count = lambda s: sum(1 for st, _, _, _, _, _ in results if st == s)
    n_ok = count("ok")
    # Tracing-coverage rows (ISSUE 14): the fraction of OK requests
    # whose named spans explain their server-side wall time, and the
    # slowest single request — the exemplar tools/trace_report.py
    # breaks down.
    covs = [c for s, _, _, c, _, _ in results if s == "ok" and
            c is not None]
    trace_coverage = (
        round(sum(1 for c in covs if c) / len(covs), 4)
        if covs else None
    )
    slowest = [sm if sm is not None else w
               for s, _, w, _, sm, _ in results if s == "ok"]
    slowest_ms = round(max(slowest), 3) if slowest else None
    return {
        "serve_p50_ms": p50,
        "serve_p99_ms": p99,
        "serve_smoothed_p50_ms": smoothed_p50,
        "serve_smoothed_p99_ms": smoothed_p99,
        "serve_smoothed_ok_total": len(smoothed_lat),
        "serve_requests_total": len(results),
        "serve_ok_total": n_ok,
        "serve_rejected_total": count("rejected"),
        "serve_cancelled_total": count("cancelled"),
        "serve_error_total": count("error") + count("timeout"),
        "serve_rps": round(n_ok / wall_s, 2) if wall_s > 0 else None,
        "serve_wall_s": round(wall_s, 3),
        # Backoff waits taken on retry_after_s rejection hints — the
        # client-side view of admission shedding under load.
        "serve_backoff_total": backoff_total[0],
        # Request-tracing rows (BASELINE.md "Request tracing"): how
        # much of the served latency the per-request traces explain,
        # and the single worst request (server-side e2e) — diffed
        # informationally by tools/bench_compare.py.
        "serve_trace_coverage": trace_coverage,
        "serve_slowest_ms": slowest_ms,
        # Result QUALITY rows, summed over answered requests from the
        # per-response solver_health blocks: latency numbers alone would
        # hide a service answering fast with quarantined pixels.
        "serve_quarantined_pixels": health_totals.get("quarantined", 0),
        "serve_cap_bailouts": health_totals.get("cap_bailouts", 0),
        "serve_damped_recovered": health_totals.get(
            "damped_recovered", 0
        ),
    }


def synthetic_request_plan(dates, tiles, n_requests: int,
                           smoothed_every: int = 0) -> List[dict]:
    """A deterministic request mix cycling tiles x dates (newest date
    most often — the interactive-traffic shape the warm path serves).
    ``smoothed_every=K`` turns every Kth request into a ``smoothed=true``
    reanalysis read of the same tile/date (0 disables)."""
    plan = []
    for i in range(n_requests):
        tile = tiles[i % len(tiles)]
        # Bias 3:1 towards the newest date; the rest walk the ladder.
        date = dates[-1] if i % 4 else dates[i % len(dates)]
        req = {"tile": tile, "date": date.isoformat()}
        if smoothed_every and i % smoothed_every == smoothed_every - 1:
            req["smoothed"] = True
        plan.append(req)
    return plan


def bench_serve(
    tmpdir: str,
    requests: int = 24,
    concurrency: int = 4,
    tiles: int = 1,
    warm: bool = True,
    smoothed_every: int = 4,
) -> dict:
    """Self-contained serving bench (the ``bench.py`` embed): build an
    in-process service over synthetic tiles, pay the cold start outside
    the timed window (reported as ``serve_cold_ms``), then measure the
    warm serving mix."""
    from kafka_tpu.serve import (
        AdmissionPolicy, AssimilationService, TileSession,
        make_synthetic_tile, synthetic_dates,
    )
    from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
    import os

    sessions = {}
    for i in range(max(1, tiles)):
        name = f"tile{i}"
        spec = make_synthetic_tile(
            name, ckpt_dir=os.path.join(tmpdir, f"ckpt_{name}"),
            seed=i,
        )
        sessions[name] = TileSession(spec)
    dates = synthetic_dates(DEFAULT_BASE_DATE, days=16, obs_every=2)
    service = AssimilationService(
        sessions, tmpdir,
        policy=AdmissionPolicy(max_queue_depth=max(64, requests + 1)),
    ).start()
    # Live observability ride-along: an ephemeral /metrics endpoint over
    # the in-process registry, scraped MID-RUN so the artifact carries a
    # live_telemetry time series next to the latency rows.
    from kafka_tpu.telemetry.httpd import TelemetryHTTPd

    httpd = TelemetryHTTPd(port=0, role="serve").start()
    scraper = None
    try:
        target = _Target(service=service)
        cold_ms = None
        if warm:
            t0 = time.perf_counter()
            rows = run_load(
                target,
                [{"tile": n, "date": dates[-1].isoformat()}
                 for n in sessions],
                concurrency=1, timeout_s=600.0,
            )
            cold_ms = round((time.perf_counter() - t0) * 1e3, 3)
            if rows["serve_ok_total"] != len(sessions):
                raise RuntimeError(f"serve warm-up failed: {rows}")
        # The default mix folds reanalysis reads in (every 4th request
        # asks smoothed=true): the warm-up above built the checkpoint
        # chain those reads answer from, so the serve_smoothed_* rows
        # measure the chain-walk+RTS path, not a cold failure.
        plan = synthetic_request_plan(
            dates[-4:], sorted(sessions), requests,
            smoothed_every=smoothed_every,
        )
        scraper = _MetricsScraper(httpd.url).start()
        # SLO ride-along (kafka_tpu.telemetry.slo): a fast-windowed
        # evaluator over the bench registry, started AFTER the cold
        # warm-up (its first sample is the measured window's baseline)
        # — the artifact carries whether the bench burned any error
        # budget next to how fast it went.
        from kafka_tpu.telemetry import slo as _slo

        engine = _slo.SLOEngine(
            fast_window_s=30.0, slow_window_s=120.0, interval_s=0.25,
        ).start()
        try:
            rows = run_load(target, plan, concurrency=concurrency,
                            timeout_s=600.0)
        finally:
            engine.stop()
        summary = engine.summary()
        remaining = [
            (o.get("budget") or {}).get("remaining")
            for o in summary["objectives"].values()
            if (o.get("budget") or {}).get("remaining") is not None
        ]
        rows["serve_slo_alerts_total"] = summary["alerts_fired"]
        rows["serve_slo_budget_remaining"] = (
            round(min(remaining), 6) if remaining else None
        )
        rows["serve_cold_ms"] = cold_ms
        rows["live_telemetry"] = scraper.stop()
        scraper = None
        return rows
    finally:
        if scraper is not None:
            scraper.stop()
        httpd.close()
        service.close()


def bench_fleet(
    tmpdir: str,
    replicas: int = 3,
    requests: int = 24,
    concurrency: int = 4,
    tiles: int = 4,
    backoff_budget: int = 4,
) -> dict:
    """Self-contained FLEET bench (the ``bench.py`` embed's elastic
    twin of :func:`bench_serve`): N in-process kafka-serve replicas
    over a SHARED checkpoint root, fronted by a consistent-hash
    ``TileRouter``, all driven through the router's filesystem
    transport — the serve_fleet_* BENCH rows measure the one serving
    surface a client of the elastic fleet actually sees."""
    import os

    from kafka_tpu.serve import (
        AdmissionPolicy, AssimilationService, ServeDaemon, TileRouter,
        TileSession, make_synthetic_tile, synthetic_dates,
    )
    from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
    from kafka_tpu.telemetry import get_registry

    ckpt_root = os.path.join(tmpdir, "ckpt")
    tile_names = [f"tile{t}" for t in range(max(1, tiles))]
    replica_roots = {}
    daemons = []
    threads = []
    for i in range(max(2, replicas)):
        root = os.path.join(tmpdir, f"rep{i}")
        sessions = {
            name: TileSession(make_synthetic_tile(
                name,
                ckpt_dir=os.path.join(ckpt_root, f"ckpt_{name}"),
                seed=t,
            ))
            for t, name in enumerate(tile_names)
        }
        svc = AssimilationService(
            sessions, root,
            policy=AdmissionPolicy(
                max_queue_depth=max(64, requests + 1)
            ),
        )
        daemons.append(ServeDaemon(svc, root, poll_interval_s=0.01))
        replica_roots[f"rep{i}"] = root
        # kafkalint: disable=untracked-thread — bench-harness carrier
        # for an in-process replica daemon; the daemon's own service
        # worker follows the tracing convention.
        threads.append(threading.Thread(
            target=daemons[-1].run, name=f"fleet-rep{i}", daemon=True,
        ))
    router_root = os.path.join(tmpdir, "router")
    router = TileRouter(replica_roots, router_root,
                        poll_interval_s=0.01)
    # kafkalint: disable=untracked-thread — bench-harness carrier for
    # the in-process router loop.
    router_thread = threading.Thread(
        target=router.run, name="fleet-router", daemon=True,
    )
    for t in threads:
        t.start()
    router_thread.start()
    dates = synthetic_dates(DEFAULT_BASE_DATE, days=16, obs_every=2)
    target = _Target(root=router_root)
    try:
        t0 = time.perf_counter()
        warm = run_load(
            target,
            [{"tile": n, "date": dates[-1].isoformat()}
             for n in tile_names],
            concurrency=2, timeout_s=600.0,
        )
        cold_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if warm["serve_ok_total"] != len(tile_names):
            raise RuntimeError(f"fleet warm-up failed: {warm}")
        plan = synthetic_request_plan(dates[-4:], tile_names, requests)
        rows = run_load(
            target, plan, concurrency=concurrency, timeout_s=600.0,
            backoff_budget=backoff_budget,
        )
        flat = get_registry().flat()
        rerouted = int(sum(
            v for k, v in flat.items()
            if k.startswith("kafka_route_rerouted_total")
        ))
        return {
            "serve_fleet_p50_ms": rows["serve_p50_ms"],
            "serve_fleet_p99_ms": rows["serve_p99_ms"],
            "serve_fleet_requests_total": rows["serve_requests_total"],
            "serve_fleet_ok_total": rows["serve_ok_total"],
            "serve_fleet_rejected_total": rows["serve_rejected_total"],
            "serve_fleet_error_total": rows["serve_error_total"],
            "serve_fleet_rps": rows["serve_rps"],
            "serve_fleet_rerouted_total": rerouted,
            "serve_fleet_replicas": len(replica_roots),
            "serve_fleet_cold_ms": cold_ms,
            "serve_backoff_total": rows["serve_backoff_total"],
            "serve_trace_coverage": rows["serve_trace_coverage"],
            "serve_slowest_ms": rows["serve_slowest_ms"],
        }
    finally:
        router.drain()
        router_thread.join(timeout=120.0)
        for d in daemons:
            d.drain()
        for t in threads:
            t.join(timeout=120.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=None,
                    help="serve root of a RUNNING kafka-serve daemon "
                         "(or kafka-route front door)")
    ap.add_argument("--synthetic", action="store_true",
                    help="self-contained in-process service (default "
                         "when --root is not given)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="self-contained ELASTIC-FLEET mode: N "
                         "in-process replicas behind a consistent-hash "
                         "router, emitting the serve_fleet_* rows")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--backoff", type=int, default=0, metavar="K",
                    help="honor retry_after_s rejection hints with up "
                         "to K backoff waits per request (counted into "
                         "serve_backoff_total)")
    ap.add_argument("--smoothed", type=int, default=0, metavar="K",
                    help="every Kth request asks for the RTS reanalysis "
                         "(smoothed=true) instead of the forward "
                         "analysis — emits the serve_smoothed_* rows "
                         "(0 disables; synthetic mode defaults to 4)")
    ap.add_argument("--tiles", default="tile0",
                    help="comma-separated tile names (--root mode)")
    ap.add_argument("--dates", default=None,
                    help="comma-separated ISO dates to request (--root "
                         "mode; default: the synthetic default ladder)")
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--scrape-url", default=None,
                    help="a running daemon's live endpoint (e.g. "
                         "http://127.0.0.1:8080 from kafka-serve "
                         "--http-port); /metrics is scraped mid-run and "
                         "embedded as the live_telemetry series "
                         "(--root mode)")
    args = ap.parse_args(argv)

    if args.root:
        from kafka_tpu.serve.synthetic import (
            DEFAULT_BASE_DATE, synthetic_dates,
        )

        if args.dates:
            import datetime

            dates = [datetime.datetime.fromisoformat(d.strip())
                     for d in args.dates.split(",") if d.strip()]
        else:
            dates = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)
        tiles = [t.strip() for t in args.tiles.split(",") if t.strip()]
        plan = synthetic_request_plan(dates, tiles, args.requests,
                                      smoothed_every=args.smoothed)
        if args.deadline_s:
            for p in plan:
                p["deadline_s"] = args.deadline_s
        scraper = _MetricsScraper(args.scrape_url).start() \
            if args.scrape_url else None
        rows = run_load(
            _Target(root=args.root), plan,
            concurrency=args.concurrency, timeout_s=args.timeout_s,
            backoff_budget=args.backoff,
        )
        if scraper is not None:
            rows["live_telemetry"] = scraper.stop()
    else:
        import tempfile
        import shutil

        tmp = tempfile.mkdtemp(prefix="kafka_loadgen_")
        try:
            if args.fleet:
                rows = bench_fleet(
                    tmp, replicas=args.fleet, requests=args.requests,
                    concurrency=args.concurrency,
                    backoff_budget=args.backoff or 4,
                )
            else:
                rows = bench_serve(
                    tmp, requests=args.requests,
                    concurrency=args.concurrency,
                    smoothed_every=args.smoothed or 4,
                )
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(rows))
    errors = rows.get("serve_error_total",
                      rows.get("serve_fleet_error_total", 0))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
