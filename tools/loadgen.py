"""Load generator for the assimilation-as-a-service daemon.

Makes the serving story measurable like the solve story: fires
concurrent tile requests at a serving target, measures per-request
submit-to-response wall time, and emits the BENCH JSON serving rows —

    serve_p50_ms / serve_p99_ms   latency percentiles over OK responses
    serve_smoothed_p50/p99_ms     same, over smoothed=true (reanalysis)
                                  requests when --smoothed mixes them in
    serve_rejected_total          requests shed at admission
    (+ serve_ok/cancelled/error/requests totals and serve_cold_ms, the
     one cold-start solve paid before the timed phase)

Two targets:

- ``--root DIR`` drives a RUNNING ``kafka-serve`` daemon over its
  filesystem inbox/responses transport (cross-process: what production
  looks like);
- ``--synthetic`` (default when no --root) builds an in-process
  ``AssimilationService`` over synthetic tiles and drives it directly —
  the self-contained mode ``bench.py`` embeds off-TPU.

Usage:
    python -m tools.loadgen --root /tmp/serve --requests 64 --concurrency 8
    python -m tools.loadgen --synthetic --requests 32

Exit codes: 0 ok, 1 when any request timed out or errored hard.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import List, Optional

import numpy as np


class _MetricsScraper:
    """Background ``/metrics`` sampler: scrapes a live telemetry
    endpoint (``kafka_tpu.telemetry.httpd``) every ``interval_s`` while
    the load runs and keeps the ``kafka_serve_*`` series as a time
    series — the BENCH JSON's ``live_telemetry`` block, so an artifact
    shows HOW the queue depth and admission counters moved under load,
    not just the final totals."""

    PREFIX = "kafka_serve_"

    def __init__(self, url: str, interval_s: float = 0.25,
                 max_samples: int = 240):
        self.url = url.rstrip("/") + "/metrics"
        self.interval_s = interval_s
        self.max_samples = max_samples
        self.samples: List[dict] = []
        self.errors = 0
        self._stop = threading.Event()
        # Client-side thread by design, like the loadgen workers: it
        # models an external Prometheus scraper, not daemon internals.
        # kafkalint: disable=untracked-thread — external-scraper model;
        # must not join the daemon's trace timeline.
        self._thread = threading.Thread(
            target=self._run, name="loadgen-scraper", daemon=True,
        )

    def scrape_once(self) -> Optional[dict]:
        import urllib.request

        from kafka_tpu.telemetry.aggregate import parse_prom_text

        try:
            with urllib.request.urlopen(self.url, timeout=2.0) as resp:
                families = parse_prom_text(
                    resp.read().decode("utf-8")
                )
        except (OSError, ValueError):
            self.errors += 1
            return None
        sample = {"t": round(time.time(), 3)}
        for name, fam in families.items():
            if not name.startswith(self.PREFIX):
                continue
            for s in fam["samples"]:
                labels = s["labels"]
                tag = name
                if labels:
                    tag += "{" + ",".join(
                        f"{k}={v}" for k, v in sorted(labels.items())
                    ) + "}"
                sample[tag] = s["value"]
        return sample

    def _run(self) -> None:
        while not self._stop.is_set():
            sample = self.scrape_once()
            if sample is not None and len(self.samples) < \
                    self.max_samples:
                self.samples.append(sample)
            self._stop.wait(self.interval_s)

    def start(self) -> "_MetricsScraper":
        self._thread.start()
        return self

    def stop(self) -> dict:
        """Stop sampling (one final scrape included) and return the
        ``live_telemetry`` block."""
        self._stop.set()
        self._thread.join(timeout=5.0)
        final = self.scrape_once()
        if final is not None and len(self.samples) < self.max_samples:
            self.samples.append(final)
        series: dict = {}
        for sample in self.samples:
            for key, v in sample.items():
                if key == "t":
                    continue
                series.setdefault(key, []).append(v)
        return {
            "scrape_url": self.url,
            "samples": len(self.samples),
            "scrape_errors": self.errors,
            "series": series,
        }


def _percentiles(latencies_ms: List[float]) -> tuple:
    if not latencies_ms:
        return None, None
    arr = np.asarray(latencies_ms, np.float64)
    return (
        round(float(np.percentile(arr, 50)), 3),
        round(float(np.percentile(arr, 99)), 3),
    )


class _Target:
    """Uniform submit/result face over the two transports."""

    def __init__(self, root: Optional[str] = None, service=None,
                 poll_interval_s: float = 0.01):
        if (root is None) == (service is None):
            raise ValueError("exactly one of root/service")
        self.root = root
        self.service = service
        self.poll = poll_interval_s

    def submit(self, payload: dict) -> dict:
        if self.service is not None:
            return self.service.submit(payload)
        from kafka_tpu.serve import submit_request

        rid = submit_request(self.root, payload)
        return {"request_id": rid, "status": "queued"}

    def result(self, request_id: str, timeout_s: float) -> Optional[dict]:
        if self.service is not None:
            return self.service.result(request_id, timeout_s=timeout_s)
        from kafka_tpu.serve import read_response

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = read_response(self.root, request_id)
            if got is not None:
                return got
            # kafkalint: disable=ad-hoc-retry — client-side poll of a
            # cross-process filesystem response file: there is no failure
            # to classify and no backoff series, just a wait for another
            # process; a RetryPolicy would add machinery without
            # changing behaviour.
            time.sleep(self.poll)
        return None


def run_load(
    target: _Target,
    requests: List[dict],
    concurrency: int = 8,
    timeout_s: float = 300.0,
    backoff_budget: int = 0,
    backoff_cap_s: float = 5.0,
) -> dict:
    """Fire ``requests`` with ``concurrency`` client threads; returns
    the serving rows.  A rejected submission is terminal immediately
    (that IS the response — fast rejection is the overload contract) —
    UNLESS the rejection carries a ``retry_after_s`` backoff hint and
    ``backoff_budget`` > 0, in which case the client waits the hinted
    time and resubmits (each wait counted into ``serve_backoff_total``,
    at most ``backoff_budget`` waits per request) instead of hammering
    a shedding replica."""
    results = []
    health_totals: dict = {}
    backoff_total = [0]
    lock = threading.Lock()
    it = iter(list(enumerate(requests)))

    def worker():
        while True:
            with lock:
                nxt = next(it, None)
            if nxt is None:
                return
            i, payload = nxt
            payload = dict(payload)
            payload.setdefault("request_id", f"load{i:05d}")
            base_id = payload["request_id"]
            is_smoothed = bool(payload.get("smoothed"))
            t0 = time.perf_counter()
            backoffs = 0
            while True:
                ack = target.submit(payload)
                got = None
                if ack.get("status") != "rejected":
                    got = target.result(payload["request_id"],
                                        timeout_s=timeout_s)
                rejected = ack if ack.get("status") == "rejected" else (
                    got if got is not None
                    and got.get("status") == "rejected" else None
                )
                if rejected is not None:
                    hint = rejected.get("retry_after_s")
                    if hint and backoffs < backoff_budget:
                        backoffs += 1
                        # Fresh id per retry: in the filesystem
                        # transport a stale rejected response file must
                        # not alias the resubmission's answer.
                        payload["request_id"] = f"{base_id}b{backoffs}"
                        # kafkalint: disable=ad-hoc-retry — honouring
                        # the server's retry_after_s hint IS the backoff
                        # protocol; the wait length is the server's
                        # decision, not a client policy.
                        time.sleep(min(float(hint), backoff_cap_s))
                        continue
                    with lock:
                        backoff_total[0] += backoffs
                        results.append(
                            ("rejected", rejected.get("reason"),
                             0.0, None, None, is_smoothed, None, None,
                             None)
                        )
                    break
                wall_ms = (time.perf_counter() - t0) * 1e3
                status = "timeout" if got is None \
                    else got.get("status", "?")
                health = (got or {}).get("solver_health") or {}
                # Per-request tracing attribution (ISSUE 14): the
                # server's trace block carries the named phases and
                # the server-side e2e — covered means the named spans
                # explain the request's wall time (request_log's
                # fraction bar with the absolute noise floor).
                from kafka_tpu.telemetry import request_log

                trace = (got or {}).get("trace") or {}
                server_ms = trace.get("e2e_ms")
                covered = request_log.is_covered(trace)
                # Coalesced-serving stamps (BASELINE.md "Coalesced
                # serving"): batch_size rides the response trace when
                # the request was admitted into a micro-batch;
                # queue_wait_ms is the phase the batching exists to
                # shrink under load.
                batch_size = trace.get("batch_size")
                queue_wait = (trace.get("phases") or {}).get(
                    "queue_wait_ms"
                )
                served_from = (got or {}).get("served_from")
                with lock:
                    backoff_total[0] += backoffs
                    results.append(
                        (status, None, wall_ms, covered, server_ms,
                         is_smoothed, batch_size, queue_wait,
                         served_from)
                    )
                    for key, v in health.items():
                        health_totals[key] = \
                            health_totals.get(key, 0) + int(v or 0)
                break

    threads = [
        # kafkalint: disable=untracked-thread — loadgen threads are the
        # CLIENT side of the wire: they model independent external users
        # and must not join the daemon's trace timeline.
        threading.Thread(target=worker, name=f"loadgen-{k}", daemon=True)
        for k in range(max(1, concurrency))
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start
    # Forward and reanalysis latencies are DIFFERENT products under the
    # same roof: serve_p50/p99 keep meaning "forward analysis latency"
    # even when --smoothed mixes reanalysis reads into the load.
    ok_lat = [w for s, _, w, _, _, sm, _, _, _ in results
              if s == "ok" and not sm]
    smoothed_lat = [w for s, _, w, _, _, sm, _, _, _ in results
                    if s == "ok" and sm]
    p50, p99 = _percentiles(ok_lat)
    smoothed_p50, smoothed_p99 = _percentiles(smoothed_lat)
    count = lambda s: sum(
        1 for st, _, _, _, _, _, _, _, _ in results if st == s
    )
    n_ok = count("ok")
    # Tracing-coverage rows (ISSUE 14): the fraction of OK requests
    # whose named spans explain their server-side wall time, and the
    # slowest single request — the exemplar tools/trace_report.py
    # breaks down.
    covs = [c for s, _, _, c, _, _, _, _, _ in results if s == "ok" and
            c is not None]
    trace_coverage = (
        round(sum(1 for c in covs if c) / len(covs), 4)
        if covs else None
    )
    slowest = [sm if sm is not None else w
               for s, _, w, _, sm, _, _, _, _ in results if s == "ok"]
    slowest_ms = round(max(slowest), 3) if slowest else None
    # Coalesced-serving rows over OK forward requests: the mean
    # admission-group size (1 for requests served alone — the mean is
    # > 1 exactly when the micro-window coalesces under this load) and
    # the queue_wait p99 the batching exists to shrink.
    sizes = [bs or 1 for s, _, _, _, _, sm, bs, _, _ in results
             if s == "ok" and not sm]
    batch_mean = (
        round(sum(sizes) / len(sizes), 3) if sizes else None
    )
    coalesced = sum(1 for v in sizes if v >= 2)
    waits = [qw for s, _, _, _, _, sm, _, qw, _ in results
             if s == "ok" and not sm and qw is not None]
    _, queue_wait_p99 = _percentiles(waits)
    # Requests that paid a device solve (cold chain build or warm
    # incremental) — the numerator of a solve-throughput rate;
    # warm_noop and cache reads move no pixels.
    solved = sum(1 for s, _, _, _, _, _, _, _, sf in results
                 if s == "ok" and sf in ("cold", "warm"))
    return {
        "serve_p50_ms": p50,
        "serve_p99_ms": p99,
        "serve_smoothed_p50_ms": smoothed_p50,
        "serve_smoothed_p99_ms": smoothed_p99,
        "serve_smoothed_ok_total": len(smoothed_lat),
        "serve_requests_total": len(results),
        "serve_ok_total": n_ok,
        "serve_rejected_total": count("rejected"),
        "serve_cancelled_total": count("cancelled"),
        "serve_error_total": count("error") + count("timeout"),
        "serve_rps": round(n_ok / wall_s, 2) if wall_s > 0 else None,
        "serve_wall_s": round(wall_s, 3),
        # Backoff waits taken on retry_after_s rejection hints — the
        # client-side view of admission shedding under load.
        "serve_backoff_total": backoff_total[0],
        # Request-tracing rows (BASELINE.md "Request tracing"): how
        # much of the served latency the per-request traces explain,
        # and the single worst request (server-side e2e) — diffed
        # informationally by tools/bench_compare.py.
        "serve_trace_coverage": trace_coverage,
        "serve_slowest_ms": slowest_ms,
        # Coalesced-serving rows (BASELINE.md "Coalesced serving").
        "serve_batch_mean_size": batch_mean,
        "serve_batch_coalesced_total": coalesced,
        "serve_queue_wait_p99_ms": queue_wait_p99,
        "serve_solved_total": solved,
        # Result QUALITY rows, summed over answered requests from the
        # per-response solver_health blocks: latency numbers alone would
        # hide a service answering fast with quarantined pixels.
        "serve_quarantined_pixels": health_totals.get("quarantined", 0),
        "serve_cap_bailouts": health_totals.get("cap_bailouts", 0),
        "serve_damped_recovered": health_totals.get(
            "damped_recovered", 0
        ),
    }


def synthetic_request_plan(dates, tiles, n_requests: int,
                           smoothed_every: int = 0) -> List[dict]:
    """A deterministic request mix cycling tiles x dates (newest date
    most often — the interactive-traffic shape the warm path serves).
    ``smoothed_every=K`` turns every Kth request into a ``smoothed=true``
    reanalysis read of the same tile/date (0 disables)."""
    plan = []
    for i in range(n_requests):
        tile = tiles[i % len(tiles)]
        # Bias 3:1 towards the newest date; the rest walk the ladder.
        date = dates[-1] if i % 4 else dates[i % len(dates)]
        req = {"tile": tile, "date": date.isoformat()}
        if smoothed_every and i % smoothed_every == smoothed_every - 1:
            req["smoothed"] = True
        plan.append(req)
    return plan


def bench_serve(
    tmpdir: str,
    requests: int = 24,
    concurrency: int = 4,
    tiles: int = 1,
    warm: bool = True,
    smoothed_every: int = 4,
) -> dict:
    """Self-contained serving bench (the ``bench.py`` embed): build an
    in-process service over synthetic tiles, pay the cold start outside
    the timed window (reported as ``serve_cold_ms``), then measure the
    warm serving mix."""
    from kafka_tpu.serve import (
        AdmissionPolicy, AssimilationService, TileSession,
        make_synthetic_tile, synthetic_dates,
    )
    from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
    import os

    sessions = {}
    for i in range(max(1, tiles)):
        name = f"tile{i}"
        spec = make_synthetic_tile(
            name, ckpt_dir=os.path.join(tmpdir, f"ckpt_{name}"),
            seed=i,
        )
        sessions[name] = TileSession(spec)
    dates = synthetic_dates(DEFAULT_BASE_DATE, days=16, obs_every=2)
    service = AssimilationService(
        sessions, tmpdir,
        policy=AdmissionPolicy(max_queue_depth=max(64, requests + 1)),
    ).start()
    # Live observability ride-along: an ephemeral /metrics endpoint over
    # the in-process registry, scraped MID-RUN so the artifact carries a
    # live_telemetry time series next to the latency rows.
    from kafka_tpu.telemetry.httpd import TelemetryHTTPd

    httpd = TelemetryHTTPd(port=0, role="serve").start()
    scraper = None
    try:
        target = _Target(service=service)
        cold_ms = None
        if warm:
            t0 = time.perf_counter()
            rows = run_load(
                target,
                [{"tile": n, "date": dates[-1].isoformat()}
                 for n in sessions],
                concurrency=1, timeout_s=600.0,
            )
            cold_ms = round((time.perf_counter() - t0) * 1e3, 3)
            if rows["serve_ok_total"] != len(sessions):
                raise RuntimeError(f"serve warm-up failed: {rows}")
        # The default mix folds reanalysis reads in (every 4th request
        # asks smoothed=true): the warm-up above built the checkpoint
        # chain those reads answer from, so the serve_smoothed_* rows
        # measure the chain-walk+RTS path, not a cold failure.
        plan = synthetic_request_plan(
            dates[-4:], sorted(sessions), requests,
            smoothed_every=smoothed_every,
        )
        scraper = _MetricsScraper(httpd.url).start()
        # SLO ride-along (kafka_tpu.telemetry.slo): a fast-windowed
        # evaluator over the bench registry, started AFTER the cold
        # warm-up (its first sample is the measured window's baseline)
        # — the artifact carries whether the bench burned any error
        # budget next to how fast it went.
        from kafka_tpu.telemetry import slo as _slo

        engine = _slo.SLOEngine(
            fast_window_s=30.0, slow_window_s=120.0, interval_s=0.25,
        ).start()
        try:
            rows = run_load(target, plan, concurrency=concurrency,
                            timeout_s=600.0)
        finally:
            engine.stop()
        summary = engine.summary()
        remaining = [
            (o.get("budget") or {}).get("remaining")
            for o in summary["objectives"].values()
            if (o.get("budget") or {}).get("remaining") is not None
        ]
        rows["serve_slo_alerts_total"] = summary["alerts_fired"]
        rows["serve_slo_budget_remaining"] = (
            round(min(remaining), 6) if remaining else None
        )
        rows["serve_cold_ms"] = cold_ms
        rows["live_telemetry"] = scraper.stop()
        scraper = None
        return rows
    finally:
        if scraper is not None:
            scraper.stop()
        httpd.close()
        service.close()


def bench_concurrency_sweep(
    tmpdir: str,
    concurrencies=(1, 8, 32),
    tiles: int = 8,
    batch_window_ms: float = 25.0,
    max_batch: int = 8,
) -> dict:
    """Coalesced-serving sweep (the ``bench.py`` embed, BASELINE.md
    "Coalesced serving"): ONE in-process service over ``tiles``
    same-bucket synthetic tiles with the admission micro-window on,
    driven at each concurrency level against a FRESH observation date
    (so every level pays real solves, not cache hits), then once more
    at the top level with the window off — the unbatched baseline from
    the very same warm sessions.

    Emits per-level rows (``serve_sweep``) plus the headline rows
    ``serve_batched_px_s`` (device launch throughput at the top
    concurrency, gated by tools/bench_compare.py),
    ``serve_batch_mean_size`` and the batched-vs-unbatched
    ``serve_queue_wait_p99_ms`` pair."""
    import os

    from kafka_tpu.serve import (
        AdmissionPolicy, AssimilationService, TileSession,
        make_synthetic_tile, synthetic_dates,
    )
    from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE

    # The AOT warm-up below only helps the live dispatch through the
    # persistent compilation cache (lower().compile() does not populate
    # the in-process jit memo): point it at this run's scratch dir,
    # with the min-compile-time floor at 0 so even fast CPU compiles
    # persist — exactly what kafka-serve does at daemon start.
    from kafka_tpu.utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache(
        cache_dir=os.path.join(tmpdir, ".xla_cache"),
        min_compile_time_s=0.0,
    )
    levels = [max(1, int(c)) for c in concurrencies]
    # One fresh GRID WINDOW per level + warm-up + the unbatched
    # baseline: consecutive observation dates can share a grid window
    # (step_days=4, obs_every=2 packs two obs per window), and serving
    # any date in a window assimilates the whole window — a level
    # whose date the previous level already covered would measure
    # warm_noop reads, not solves.  Stride past the window.
    stride = 2  # obs dates per grid window at the synthetic defaults
    n_dates_needed = stride * (len(levels) + 1) + 1
    days = 2 * (n_dates_needed + 1)
    sessions = {}
    for i in range(max(2, tiles)):
        name = f"tile{i}"
        sessions[name] = TileSession(make_synthetic_tile(
            name, ckpt_dir=os.path.join(tmpdir, f"ckpt_{name}"),
            days=days, seed=i,
        ))
    dates = synthetic_dates(DEFAULT_BASE_DATE, days=days, obs_every=2)
    names = sorted(sessions)
    service = AssimilationService(
        sessions, tmpdir,
        policy=AdmissionPolicy(max_queue_depth=4096),
        batch_window_ms=batch_window_ms, max_batch=max_batch,
    ).start()
    executor = service._executor
    try:
        # Cold start outside every timed window: build each tile's
        # chain through dates[0] (pays the compiles too).
        t0 = time.perf_counter()
        warm = run_load(
            _Target(service=service),
            [{"tile": n, "date": dates[0].isoformat(),
              "request_id": f"sweepwarm{i:03d}"}
             for i, n in enumerate(names)],
            concurrency=1, timeout_s=600.0,
        )
        cold_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if warm["serve_ok_total"] != len(names):
            raise RuntimeError(f"sweep warm-up failed: {warm}")
        # Pixels per launch member: the bucket's padded pixel count —
        # what one member of a device launch actually solves over.
        bucket = sessions[names[0]].serve_bucket()
        n_pad = bucket.n_pad if bucket is not None else None
        # AOT the batched program sizes a level can form (outside every
        # timed window, like the daemon's startup warm-up does): a
        # level whose first coalesced launch paid the K-member compile
        # would measure XLA, not serving.
        from kafka_tpu.serve import batch as serve_batch

        cap = min(len(names), max_batch)
        serve_batch.aot_compile_buckets(
            sessions, batch_sizes=tuple(range(1, cap + 1)),
        )

        def run_level(concurrency: int, date, tag: str) -> dict:
            # Explicit per-level request ids: run_load's default
            # load%05d ids REPEAT across calls, and a repeated id reads
            # the previous level's stale response file back.
            n_requests = max(concurrency, len(names))
            plan = [{"tile": names[i % len(names)],
                     "date": date.isoformat(),
                     "request_id": f"sweep{tag}n{i:04d}"}
                    for i in range(n_requests)]
            m = executor.metrics()
            launches0 = m["launches"].value() or 0
            members0 = m["launch_members"].value() or 0
            rows = run_load(_Target(service=service), plan,
                            concurrency=concurrency, timeout_s=600.0)
            launches = (m["launches"].value() or 0) - launches0
            members = (m["launch_members"].value() or 0) - members0
            wall = rows["serve_wall_s"]
            return {
                "concurrency": concurrency,
                "serve_p50_ms": rows["serve_p50_ms"],
                "serve_p99_ms": rows["serve_p99_ms"],
                "serve_queue_wait_p99_ms":
                    rows["serve_queue_wait_p99_ms"],
                "serve_batch_mean_size": rows["serve_batch_mean_size"],
                "serve_batch_coalesced_total":
                    rows["serve_batch_coalesced_total"],
                "serve_rps": rows["serve_rps"],
                "serve_ok_total": rows["serve_ok_total"],
                "serve_error_total": rows["serve_error_total"],
                # Device-level view from the executor counters (mean
                # members per coalesced launch) and the level's solve
                # throughput in padded pixels per second over requests
                # that actually paid a solve (warm_noop/cache excluded
                # — they move no pixels).
                "serve_device_batch_mean": (
                    round(members / launches, 3) if launches else None
                ),
                "serve_solved_total": rows["serve_solved_total"],
                "serve_px_s": (
                    round(rows["serve_solved_total"] * n_pad / wall, 1)
                    if n_pad and wall and wall > 0 else None
                ),
            }

        sweep = [run_level(c, dates[stride * (1 + i)], f"c{c}i{i}")
                 for i, c in enumerate(levels)]
        top = sweep[-1]
        # The unbatched baseline, SAME run, same warm sessions: window
        # off, a fresh grid window, the top concurrency again.
        service.set_batch_window(0.0)
        baseline = run_level(levels[-1], dates[stride * (1 + len(levels))],
                             "base")
        service.set_batch_window(batch_window_ms)
        errors = sum(lv["serve_error_total"] for lv in sweep) \
            + baseline["serve_error_total"]
        return {
            "serve_sweep": sweep,
            "serve_sweep_concurrencies": levels,
            "serve_cold_ms": cold_ms,
            "serve_batched_px_s": top["serve_px_s"],
            "serve_batch_mean_size": top["serve_batch_mean_size"],
            "serve_device_batch_mean": top["serve_device_batch_mean"],
            "serve_queue_wait_p99_ms": top["serve_queue_wait_p99_ms"],
            "serve_unbatched_p99_ms": baseline["serve_p99_ms"],
            "serve_unbatched_queue_wait_p99_ms":
                baseline["serve_queue_wait_p99_ms"],
            "serve_unbatched_px_s": baseline["serve_px_s"],
            "serve_error_total": errors,
        }
    finally:
        service.close()


def bench_fleet(
    tmpdir: str,
    replicas: int = 3,
    requests: int = 24,
    concurrency: int = 4,
    tiles: int = 4,
    backoff_budget: int = 4,
) -> dict:
    """Self-contained FLEET bench (the ``bench.py`` embed's elastic
    twin of :func:`bench_serve`): N in-process kafka-serve replicas
    over a SHARED checkpoint root, fronted by a consistent-hash
    ``TileRouter``, all driven through the router's filesystem
    transport — the serve_fleet_* BENCH rows measure the one serving
    surface a client of the elastic fleet actually sees."""
    import os

    from kafka_tpu.serve import (
        AdmissionPolicy, AssimilationService, ServeDaemon, TileRouter,
        TileSession, make_synthetic_tile, synthetic_dates,
    )
    from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
    from kafka_tpu.telemetry import get_registry

    ckpt_root = os.path.join(tmpdir, "ckpt")
    tile_names = [f"tile{t}" for t in range(max(1, tiles))]
    replica_roots = {}
    daemons = []
    threads = []
    for i in range(max(2, replicas)):
        root = os.path.join(tmpdir, f"rep{i}")
        sessions = {
            name: TileSession(make_synthetic_tile(
                name,
                ckpt_dir=os.path.join(ckpt_root, f"ckpt_{name}"),
                seed=t,
            ))
            for t, name in enumerate(tile_names)
        }
        svc = AssimilationService(
            sessions, root,
            policy=AdmissionPolicy(
                max_queue_depth=max(64, requests + 1)
            ),
        )
        daemons.append(ServeDaemon(svc, root, poll_interval_s=0.01))
        replica_roots[f"rep{i}"] = root
        # kafkalint: disable=untracked-thread — bench-harness carrier
        # for an in-process replica daemon; the daemon's own service
        # worker follows the tracing convention.
        threads.append(threading.Thread(
            target=daemons[-1].run, name=f"fleet-rep{i}", daemon=True,
        ))
    router_root = os.path.join(tmpdir, "router")
    router = TileRouter(replica_roots, router_root,
                        poll_interval_s=0.01)
    # kafkalint: disable=untracked-thread — bench-harness carrier for
    # the in-process router loop.
    router_thread = threading.Thread(
        target=router.run, name="fleet-router", daemon=True,
    )
    for t in threads:
        t.start()
    router_thread.start()
    dates = synthetic_dates(DEFAULT_BASE_DATE, days=16, obs_every=2)
    target = _Target(root=router_root)
    try:
        t0 = time.perf_counter()
        warm = run_load(
            target,
            [{"tile": n, "date": dates[-1].isoformat()}
             for n in tile_names],
            concurrency=2, timeout_s=600.0,
        )
        cold_ms = round((time.perf_counter() - t0) * 1e3, 3)
        if warm["serve_ok_total"] != len(tile_names):
            raise RuntimeError(f"fleet warm-up failed: {warm}")
        plan = synthetic_request_plan(dates[-4:], tile_names, requests)
        rows = run_load(
            target, plan, concurrency=concurrency, timeout_s=600.0,
            backoff_budget=backoff_budget,
        )
        flat = get_registry().flat()
        rerouted = int(sum(
            v for k, v in flat.items()
            if k.startswith("kafka_route_rerouted_total")
        ))
        return {
            "serve_fleet_p50_ms": rows["serve_p50_ms"],
            "serve_fleet_p99_ms": rows["serve_p99_ms"],
            "serve_fleet_requests_total": rows["serve_requests_total"],
            "serve_fleet_ok_total": rows["serve_ok_total"],
            "serve_fleet_rejected_total": rows["serve_rejected_total"],
            "serve_fleet_error_total": rows["serve_error_total"],
            "serve_fleet_rps": rows["serve_rps"],
            "serve_fleet_rerouted_total": rerouted,
            "serve_fleet_replicas": len(replica_roots),
            "serve_fleet_cold_ms": cold_ms,
            "serve_backoff_total": rows["serve_backoff_total"],
            "serve_trace_coverage": rows["serve_trace_coverage"],
            "serve_slowest_ms": rows["serve_slowest_ms"],
        }
    finally:
        router.drain()
        router_thread.join(timeout=120.0)
        for d in daemons:
            d.drain()
        for t in threads:
            t.join(timeout=120.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=None,
                    help="serve root of a RUNNING kafka-serve daemon "
                         "(or kafka-route front door)")
    ap.add_argument("--synthetic", action="store_true",
                    help="self-contained in-process service (default "
                         "when --root is not given)")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="self-contained ELASTIC-FLEET mode: N "
                         "in-process replicas behind a consistent-hash "
                         "router, emitting the serve_fleet_* rows")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--concurrency-sweep", default=None, metavar="LIST",
                    help="comma-separated concurrency levels (e.g. "
                         "1,8,32): run the self-contained coalesced-"
                         "serving sweep — per-level serve_p99_ms / "
                         "queue_wait / batch-size rows plus the gated "
                         "serve_batched_px_s throughput and an "
                         "unbatched same-run baseline (synthetic "
                         "mode only)")
    ap.add_argument("--backoff", type=int, default=0, metavar="K",
                    help="honor retry_after_s rejection hints with up "
                         "to K backoff waits per request (counted into "
                         "serve_backoff_total)")
    ap.add_argument("--smoothed", type=int, default=0, metavar="K",
                    help="every Kth request asks for the RTS reanalysis "
                         "(smoothed=true) instead of the forward "
                         "analysis — emits the serve_smoothed_* rows "
                         "(0 disables; synthetic mode defaults to 4)")
    ap.add_argument("--tiles", default="tile0",
                    help="comma-separated tile names (--root mode)")
    ap.add_argument("--dates", default=None,
                    help="comma-separated ISO dates to request (--root "
                         "mode; default: the synthetic default ladder)")
    ap.add_argument("--deadline-s", type=float, default=None)
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--scrape-url", default=None,
                    help="a running daemon's live endpoint (e.g. "
                         "http://127.0.0.1:8080 from kafka-serve "
                         "--http-port); /metrics is scraped mid-run and "
                         "embedded as the live_telemetry series "
                         "(--root mode)")
    args = ap.parse_args(argv)

    if args.concurrency_sweep:
        if args.root:
            print("--concurrency-sweep is self-contained (synthetic "
                  "mode); drop --root", file=sys.stderr)
            return 2
        import shutil
        import tempfile

        levels = [int(c) for c in args.concurrency_sweep.split(",")
                  if c.strip()]
        tmp = tempfile.mkdtemp(prefix="kafka_loadgen_sweep_")
        try:
            rows = bench_concurrency_sweep(tmp, concurrencies=levels)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        print(json.dumps(rows))
        return 1 if rows.get("serve_error_total") else 0

    if args.root:
        from kafka_tpu.serve.synthetic import (
            DEFAULT_BASE_DATE, synthetic_dates,
        )

        if args.dates:
            import datetime

            dates = [datetime.datetime.fromisoformat(d.strip())
                     for d in args.dates.split(",") if d.strip()]
        else:
            dates = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)
        tiles = [t.strip() for t in args.tiles.split(",") if t.strip()]
        plan = synthetic_request_plan(dates, tiles, args.requests,
                                      smoothed_every=args.smoothed)
        if args.deadline_s:
            for p in plan:
                p["deadline_s"] = args.deadline_s
        scraper = _MetricsScraper(args.scrape_url).start() \
            if args.scrape_url else None
        rows = run_load(
            _Target(root=args.root), plan,
            concurrency=args.concurrency, timeout_s=args.timeout_s,
            backoff_budget=args.backoff,
        )
        if scraper is not None:
            rows["live_telemetry"] = scraper.stop()
    else:
        import tempfile
        import shutil

        tmp = tempfile.mkdtemp(prefix="kafka_loadgen_")
        try:
            if args.fleet:
                rows = bench_fleet(
                    tmp, replicas=args.fleet, requests=args.requests,
                    concurrency=args.concurrency,
                    backoff_budget=args.backoff or 4,
                )
            else:
                rows = bench_serve(
                    tmp, requests=args.requests,
                    concurrency=args.concurrency,
                    smoothed_every=args.smoothed or 4,
                )
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(rows))
    errors = rows.get("serve_error_total",
                      rows.get("serve_fleet_error_total", 0))
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
