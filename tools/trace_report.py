"""trace_report — slow-request forensics over the per-request wide events.

Reads every ``request_log.jsonl`` under a telemetry root (both the
router's and the replicas' — ``kafka_tpu.telemetry.request_log``) and
answers the question the latency histograms cannot: *which* requests
were slow, and *where* their time went.

- **slowest-N** (``--slowest``): the worst requests by end-to-end wall
  time, each with its phase-attribution breakdown (admission_wait /
  queue_wait / resume / solve / dump on a replica; + failover / forward
  / relay through the router) and its reroute history;
- **p99 exemplars**: the latency percentiles resolved to CONCRETE
  request ids — the p99 is a real request you can open, and the
  histogram bucket it lands in lists its neighbours;
- **unattributed check** (``--unattributed``): requests whose named
  phases cover less than ``--coverage`` (default 0.95) of their wall
  time have unexplained latency — exit 1 when any are found, the
  tracing-coverage gate;
- **per-request stitch** (``--request ID --stitch OUT.json``): write
  the request's cross-process Chrome-trace waterfall (router + replica
  tracks, flow arrows across the hops) via
  ``telemetry.aggregate.stitch_traces``.

When one request left records in BOTH the router and a replica, the
router's record wins (it carries the merged end-to-end phases); the
replica record still contributes served_from/solver_health when the
router's lacks them.

Usage:
    python -m tools.trace_report /path/to/telemetry --slowest 10
    python -m tools.trace_report /path/to/telemetry --json
    python -m tools.trace_report /path/to/telemetry --unattributed
    python -m tools.trace_report /path/to/telemetry \\
        --request a1b2c3 --stitch /tmp/req.json

Exit codes: 0 report rendered, 1 ``--unattributed`` found requests
below the coverage bar, 2 usage/missing root.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

if __package__ in (None, ""):  # script mode: make kafka_tpu importable
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))

#: latency-histogram bucket bounds (ms) the exemplars are binned into —
#: the serve latency histogram's own buckets (registry.DEFAULT_BUCKETS
#: is in seconds), so a bucket here IS a bucket on /metrics.
from kafka_tpu.telemetry.registry import DEFAULT_BUCKETS  # noqa: E402
from kafka_tpu.telemetry import request_log  # noqa: E402

BUCKETS_MS = [b * 1e3 for b in DEFAULT_BUCKETS]

#: replica-side phases that the router record supersedes.
PHASE_ORDER = (
    "admission_wait_ms", "failover_ms", "forward_ms", "queue_wait_ms",
    "resume_ms", "solve_ms", "dump_ms", "relay_ms",
)


def merge_records(records: List[dict]) -> List[dict]:
    """One entry per request id: the router record (merged end-to-end
    phases) wins over the replica's; replica-only fields (served_from,
    solver_health, quality) backfill."""
    by_id: Dict[str, dict] = {}
    for rec in records:
        rid = rec["request_id"]
        cur = by_id.get(rid)
        if cur is None:
            by_id[rid] = dict(rec)
            continue
        keep, fill = (rec, cur) if rec.get("role") == "route" \
            else (cur, rec)
        merged = dict(keep)
        for key, val in fill.items():
            if merged.get(key) in (None, {}, []):
                merged[key] = val
        by_id[rid] = merged
    out = list(by_id.values())
    for rec in out:
        rec["coverage"] = request_log.attributed_fraction(rec)
    out.sort(key=lambda r: -(r.get("e2e_ms") or 0))
    return out


def _quantile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def _bucket_le(value_ms: float) -> Optional[float]:
    for le in BUCKETS_MS:
        if value_ms <= le:
            return le
    return None


def exemplars(entries: List[dict]) -> dict:
    """Latency percentiles resolved to concrete requests: for p50 and
    p99 over the OK requests, the exemplar request at that rank plus
    the histogram bucket it falls in (and that bucket's other request
    ids) — the link from a histogram spike to openable traces."""
    ok = sorted(
        (r for r in entries
         if r.get("status") == "ok"
         and isinstance(r.get("e2e_ms"), (int, float))),
        key=lambda r: r["e2e_ms"],
    )
    out: dict = {"n_ok": len(ok)}
    for q, name in ((0.5, "p50"), (0.99, "p99")):
        if not ok:
            out[name] = None
            continue
        idx = min(len(ok) - 1,
                  max(0, int(round(q * (len(ok) - 1)))))
        ex = ok[idx]
        le = _bucket_le(ex["e2e_ms"])
        bucket_ids = [
            r["request_id"] for r in ok
            if _bucket_le(r["e2e_ms"]) == le
        ]
        out[name] = {
            "value_ms": round(ex["e2e_ms"], 3),
            "request_id": ex["request_id"],
            "tile": ex.get("tile"),
            "served_from": ex.get("served_from"),
            "bucket_le_ms": le,
            "bucket_request_ids": bucket_ids[:5],
        }
    return out


def _phase_line(rec: dict) -> str:
    phases = rec.get("phases") or {}
    e2e = rec.get("e2e_ms") or 0
    parts = []
    for key in PHASE_ORDER:
        v = phases.get(key)
        if not isinstance(v, (int, float)) or v <= 0:
            continue
        pct = f" {100 * v / e2e:.0f}%" if e2e else ""
        parts.append(f"{key[:-3]}={v:.1f}ms{pct}")
    for key in sorted(set(phases) - set(PHASE_ORDER)):
        v = phases[key]
        if isinstance(v, (int, float)) and v > 0:
            parts.append(f"{key[:-3]}={v:.1f}ms")
    return "  ".join(parts) or "(no phases recorded)"


def render(entries: List[dict], slowest: int, torn: int,
           coverage_target: float) -> str:
    by_status: Dict[str, int] = {}
    for r in entries:
        by_status[r.get("status", "?")] = \
            by_status.get(r.get("status", "?"), 0) + 1
    lines = [
        f"trace_report: {len(entries)} request(s) "
        + " ".join(f"{k}={v}" for k, v in sorted(by_status.items()))
        + (f"  (skipped {torn} torn line(s))" if torn else ""),
    ]
    ex = exemplars(entries)
    for name in ("p50", "p99"):
        e = ex.get(name)
        if e:
            lines.append(
                f"{name}: {e['value_ms']:.1f}ms — request "
                f"{e['request_id']} (tile={e['tile']}, "
                f"served_from={e['served_from']}, "
                f"bucket le={e['bucket_le_ms']}ms: "
                f"{','.join(e['bucket_request_ids'])})"
            )
    lines.append(f"slowest {min(slowest, len(entries))}:")
    for rec in entries[:slowest]:
        cov = rec.get("coverage")
        cov_txt = "-" if cov is None else f"{100 * cov:.1f}%"
        flag = "  UNATTRIBUTED" if request_log.is_covered(
            rec, target=coverage_target) is False else ""
        e2e = rec.get("e2e_ms")
        lines.append(
            f"  {rec['request_id']} [{rec.get('role')}] "
            f"{rec.get('status')}"
            + (f" {rec['served_from']}" if rec.get("served_from")
               else "")
            + (f" tile={rec['tile']}" if rec.get("tile") else "")
            + (f" replica={rec['replica']}" if rec.get("replica")
               else "")
            + (f"  e2e={e2e:.1f}ms" if isinstance(e2e, (int, float))
               else "")
            + f"  attributed={cov_txt}{flag}"
        )
        lines.append(f"    {_phase_line(rec)}")
        for hop in rec.get("reroutes") or ():
            lines.append(
                f"    reroute: {hop.get('replica')} "
                f"({hop.get('reason')}, held "
                f"{hop.get('held_ms', 0):.0f}ms)"
            )
    return "\n".join(lines)


def build_report(root: str, slowest: int = 10,
                 coverage_target: float = request_log.COVERAGE_TARGET,
                 ) -> dict:
    """The ``--json`` payload, importable for tests and other tools."""
    records, torn = request_log.load_records(root)
    entries = merge_records(records)
    unattributed = [
        {"request_id": r["request_id"], "role": r.get("role"),
         "e2e_ms": r.get("e2e_ms"),
         "coverage": None if r.get("coverage") is None
         else round(r["coverage"], 4)}
        for r in entries
        if request_log.is_covered(r, target=coverage_target) is False
    ]
    covered = [r for r in entries if r.get("coverage") is not None]
    by_status: Dict[str, int] = {}
    for r in entries:
        by_status[r.get("status", "?")] = \
            by_status.get(r.get("status", "?"), 0) + 1
    return {
        "root": os.path.abspath(root),
        "requests_total": len(entries),
        "by_status": by_status,
        "torn_lines": torn,
        "coverage_target": coverage_target,
        "coverage_ok_fraction": (
            round(sum(1 for r in covered
                      if request_log.is_covered(
                          r, target=coverage_target))
                  / len(covered), 4) if covered else None
        ),
        "unattributed": unattributed,
        "exemplars": exemplars(entries),
        "slowest": [
            {k: rec.get(k) for k in (
                "request_id", "role", "status", "tile", "date",
                "served_from", "replica", "e2e_ms", "phases",
                "coverage", "reroutes", "replayed",
                "solver_health", "quality",
            ) if rec.get(k) is not None}
            for rec in entries[:slowest]
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("root", help="telemetry root holding "
                                 "request_log.jsonl files (searched "
                                 "recursively)")
    ap.add_argument("--slowest", type=int, default=10, metavar="N",
                    help="how many worst-latency requests to break "
                         "down (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report instead of the "
                         "summary")
    ap.add_argument("--unattributed", action="store_true",
                    help="coverage check: exit 1 when any request's "
                         "named phases attribute less than --coverage "
                         "of its wall time")
    ap.add_argument("--coverage", type=float,
                    default=request_log.COVERAGE_TARGET,
                    help="attribution bar for --unattributed "
                         "(default 0.95)")
    ap.add_argument("--request", default=None, metavar="ID",
                    help="report only this request id")
    ap.add_argument("--stitch", default=None, metavar="OUT",
                    help="with --request: write the request's stitched "
                         "cross-process Chrome trace to OUT")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"trace_report: no such directory: {args.root}",
              file=sys.stderr)
        return 2
    if args.stitch and not args.request:
        print("trace_report: --stitch needs --request",
              file=sys.stderr)
        return 2
    report = build_report(args.root, slowest=args.slowest,
                          coverage_target=args.coverage)
    entries = merge_records(request_log.load_records(args.root)[0])
    if args.request:
        entries = [r for r in entries
                   if r["request_id"] == args.request]
        if not entries:
            print(f"trace_report: no record of request "
                  f"{args.request!r} under {args.root}",
                  file=sys.stderr)
            return 2
        report["slowest"] = [
            {k: rec.get(k) for k in (
                "request_id", "role", "status", "tile", "date",
                "served_from", "replica", "e2e_ms", "phases",
                "coverage", "reroutes", "replayed",
                "solver_health", "quality",
            ) if rec.get(k) is not None}
            for rec in entries
        ]
    if args.stitch:
        from kafka_tpu.telemetry.aggregate import stitch_traces

        doc = stitch_traces(args.root, request_id=args.request)
        with open(args.stitch, "w") as f:
            json.dump(doc, f)
        report["stitched_trace"] = {
            "path": os.path.abspath(args.stitch),
            "sources": doc["otherData"]["sources"],
            "events": len(doc["traceEvents"]),
        }
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(entries, args.slowest, report["torn_lines"],
                     args.coverage))
        if report.get("stitched_trace"):
            st = report["stitched_trace"]
            print(f"stitched trace: {st['path']} "
                  f"({len(st['sources'])} process track(s), "
                  f"{st['events']} events)")
    if args.unattributed and report["unattributed"]:
        print(
            f"trace_report: {len(report['unattributed'])} request(s) "
            f"below the {args.coverage:.0%} attribution bar",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
