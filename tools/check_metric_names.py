"""Static telemetry-name lint: metrics, event names, span phases.

Greps every ``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
registration in the production tree (``kafka_tpu/`` + ``bench.py``) and
fails on:

- a name not matching the documented ``kafka_<subsystem>_<name>``
  convention (BASELINE.md "Observability");
- the same name registered at more than one source location (each metric
  has exactly ONE owner — duplicated literals drift apart silently);
- the same name registered as two different kinds.

It also lints the ``emit("...")`` event names and ``span("...")`` phase
names (the JSONL event log and the trace timeline share these
vocabularies with dashboards and the crash dumps):

- names must be lower_snake_case (``^[a-z][a-z0-9_]*$``) — off-convention
  casing silently forks a grep/dashboard query;
- two DIFFERENT literals that normalise to the same name (case or
  underscore variants, e.g. ``chunk_done`` vs ``chunkDone``) are
  near-duplicates that would split one logical event across two names;
- one name used as BOTH an event kind and a span phase is flagged — one
  name, one meaning.  (The same literal at several sites is fine: e.g.
  ``run_done`` is legitimately emitted by each driver.)

Wired into tier-1 as ``tests/test_metric_names.py``, so a telemetry
regression breaks the suite instead of the dashboard.

Usage:
    python tools/check_metric_names.py [repo_root]
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List, Tuple

#: registration call with a literal first argument.
REGISTRATION_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE
)
NAME_RE = re.compile(r"^kafka_[a-z0-9]+_[a-z0-9_]+$")

#: emit("...") event and span("...") phase call sites with a literal
#: first argument (the lookbehind keeps trace_span()/add_span() out of
#: the span scan — those carry arbitrary span names, not engine phases).
EMIT_RE = re.compile(r"\.\s*emit\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE)
SPAN_RE = re.compile(r"(?<!\w)span\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE)
EVENT_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: production sources scanned for registrations, relative to the root.
SCAN = ("kafka_tpu", "bench.py")


def iter_sources(root: str):
    for entry in SCAN:
        path = os.path.join(root, entry)
        if os.path.isfile(path):
            yield path
        else:
            for dirpath, _dirnames, filenames in os.walk(path):
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def collect_registrations(
    root: str,
) -> Dict[str, List[Tuple[str, int, str]]]:
    """name -> [(relative_path, line, kind), ...] over the scanned tree."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}
    for path in iter_sources(root):
        with open(path) as f:
            text = f.read()
        for m in REGISTRATION_RE.finditer(text):
            kind, name = m.group(1), m.group(2)
            line = text.count("\n", 0, m.start()) + 1
            rel = os.path.relpath(path, root)
            out.setdefault(name, []).append((rel, line, kind))
    return out


def collect_names(root: str, regex: re.Pattern,
                  ) -> Dict[str, List[Tuple[str, int]]]:
    """literal first-arg -> [(relative_path, line), ...] for ``regex``."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for path in iter_sources(root):
        with open(path) as f:
            text = f.read()
        for m in regex.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            rel = os.path.relpath(path, root)
            out.setdefault(m.group(1), []).append((rel, line))
    return out


def check_event_and_phase_names(root: str) -> List[str]:
    """emit()/span() vocabulary violations (empty list = clean)."""
    errors: List[str] = []
    events = collect_names(root, EMIT_RE)
    phases = collect_names(root, SPAN_RE)
    #: normalised form -> {(namespace, literal): sites}
    by_norm: Dict[str, Dict[Tuple[str, str], List[Tuple[str, int]]]] = {}
    for namespace, names in (("event", events), ("phase", phases)):
        for name, sites in names.items():
            where = ", ".join(f"{p}:{ln}" for p, ln in sites)
            if not EVENT_NAME_RE.match(name):
                errors.append(
                    f"{namespace} name {name!r} ({where}) is not "
                    "lower_snake_case"
                )
            norm = name.replace("_", "").lower()
            by_norm.setdefault(norm, {})[(namespace, name)] = sites
    for norm, variants in sorted(by_norm.items()):
        literals = {name for _, name in variants}
        namespaces = {ns for ns, _ in variants}
        where = "; ".join(
            f"{ns} {name!r} at " + ", ".join(f"{p}:{ln}" for p, ln in sites)
            for (ns, name), sites in sorted(variants.items())
        )
        if len(literals) > 1:
            errors.append(
                f"near-duplicate names {sorted(literals)} ({where}) — "
                "case/underscore variants of one name"
            )
        elif len(namespaces) > 1:
            errors.append(
                f"{next(iter(literals))!r} used as both an event and a "
                f"span phase ({where}) — one name, one meaning"
            )
    return errors


def check(root: str) -> List[str]:
    """All convention violations in ``root`` (empty list = clean)."""
    errors: List[str] = []
    regs = collect_registrations(root)
    if not regs:
        errors.append(
            f"no metric registrations found under {root!r} — the scanner "
            "or the telemetry wiring is broken"
        )
    for name, sites in sorted(regs.items()):
        where = ", ".join(f"{p}:{ln}" for p, ln, _ in sites)
        if not NAME_RE.match(name):
            errors.append(
                f"{name!r} ({where}) does not match "
                "kafka_<subsystem>_<name>"
            )
        if len(sites) > 1:
            errors.append(
                f"{name!r} registered at {len(sites)} sites ({where}); "
                "each metric must have exactly one owner"
            )
        kinds = {k for _, _, k in sites}
        if len(kinds) > 1:
            errors.append(
                f"{name!r} registered as multiple kinds "
                f"({sorted(kinds)}; {where})"
            )
    errors.extend(check_event_and_phase_names(root))
    return errors


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    errors = check(root)
    regs = collect_registrations(root)
    if errors:
        for e in errors:
            print(f"check_metric_names: {e}", file=sys.stderr)
        return 1
    events = collect_names(root, EMIT_RE)
    phases = collect_names(root, SPAN_RE)
    print(
        f"check_metric_names: {len(regs)} metric names OK "
        f"({sum(len(s) for s in regs.values())} registrations), "
        f"{len(events)} event names, {len(phases)} span phases"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
