"""Static telemetry-name lint — compatibility shim over tools.kafkalint.

The implementation moved into the kafkalint framework
(``tools/kafkalint/rules_telemetry.py``), where the same three checks run
as the ``metric-name`` / ``event-name`` / ``event-collision`` rules with
shared suppression syntax and output.  This shim keeps the original CLI,
exit codes, and module API (``check``, ``collect_registrations``,
``collect_names``, the regexes) exactly as before, so existing callers —
``tests/test_metric_names.py`` in particular — work unchanged.

Usage:
    python tools/check_metric_names.py [repo_root]
"""

from __future__ import annotations

import os
import sys

#: this file is loaded by path (importlib spec / direct execution), so
#: make the repo root importable before reaching for the package.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.kafkalint.rules_telemetry import (  # noqa: E402,F401
    EMIT_RE,
    EVENT_NAME_RE,
    NAME_RE,
    REGISTRATION_RE,
    SCAN,
    SPAN_RE,
    check,
    check_event_and_phase_names,
    collect_names,
    collect_registrations,
    iter_sources,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
