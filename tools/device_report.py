"""device_report — the device-plane view over a profiler capture dir.

``/kernelz`` answers for a LIVE process; this tool answers for the
artifacts a run left behind.  Point it at a telemetry root (or straight
at a capture/session dir) and it parses the NEWEST profiler capture
session (``telemetry.devprof`` — stdlib gzip+json over the
``*.trace.json.gz`` Chrome traces jax.profiler writes) into:

- the ranked kernel table (slowest first) with
  fusion/collective/transfer/other buckets and per-kernel share of
  total device time;
- the bucket split and the collective-time fraction — the mesh-balance
  red flag a scaled-out run is watched for;
- the per-device-track share of device time (skew reads as unequal
  fractions).

``--mesh-history MULTICHIP_r01.json ...`` additionally renders the
archived multichip round artifacts (loaded through
``bench_history.unwrap_artifact``, so wrapped harness archives and the
bare checked-in dicts both work) as a mesh trajectory: devices, verdict
and the result line per round.

Usage:
    python -m tools.device_report TELEMETRY_DIR [--json] [--n 16]
        [--all-sessions] [--mesh-history MULTICHIP_r*.json ...]

Exit codes: 0 (report rendered), 2 usage / nothing parseable and no
mesh history given.  Strictly read-only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

from kafka_tpu.telemetry import devprof  # noqa: E402
from tools.bench_history import load_artifact  # noqa: E402


def build_report(root: str, n: int = 16,
                 all_sessions: bool = False) -> dict:
    """The ``--json`` payload: per-session parse results (newest only
    unless ``all_sessions``), plus the root-level session census."""
    sessions = devprof.find_capture_sessions(root)
    picked = sessions if all_sessions else sessions[-1:]
    parsed = []
    for session in picked:
        table = devprof.parse_capture(session)
        if table is None:
            parsed.append({
                "session_dir": session, "parseable": False,
            })
            continue
        parsed.append({
            "session_dir": session,
            "parseable": True,
            "epoch_unix_s": devprof.capture_epoch(session, stop_at=root),
            "device_ms": table["device_ms"],
            "by_bucket": table["by_bucket"],
            "collective_fraction": table["collective_fraction"],
            "device_split": table["device_split"],
            "parse_errors": table["parse_errors"],
            "truncated_ms": table["truncated_ms"],
            "kernels": table["kernels"][:max(0, n)],
        })
    return {
        "root": os.path.abspath(root),
        "n_sessions": len(sessions),
        "sessions": parsed,
    }


def mesh_history(paths) -> list:
    """Archived multichip rounds (``MULTICHIP_r*.json``) as one row per
    artifact — wrapped or bare, via ``bench_history.unwrap_artifact``."""
    rows = []
    for path in paths:
        art = load_artifact(path)
        if art is None:
            continue
        tail = (art.get("tail") or "").strip().splitlines()
        rows.append({
            "name": os.path.basename(path),
            "n_devices": art.get("n_devices"),
            "ok": art.get("ok"),
            "skipped": art.get("skipped"),
            "rc": art.get("rc"),
            "result": tail[-1] if tail else None,
        })
    return rows


def render(report: dict, history: list) -> str:
    lines = [
        f"device_report: {report['n_sessions']} capture session(s) "
        f"under {report['root']}",
    ]
    for s in report["sessions"]:
        rel = os.path.relpath(s["session_dir"], report["root"])
        if not s["parseable"]:
            lines.append(f"  {rel}: NOT PARSEABLE (no device-lane "
                         "kernel spans)")
            continue
        cf = s["collective_fraction"]
        lines.append(
            f"  {rel}: device {s['device_ms']:.3f}ms"
            + (f", collective {cf:.1%}" if cf is not None else "")
            + (f", {s['parse_errors']} file parse error(s)"
               if s["parse_errors"] else "")
        )
        for b, ms in s["by_bucket"].items():
            lines.append(f"    bucket {b:<10s} {ms:10.3f}ms")
        lines.append("    slowest kernels:")
        for k in s["kernels"]:
            lines.append(
                f"      {k['ms']:10.3f}ms {k['fraction']:6.1%} "
                f"[{k['bucket']:10s}] x{k['count']} {k['name']}"
            )
        if s["truncated_ms"]:
            lines.append(
                f"      ... long tail: {s['truncated_ms']:.3f}ms beyond "
                "the table"
            )
        for track, frac in sorted((s["device_split"] or {}).items()):
            lines.append(f"    time {track}: {frac:.1%}")
    if not report["sessions"]:
        lines.append("  (no capture sessions found — trigger one via "
                     "/profilez or --profile-windows)")
    if history:
        lines.append("mesh history (multichip rounds, oldest -> newest):")
        for r in history:
            verdict = ("skipped" if r["skipped"]
                       else "ok" if r["ok"] else "FAILED")
            lines.append(
                f"  {r['name']}: {r['n_devices']} device(s) [{verdict}]"
                + (f" {r['result']}" if r["result"] else "")
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("root", nargs="?", default=None,
                    help="telemetry root / capture dir to scan for "
                         "profiler sessions (optional with "
                         "--mesh-history)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report instead of the table")
    ap.add_argument("--n", type=int, default=16,
                    help="kernel-table rows per session (default 16)")
    ap.add_argument("--all-sessions", action="store_true",
                    help="parse every session under the root, not just "
                         "the newest")
    ap.add_argument("--mesh-history", nargs="+", default=(),
                    metavar="ART",
                    help="archived MULTICHIP_r*.json round artifacts to "
                         "render as a mesh trajectory (wrapped or bare)")
    args = ap.parse_args(argv)
    if args.root is None and not args.mesh_history:
        print("device_report: give a capture root and/or --mesh-history",
              file=sys.stderr)
        return 2
    report = {"root": None, "n_sessions": 0, "sessions": []}
    if args.root is not None:
        if not os.path.isdir(args.root):
            print(f"device_report: no such directory: {args.root}",
                  file=sys.stderr)
            return 2
        report = build_report(args.root, n=args.n,
                              all_sessions=args.all_sessions)
    history = mesh_history(args.mesh_history)
    if not report["sessions"] and not history and args.mesh_history:
        print("device_report: no loadable artifacts", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps({**report, "mesh_history": history},
                         indent=2, sort_keys=True))
    else:
        print(render(report, history))
    return 0


if __name__ == "__main__":
    sys.exit(main())
