"""Request-identity rule: one sanctioned request-id origin.

``request-id-origin`` (ISSUE 14) encodes the request-tracing
convention: a request id is the PER-REQUEST TRACE KEY — minted once at
admission by ``kafka_tpu/serve/request.py``'s ``new_request_id`` and
then propagated verbatim on the filesystem wire (request payloads,
journal entries, response bodies, spans).  A second minting site
anywhere in ``serve/`` forks the trace: the router's spans and the
replica's spans would carry different ids for the same request, the
journal replay would start a fresh waterfall instead of continuing the
recorded one, and ``stitch_traces(request_id=...)`` would silently
show half a request.

The rule flags, in ``kafka_tpu/serve/`` outside the sanctioned origin
module:

- any call of the id-entropy primitives — ``uuid.*``, ``os.urandom``,
  ``secrets.token_hex`` / ``token_urlsafe`` / ``token_bytes``;
- direct literal construction of a request id: a ``request_id=``
  keyword, a ``"request_id"`` dict key or a ``[...]["request_id"]``
  assignment whose value is a string literal, an f-string or a string
  concatenation — ids must FLOW (``req.request_id``), never be built.

``kafka_tpu/serve/request.py`` is exempt (it IS the origin).  Entropy
elsewhere in the repo (chunk prefixes, run ids) is out of scope: the
rule guards request identity, not randomness.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: the tree where request identity lives.
SCOPES = ("kafka_tpu/serve/",)

#: the one sanctioned origin module.
SANCTIONED = ("kafka_tpu/serve/request.py",)

#: dotted call targets that mint identity entropy.
MINT_CALLS = {
    "os.urandom",
    "secrets.token_hex", "secrets.token_urlsafe",
    "secrets.token_bytes",
}


def _dotted(node) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_literal_construction(node) -> bool:
    """A string literal, f-string, or string concatenation — an id
    BUILT in place rather than flowed from the origin."""
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        return _is_literal_construction(node.left) or \
            _is_literal_construction(node.right)
    return False


@register
class RequestIdOrigin(Rule):
    name = "request-id-origin"
    description = (
        "request id minted (uuid/os.urandom/token_hex) or built from "
        "literals in serve/ outside serve/request.py — a request id "
        "is the per-request trace key; duplicate origins fork traces. "
        "Use serve.request.new_request_id and let ids flow"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or \
                not any(ctx.rel.startswith(s) for s in SCOPES) or \
                ctx.rel in SANCTIONED:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in MINT_CALLS or dotted == "uuid" or \
                        dotted.startswith("uuid."):
                    findings.append(Finding(
                        path=ctx.rel, line=node.lineno, rule=self.name,
                        message=(
                            f"{dotted}() mints id entropy in serve/ — "
                            "request ids have ONE origin "
                            "(serve.request.new_request_id); a second "
                            "minting site forks the per-request trace"
                        ),
                    ))
                for kw in node.keywords:
                    if kw.arg == "request_id" and \
                            _is_literal_construction(kw.value):
                        findings.append(self._built(ctx, kw.value))
            elif isinstance(node, ast.Dict):
                for key, val in zip(node.keys, node.values):
                    if isinstance(key, ast.Constant) and \
                            key.value == "request_id" and \
                            _is_literal_construction(val):
                        findings.append(self._built(ctx, val))
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript) and \
                            isinstance(tgt.slice, ast.Constant) and \
                            tgt.slice.value == "request_id" and \
                            _is_literal_construction(node.value):
                        findings.append(self._built(ctx, node.value))
        return findings

    def _built(self, ctx: FileContext, node) -> Finding:
        return Finding(
            path=ctx.rel, line=node.lineno, rule=self.name,
            message=(
                "request_id built from literals — ids must flow from "
                "the admission-time origin (req.request_id), never be "
                "constructed in place: a rebuilt id detaches the "
                "request from its trace and its journal entry"
            ),
        )
