"""JAX/TPU hazard rules: host transfers in jit, implicit f64, static flags.

These encode the engine's device-path conventions (BASELINE.md "Static
analysis"): exactly one device->host read per window means NO hidden
transfer may hide inside a jitted/scanned body; the 2e-3 fused-parity
tolerance story holds only while device math stays float32; bool/str
arguments of jitted functions must be static or every flag flip retraces.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register
from . import jitscan

#: packages whose modules hold device code (traced math).  Everything
#: else — io readers, cli drivers, telemetry, testing, tools — is host
#: side, where float64 is often *correct* (geolocation, emulator import).
DEVICE_PREFIXES = (
    "kafka_tpu/core/",
    "kafka_tpu/shard/",
    "kafka_tpu/obsops/",
    "kafka_tpu/engine/",
)
DEVICE_FILES = ("bench.py",)

#: host-side modules inside device packages: f64 is deliberate there.
HOST_ALLOWLIST = {
    # Emulator import: K can be ill-conditioned, the solve is f64 on host
    # and the bank is cast to f32 at the end (obsops/gp_import.py).
    "kafka_tpu/obsops/gp_import.py",
    # Published-spectra anchor tables, band-averaged once at import by
    # plain numpy; never traced.
    "kafka_tpu/obsops/prospect_data.py",
    # Geolocation/warp math is host-side numpy where f64 precision is the
    # point (sub-pixel UTM/sinusoidal transforms).
    "kafka_tpu/io/warp.py",
}


def is_device_module(rel: str) -> bool:
    if rel in HOST_ALLOWLIST:
        return False
    return rel in DEVICE_FILES or rel.startswith(DEVICE_PREFIXES)


def _shielded(node: ast.AST, traced: set) -> bool:
    """True when ``node`` reads no traced value: constants, or names only
    reached through static accessors (``.shape``/``.ndim``/``.dtype``/
    ``len()``) that trace-time Python evaluates to plain ints."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape", "ndim", "dtype", "size"):
            continue
        if isinstance(sub, ast.Name) and sub.id in traced:
            if not _under_static_accessor(node, sub):
                return False
    return True


def _under_static_accessor(root: ast.AST, target: ast.Name) -> bool:
    """Is ``target`` only reachable through a .shape/.ndim/.dtype
    attribute or a len() call within ``root``?"""

    class V(ast.NodeVisitor):
        found_bare = False

        def visit_Attribute(self, node: ast.Attribute) -> None:
            if node.attr in ("shape", "ndim", "dtype", "size"):
                return  # static at trace time; don't descend
            self.generic_visit(node)

        def visit_Call(self, node: ast.Call) -> None:
            if isinstance(node.func, ast.Name) and node.func.id == "len":
                return
            self.generic_visit(node)

        def visit_Name(self, node: ast.Name) -> None:
            if node is target:
                self.found_bare = True

    v = V()
    v.visit(root)
    return not v.found_bare


@register
class HostTransferInJit(Rule):
    name = "host-transfer-in-jit"
    description = (
        "np.* calls, float()/int()/.item() on traced values, and "
        "device_get inside jitted/pallas/lax-control-flow bodies — each "
        "is a hidden device->host transfer (or a silent constant fold) "
        "that breaks the one-read-per-window budget"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        entries = jitscan.jit_entries(ctx.tree)
        if not entries:
            return ()
        np_names = jitscan.numpy_aliases(ctx.tree)
        findings: List[Finding] = []
        seen_lines = set()

        def flag(node: ast.AST, what: str, region: str) -> None:
            key = (node.lineno, what)
            if key in seen_lines:
                return
            seen_lines.add(key)
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"{what} inside jit region '{region}' — a hidden "
                    "device->host transfer (or silent constant fold); "
                    "keep traced math in jnp and hoist host work out of "
                    "the jitted/scanned body"
                ),
            ))

        for entry in entries:
            traced = jitscan.region_locals(entry.func)
            body = entry.func.body
            stmts = body if isinstance(body, list) else [body]
            for stmt in stmts:
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    t = jitscan.tail(f)
                    if (isinstance(f, ast.Attribute)
                            and isinstance(f.value, ast.Name)
                            and f.value.id in np_names):
                        flag(node, f"{f.value.id}.{f.attr}()", entry.name)
                    elif (isinstance(f, ast.Name)
                          and f.id in ("float", "int")
                          and node.args
                          and not _shielded(node.args[0], traced)):
                        flag(node, f"{f.id}() on a traced value",
                             entry.name)
                    elif isinstance(f, ast.Attribute) and f.attr == "item":
                        flag(node, ".item()", entry.name)
                    elif t == "device_get":
                        flag(node, "device_get()", entry.name)
        return findings


@register
class ImplicitF64(Rule):
    name = "implicit-f64"
    description = (
        "float64 dtypes (np.float64/jnp.float64/'float64') and dtype-less "
        "jnp.asarray of Python float literals in device-code modules — "
        "device math is float32-only (the 2e-3 fused-parity budget); "
        "host-side modules (io/warp.py, obsops/gp_import.py, ...) are "
        "allowlisted because f64 is correct there"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not is_device_module(ctx.rel):
            return ()
        jnp_names = jitscan.jnp_aliases(ctx.tree)
        findings: List[Finding] = []

        def flag(node: ast.AST, msg: str) -> None:
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=msg,
            ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                base = jitscan.dotted(node.value) or "?"
                flag(node, (
                    f"{base}.float64 in a device-code module — device "
                    "math is float32-only; compute in f32 or move this "
                    "to a host-side module (allowlisted in "
                    "tools/kafkalint/rules_jax.py)"
                ))
            elif isinstance(node, ast.Call):
                for arg in (*node.args,
                            *(kw.value for kw in node.keywords)):
                    if (isinstance(arg, ast.Constant)
                            and arg.value == "float64"):
                        flag(arg, (
                            "dtype \"float64\" in a device-code module "
                            "— device math is float32-only"
                        ))
                self._check_asarray(node, jnp_names, flag)
        return findings

    @staticmethod
    def _check_asarray(node: ast.Call, jnp_names: set, flag) -> None:
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("asarray", "array")
                and isinstance(f.value, ast.Name)
                and f.value.id in jnp_names):
            return
        if len(node.args) != 1 or any(
                kw.arg == "dtype" for kw in node.keywords):
            return
        arg = node.args[0]
        has_float = any(
            isinstance(sub, ast.Constant) and isinstance(sub.value, float)
            for sub in ast.walk(arg)
        )
        only_literals = all(
            isinstance(sub, (ast.Constant, ast.List, ast.Tuple,
                             ast.UnaryOp, ast.unaryop, ast.expr_context))
            for sub in ast.walk(arg)
        )
        if has_float and only_literals:
            flag(node, (
                f"dtype-less {f.value.id}.{f.attr}() of a Python float "
                "literal — promotes to f64 under jax_enable_x64; pass "
                "an explicit jnp.float32"
            ))


@register
class StaticArgFlag(Rule):
    name = "static-arg-flag"
    description = (
        "bool/str parameters of jitted functions not named in "
        "static_argnames/static_argnums — structural flags must be "
        "static or every value change retraces (str args fail tracing "
        "outright)"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        findings: List[Finding] = []
        for entry in jitscan.jit_entries(ctx.tree):
            if entry.static_argnums is None or not entry.statics_known:
                continue  # control-flow body, or non-literal statics
            fn = entry.func
            if isinstance(fn, ast.Lambda):
                continue  # lambdas carry no annotations/defaults to read
            a = fn.args
            positional = [*a.posonlyargs, *a.args]
            defaults = dict(zip(
                [p.arg for p in positional[len(positional)
                                           - len(a.defaults):]],
                a.defaults,
            ))
            for kwarg, d in zip(a.kwonlyargs, a.kw_defaults):
                if d is not None:
                    defaults[kwarg.arg] = d
            for idx, param in enumerate(positional + list(a.kwonlyargs)):
                kind = _flag_kind(param, defaults.get(param.arg))
                if kind is None:
                    continue
                covered = (
                    param.arg in entry.static_argnames
                    or (param in positional
                        and idx in entry.static_argnums)
                )
                if not covered:
                    findings.append(Finding(
                        path=ctx.rel, line=param.lineno, rule=self.name,
                        message=(
                            f"parameter '{param.arg}' of jitted "
                            f"'{entry.name}' ({entry.via}) is "
                            f"{kind}-typed but not in static_argnames/"
                            "static_argnums — structural flags must be "
                            "static (str args fail tracing; bool args "
                            "silently retrace per value)"
                        ),
                    ))
        return findings


#: the one function allowed to relayout a dense Jacobian batch — the
#: compat shim for operators without an in-kernel linearisation
#: (core/pallas_solve.py).
RELAYOUT_SHIM = "jac_to_rows"

_RELAYOUT_FUNCS = {"transpose", "moveaxis", "swapaxes", "reshape"}


@register
class KernelRelayout(Rule):
    name = "kernel-relayout"
    description = (
        "jnp.transpose/moveaxis/reshape (or the method forms) applied to "
        "a (B, n, p) Jacobian array in core/ outside the sanctioned "
        "jac_to_rows compat shim — every such relayout is an extra HBM "
        "pass the fused kernel exists to delete; operators should "
        "advertise inkernel_linearize (jac_rows born in lane layout) or "
        "route through the shim"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not ctx.rel.startswith("kafka_tpu/core/"):
            return ()
        jnp_names = jitscan.jnp_aliases(ctx.tree)
        findings: List[Finding] = []
        seen_lines = set()

        def mentions_jac(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and "jac" in sub.id.lower():
                    return True
                if isinstance(sub, ast.Attribute) and \
                        "jac" in sub.attr.lower():
                    return True
            return False

        def flag(node: ast.Call, what: str) -> None:
            if node.lineno in seen_lines:
                return  # one finding per relayout chain/line
            seen_lines.add(node.lineno)
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"{what} on a Jacobian array outside the sanctioned "
                    f"{RELAYOUT_SHIM} shim — a dense (B, n, p) relayout "
                    "is an extra HBM pass; use the shim (out-of-kernel "
                    "operators) or kernel_linearize_rows (in-kernel "
                    "lane-layout Jacobians)"
                ),
            ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == RELAYOUT_SHIM:
                # the shim itself: its body is the one sanctioned use.
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        seen_lines.add(sub.lineno)
        np_names = jitscan.numpy_aliases(ctx.tree)
        module_aliases = jnp_names | np_names
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _RELAYOUT_FUNCS):
                continue
            if isinstance(f.value, ast.Name) and \
                    f.value.id in module_aliases:
                # module form: jnp.moveaxis(jac, ...) — the jac mention
                # lives in the arguments.
                if mentions_jac(node):
                    flag(node, f"{f.value.id}.{f.attr}()")
            elif mentions_jac(f.value):
                # method form: lin.jac.reshape(...) / jac_rows.transpose()
                flag(node, f".{f.attr}() method")
        return findings


#: the one module allowed to select non-finite values away in device
#: code — every replacement there is paired with a solve-health verdict
#: (retreat flags feed escalation; quarantine selects set QA bits).
NONFINITE_SANCTUARY = "kafka_tpu/core/solver_health.py"

_NONFINITE_PROBES = {"isnan", "isfinite", "isinf"}


@register
class NonfiniteLaunder(Rule):
    name = "nonfinite-launder"
    description = (
        "jnp.nan_to_num, or jnp.where whose condition probes "
        "isnan/isfinite/isinf, outside core/solver_health.py — "
        "replacing a non-finite value with a plausible number without "
        "raising a solve-health verdict is exactly the silent per-pixel "
        "divergence the health layer exists to end; detect through "
        "solver_health helpers so the replacement carries a QA bit"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or ctx.rel == NONFINITE_SANCTUARY:
            return ()
        jnp_names = jitscan.jnp_aliases(ctx.tree)
        if not jnp_names:
            return ()
        findings: List[Finding] = []

        def probes_nonfinite(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute) and \
                        sub.func.attr in _NONFINITE_PROBES:
                    return True
            return False

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id in jnp_names):
                continue
            if f.attr == "nan_to_num":
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=(
                        f"{f.value.id}.nan_to_num() launders NaN/inf "
                        "into plausible numbers with no verdict — "
                        "route the replacement through "
                        "core/solver_health.py so the pixel is flagged"
                    ),
                ))
            elif f.attr == "where" and node.args and \
                    probes_nonfinite(node.args[0]):
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=(
                        f"{f.value.id}.where() on an isnan/isfinite "
                        "probe silently launders non-finite values — "
                        "use the sanctioned solver_health selects "
                        "(retreat/quarantine_select), which pair every "
                        "replacement with a QA verdict"
                    ),
                ))
        return findings


def _flag_kind(param: ast.arg, default) -> str:
    """'bool'/'str' when the parameter is annotated or defaulted as such."""
    ann = param.annotation
    if isinstance(ann, ast.Name) and ann.id in ("bool", "str"):
        return ann.id
    if isinstance(ann, ast.Constant) and ann.value in ("bool", "str"):
        return ann.value
    if isinstance(default, ast.Constant):
        if isinstance(default.value, bool):
            return "bool"
        if isinstance(default.value, str):
            return "str"
    return None
