"""Shared AST analysis: where does device code live in a module?

A *jit region* is a function whose body is traced and runs on device:

- defs decorated with ``jax.jit`` / ``pjit`` / ``pmap`` (directly or via
  ``functools.partial(jax.jit, ...)``);
- callables handed to ``jax.jit(...)`` / ``pjit(...)`` call forms;
- ``shard_map`` bodies — call form ``shard_map(f, mesh=..., ...)`` and
  decorator form ``@partial(shard_map, ...)``: the wrapped function is a
  per-shard device program exactly like a jit body (its ``static_argnums``
  stay ``None`` — shard_map has no statics for the static-arg rule);
- Pallas kernels (first argument of ``pl.pallas_call``);
- bodies of structured control flow: ``lax.scan`` / ``lax.map`` /
  ``lax.while_loop`` / ``lax.fori_loop`` / ``lax.cond`` / ``lax.switch``;
- anything lexically nested inside one of the above.

Targets are resolved through ``functools.partial`` and the common
transforms (``grad`` / ``value_and_grad`` / ``vmap`` / ``checkpoint``) to
a ``Lambda`` or a same-module ``def`` by name; unresolvable targets
(e.g. methods of instances built elsewhere) are skipped — this is a
convention lint, not a soundness proof.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple, Union

FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

JIT_TAILS = {"jit", "pjit", "pmap"}
SHARD_MAP_TAIL = "shard_map"
TRANSFORM_TAILS = {"value_and_grad", "grad", "vmap", "checkpoint", "remat"}

#: control-flow entry points -> indices of their callable arguments.
#: ("rest1" = every positional arg from index 1 on, for cond/switch.)
_BODY_ARGS = {
    "scan": (0,),
    "map": (0,),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "cond": "rest1",
    "switch": "rest1",
    "pallas_call": (0,),
}


def dotted(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for the matching Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def tail(node: ast.AST) -> Optional[str]:
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def collect_defs(tree: ast.AST) -> Dict[str, FuncNode]:
    """Every named def in the module (methods included), by bare name.
    Later defs shadow earlier same-named ones — good enough for
    resolving ``target=`` / body-callable references."""
    defs: Dict[str, FuncNode] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
    return defs


def resolve_callable(node: ast.AST,
                     defs: Dict[str, FuncNode]) -> List[FuncNode]:
    """Resolve an expression used as a callable to def/lambda nodes."""
    if isinstance(node, ast.Lambda):
        return [node]
    if isinstance(node, (ast.Name, ast.Attribute)):
        t = tail(node)
        return [defs[t]] if t in defs else []
    if isinstance(node, ast.Call) and node.args:
        t = tail(node.func)
        if t == "partial" or t in TRANSFORM_TAILS:
            return resolve_callable(node.args[0], defs)
    return []


@dataclasses.dataclass
class JitEntry:
    """One jit region root.

    ``func`` — the def/lambda whose body is device code.
    ``via`` — how it became one (decorator / wrapping call / body-of).
    ``static_argnums`` / ``static_argnames`` — only for jit-wrapped
    entries whose statics were literal enough to read; None means "not a
    jit wrapping" (control-flow bodies) and the static-arg rule skips it.
    """

    func: FuncNode
    via: str
    static_argnums: Optional[Tuple[int, ...]] = None
    static_argnames: Optional[Tuple[str, ...]] = None
    statics_known: bool = True

    @property
    def name(self) -> str:
        return getattr(self.func, "name", "<lambda>")


def _literal_statics(keywords) -> Tuple[Tuple[int, ...], Tuple[str, ...],
                                        bool]:
    """(static_argnums, static_argnames, fully-literal?) from jit kwargs."""
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    known = True
    for kw in keywords or ():
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        try:
            val = ast.literal_eval(kw.value)
        except (ValueError, SyntaxError):
            known = False
            continue
        if isinstance(val, (int, str)):
            val = (val,)
        if kw.arg == "static_argnums":
            nums = tuple(int(v) for v in val)
        else:
            names = tuple(str(v) for v in val)
    return nums, names, known


def _decorator_entry(fn: FuncNode) -> Optional[JitEntry]:
    for d in getattr(fn, "decorator_list", ()):
        if tail(d) in JIT_TAILS:
            return JitEntry(fn, via=f"@{dotted(d)}",
                            static_argnums=(), static_argnames=())
        if isinstance(d, ast.Call):
            t = tail(d.func)
            if t in JIT_TAILS:
                nums, names, known = _literal_statics(d.keywords)
                return JitEntry(fn, via=f"@{dotted(d.func)}(...)",
                                static_argnums=nums, static_argnames=names,
                                statics_known=known)
            if t == "partial" and d.args and tail(d.args[0]) in JIT_TAILS:
                nums, names, known = _literal_statics(d.keywords)
                return JitEntry(
                    fn, via=f"@partial({dotted(d.args[0])}, ...)",
                    static_argnums=nums, static_argnames=names,
                    statics_known=known,
                )
            if t == SHARD_MAP_TAIL:
                return JitEntry(fn, via=f"@{dotted(d.func)}(...)")
            if (t == "partial" and d.args
                    and tail(d.args[0]) == SHARD_MAP_TAIL):
                return JitEntry(
                    fn, via=f"@partial({dotted(d.args[0])}, ...)"
                )
    return None


def _is_lax_call(func_node: ast.AST, t: str) -> bool:
    """Guard bare-name collisions: ``map``/``cond``/... must be lax-
    qualified; ``scan``/``pallas_call``/jit tails may appear bare."""
    d = dotted(func_node) or ""
    if t in ("map", "cond", "switch", "while_loop", "fori_loop"):
        return ".".join(d.split(".")[:-1]).endswith("lax") or d == t and \
            t in ("while_loop", "fori_loop")
    return True


def jit_entries(tree: ast.AST) -> List[JitEntry]:
    """Every jit-region root in the module, decorator and call forms."""
    defs = collect_defs(tree)
    entries: List[JitEntry] = []
    seen = set()

    def add(func: FuncNode, **kw) -> None:
        if id(func) not in seen:
            seen.add(id(func))
            entries.append(JitEntry(func, **kw))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            e = _decorator_entry(node)
            if e is not None and id(node) not in seen:
                seen.add(id(node))
                entries.append(e)
        elif isinstance(node, ast.Call):
            t = tail(node.func)
            if t in JIT_TAILS and node.args:
                nums, names, known = _literal_statics(node.keywords)
                for func in resolve_callable(node.args[0], defs):
                    add(func, via=f"{dotted(node.func)}(...) call",
                        static_argnums=nums, static_argnames=names,
                        statics_known=known)
            elif t == SHARD_MAP_TAIL and node.args:
                # per-shard body: a jit region, but with no jit statics —
                # static_argnums stays None so the static-arg rule skips.
                for func in resolve_callable(node.args[0], defs):
                    add(func, via=f"{dotted(node.func)}(...) call")
            elif t in _BODY_ARGS and _is_lax_call(node.func, t):
                spec = _BODY_ARGS[t]
                idxs = (
                    range(1, len(node.args)) if spec == "rest1" else spec
                )
                for i in idxs:
                    if i < len(node.args):
                        for func in resolve_callable(node.args[i], defs):
                            add(func, via=f"body of {dotted(node.func)}")
    return entries


def region_locals(func: FuncNode) -> set:
    """Names bound inside the region: parameters of the root and of every
    nested def/lambda, plus local assignment/loop/with targets.  These are
    the names a host-transfer call on which is (conservatively) a traced
    value; closure reads from outside the region are not included."""
    names: set = set()

    def add_args(fn: FuncNode) -> None:
        a = fn.args
        for arg in (*a.posonlyargs, *a.args, *a.kwonlyargs):
            names.add(arg.arg)
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)

    add_args(func)
    body = func.body if isinstance(func.body, list) else [func.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                add_args(node)
            elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                                   ast.For, ast.AsyncFor, ast.NamedExpr)):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AnnAssign, ast.AugAssign,
                                       ast.NamedExpr)):
                    targets = [node.target]
                else:
                    targets = [node.target]
                for t in targets:
                    for sub in ast.walk(t):
                        if isinstance(sub, ast.Name):
                            names.add(sub.id)
    return names


def numpy_aliases(tree: ast.AST) -> set:
    """Module-level names bound to the ``numpy`` package."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases or {"np", "numpy"} & _names_used(tree)


def jnp_aliases(tree: ast.AST) -> set:
    """Module-level names bound to ``jax.numpy``."""
    aliases = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.numpy":
                    aliases.add(a.asname or "jax")
    return aliases | {"jnp"}


def _names_used(tree: ast.AST) -> set:
    return {n.id for n in ast.walk(tree) if isinstance(n, ast.Name)}
