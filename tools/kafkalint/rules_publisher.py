"""Telemetry-plane rule: the publisher/httpd threads must never block.

``blocking-call-in-publisher`` (rule 13, ISSUE 10): the live publisher
(``kafka_tpu/telemetry/live.py``) and the HTTP endpoint handlers
(``kafka_tpu/telemetry/httpd.py``) run on background threads inside
EVERY instrumented process — engine runs, queue workers, the serving
daemon.  An unbounded outbound call there (an HTTP fetch, a raw socket
connect, a subprocess) turns the observability plane into a liveness
hazard: a hung scrape target stalls the heartbeat, the heartbeat going
stale flags the host dead, and the fleet starts reclaiming work from a
perfectly healthy process.  The plane must stay strictly local — read
the registry, write one atomic file, answer one socket that the OS
accepted for us.

The rule flags, anywhere under ``kafka_tpu/telemetry/``:

- any ``requests.*`` call (the library's default timeout is None —
  unbounded by construction);
- ``urllib`` fetches (``urlopen``);
- outbound socket construction (``socket.socket``,
  ``socket.create_connection``, ``socket.getaddrinfo``) — inbound
  serving via ``http.server`` never constructs these directly;
- subprocess spawns (``subprocess.run`` / ``Popen`` / ``call`` /
  ``check_call`` / ``check_output`` / ``getoutput``).

``socket.gethostname()`` stays legal (local, non-blocking — the
snapshot's identity field).  Consumers that legitimately scrape over
HTTP (``tools/loadgen.py``, tests) live outside the telemetry tree and
are out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from . import jitscan
from .core import FileContext, Finding, Rule, register

#: the publisher/httpd tree the no-blocking contract covers.
SCOPE_PREFIX = "kafka_tpu/telemetry/"

#: module -> banned attribute calls on it ("*" = every attribute).
_BANNED_ATTRS = {
    "requests": {"*"},
    "socket": {"socket", "create_connection", "getaddrinfo"},
    "subprocess": {"run", "Popen", "call", "check_call",
                   "check_output", "getoutput"},
    "request": {"urlopen"},   # urllib.request.urlopen
    "urllib": {"urlopen"},
}

#: bare-name calls (``from subprocess import Popen`` style imports).
_BANNED_NAMES = {
    "urlopen", "Popen", "check_output", "check_call",
    "create_connection", "getaddrinfo",
}


@register
class BlockingCallInPublisher(Rule):
    name = "blocking-call-in-publisher"
    description = (
        "unbounded requests/socket/subprocess calls inside the "
        "telemetry publisher/httpd tree (kafka_tpu/telemetry/) — the "
        "heartbeat and endpoint threads run in every process and must "
        "never block on the outside world, or a hung scrape target "
        "reads as a dead host"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not ctx.rel.startswith(SCOPE_PREFIX):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            blocked = self._blocked_call(node)
            if blocked:
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=(
                        f"{blocked} inside the telemetry "
                        "publisher/httpd tree — the live plane must "
                        "stay local and non-blocking (read the "
                        "registry, write one atomic file); move "
                        "outbound work to the consumer side "
                        "(tools/, aggregate callers)"
                    ),
                ))
        return findings

    @staticmethod
    def _blocked_call(call: ast.Call) -> str:
        f = call.func
        if isinstance(f, ast.Attribute):
            base = jitscan.dotted(f.value) or ""
            base_tail = base.rsplit(".", 1)[-1]
            banned = _BANNED_ATTRS.get(base_tail)
            if banned and ("*" in banned or f.attr in banned):
                return f"{base}.{f.attr}(...)"
            return ""
        if isinstance(f, ast.Name) and f.id in _BANNED_NAMES:
            return f"{f.id}(...)"
        return ""
