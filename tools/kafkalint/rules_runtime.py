"""Runtime-convention rules: thread trace propagation, exception hygiene.

``untracked-thread`` encodes the PR 3 tracing convention: contextvars do
NOT flow into new threads, so every thread owner captures
``tracing.current_context()`` at construction and the thread target
re-installs it with ``tracing.set_context(...)`` — otherwise the thread's
spans detach from the run timeline (see ``engine/prefetch.py`` and
``io/output.py`` for the canonical shape).

``bare-except`` flags ``except:`` / ``except Exception:`` /
``except BaseException:`` handlers that swallow the error: no re-raise,
no logging (stdlib logger methods or registry ``emit``), and no
justification comment.  The accepted justification form is a trailing
comment on the ``except`` line (or a comment line opening the handler
body) that says *why* swallowing is correct — kafkalint/expect directives
and bare ``noqa`` codes do not count.

``ad-hoc-retry`` encodes the resilience-layer convention (ISSUE 6):
``time.sleep`` outside ``kafka_tpu/resilience/`` is a hand-rolled
backoff/poll — inside a loop it is an ad-hoc retry loop that must go
through ``resilience.RetryPolicy`` (classified failures, counted retries,
injectable sleep); straight-line sleeps are flagged too, so waits either
move behind the policy layer or carry an inline suppression saying why
not (``telemetry/health.py``'s single probe re-read is the production
example).

``naive-marker-write`` encodes the queue-protocol convention (ISSUE 7):
the ``.done``/``.failed``/``.lease`` markers ARE the multi-host
coordination protocol, and a plain ``open(path, "w")`` on one is a torn
half-written marker waiting to happen (another host can read it
mid-write) — every marker write must go through the atomic
``_write_marker`` helpers (unique tmp + ``os.replace``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from .core import FileContext, Finding, Rule, register
from . import jitscan

_LOG_ATTRS = {
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "emit",
}

#: directives are machine syntax, not human justification.
_DIRECTIVE_RE = re.compile(r"^\s*(kafkalint\s*:|expect\s*:)")
_NOQA_RE = re.compile(r"noqa\s*:?\s*[A-Z0-9, ]*")


@register
class UntrackedThread(Rule):
    name = "untracked-thread"
    description = (
        "threading.Thread spawns whose target does not re-install the "
        "TraceContext (tracing.set_context) — contextvars don't cross "
        "thread creation, so the thread's spans/events detach from the "
        "run timeline"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        defs = jitscan.collect_defs(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and jitscan.tail(node.func) == "Thread"):
                continue
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and node.args:
                # threading.Thread(group, target, ...) positional form.
                target = node.args[1] if len(node.args) > 1 else None
            if target is None:
                findings.append(self._finding(
                    ctx, node,
                    "threading.Thread(...) with no resolvable target — "
                    "cannot verify the TraceContext re-install",
                ))
                continue
            resolved = jitscan.resolve_callable(target, defs)
            if not resolved:
                findings.append(self._finding(
                    ctx, node,
                    f"threading.Thread target "
                    f"{ast.unparse(target)!r} is not resolvable in this "
                    "module — cannot verify the TraceContext re-install",
                ))
                continue
            for func in resolved:
                if not self._installs_context(func):
                    name = getattr(func, "name", "<lambda>")
                    findings.append(self._finding(
                        ctx, node,
                        f"threading.Thread target '{name}' never calls "
                        "tracing.set_context(...) — capture "
                        "tracing.current_context() at construction and "
                        "re-install it first thing in the target",
                    ))
        return findings

    @staticmethod
    def _installs_context(func) -> bool:
        body = func.body if isinstance(func.body, list) else [func.body]
        for stmt in body:
            for node in ast.walk(stmt):
                if (isinstance(node, ast.Call)
                        and jitscan.tail(node.func) == "set_context"):
                    return True
        return False

    def _finding(self, ctx: FileContext, node: ast.AST,
                 msg: str) -> Finding:
        return Finding(path=ctx.rel, line=node.lineno, rule=self.name,
                       message=msg + " (PR 3 tracing convention; see "
                               "engine/prefetch.py for the shape)")


@register
class BareExcept(Rule):
    name = "bare-except"
    description = (
        "except:/except Exception: handlers with no re-raise, no "
        "logging, and no justification comment — silent swallows hide "
        "real failures; narrow the type, log it, or justify it inline"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            caught = self._broad_catch(node.type)
            if caught is None:
                continue
            if any(isinstance(n, ast.Raise)
                   for stmt in node.body for n in ast.walk(stmt)):
                continue
            if self._logs(node):
                continue
            if self._justified(ctx, node):
                continue
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"'except {caught}' swallows the error with no "
                    "re-raise, no logging, and no justification comment "
                    "— narrow the exception type, log through the "
                    "registry/logger, or add a trailing '# <why this is "
                    "safe>' comment"
                ),
            ))
        return findings

    @staticmethod
    def _broad_catch(type_node) -> Optional[str]:
        if type_node is None:
            return ""
        names = []
        nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        for n in nodes:
            names.append(jitscan.tail(n) or "?")
        broad = [n for n in names if n in ("Exception", "BaseException")]
        return broad[0] if broad else None

    @staticmethod
    def _logs(handler: ast.ExceptHandler) -> bool:
        for stmt in handler.body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in _LOG_ATTRS:
                    return True
        return False

    @staticmethod
    def _justified(ctx: FileContext, handler: ast.ExceptHandler) -> bool:
        """A human reason on the except line, between it and the first
        body statement, or trailing the first body line."""
        first_body = handler.body[0].lineno if handler.body else \
            handler.lineno
        for lineno in range(handler.lineno, first_body + 1):
            line = ctx.line_text(lineno)
            if "#" not in line:
                continue
            comment = line.split("#", 1)[1]
            if _DIRECTIVE_RE.match(comment):
                continue
            stripped = _NOQA_RE.sub("", comment)
            if re.search(r"[A-Za-z]{2}", stripped):
                return True
        return False


@register
class AdHocRetry(Rule):
    name = "ad-hoc-retry"
    description = (
        "time.sleep outside kafka_tpu/resilience/ — hand-rolled "
        "backoff/poll loops must go through resilience.RetryPolicy "
        "(classified failures, counted retries, injectable sleep); "
        "inline-suppress the rare justified wait"
    )

    #: the one module allowed to sleep: the policy layer itself.
    EXEMPT_PREFIX = "kafka_tpu/resilience/"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or ctx.rel.startswith(self.EXEMPT_PREFIX):
            return ()
        findings: List[Finding] = []
        self._scan(ctx, ctx.tree, False, findings)
        return findings

    def _scan(self, ctx: FileContext, node: ast.AST, in_loop: bool,
              findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call) and self._is_sleep(child):
                if in_loop:
                    msg = (
                        "time.sleep inside a loop is a hand-rolled "
                        "backoff — retry through "
                        "kafka_tpu.resilience.RetryPolicy instead"
                    )
                else:
                    msg = (
                        "ad-hoc time.sleep wait — route retries/backoff "
                        "through kafka_tpu.resilience.RetryPolicy, or "
                        "justify the wait with an inline suppression"
                    )
                findings.append(Finding(
                    path=ctx.rel, line=child.lineno, rule=self.name,
                    message=msg,
                ))
            self._scan(
                ctx, child,
                in_loop or isinstance(
                    child, (ast.For, ast.While, ast.AsyncFor)
                ),
                findings,
            )

    @staticmethod
    def _is_sleep(call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr == "sleep":
            # time.sleep / aliased-module sleep; object methods named
            # .sleep on non-module receivers are out of scope.
            base = jitscan.tail(f.value) or ""
            return "time" in base
        return isinstance(f, ast.Name) and f.id == "sleep"


@register
class NaiveMarkerWrite(Rule):
    name = "naive-marker-write"
    description = (
        "open(..., 'w') on a .done/.failed/.lease marker path outside "
        "the sanctioned _write_marker helpers — marker files are the "
        "multi-host protocol and must be written atomically (unique tmp "
        "+ os.replace), or another host reads a torn payload"
    )

    #: marker suffixes that form the queue protocol.
    MARKERS = (".done", ".failed", ".lease")
    #: functions allowed to touch marker paths directly (the atomic
    #: writers themselves).
    SANCTIONED = ("_write_marker",)

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        findings: List[Finding] = []
        self._scan(ctx, ctx.tree, (), findings)
        return findings

    def _scan(self, ctx: FileContext, node: ast.AST, stack, findings):
        for child in ast.iter_child_nodes(node):
            child_stack = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_stack = stack + (child.name,)
            if (isinstance(child, ast.Call)
                    and self._is_marker_write(child)
                    and not any(s in self.SANCTIONED for s in stack)):
                findings.append(Finding(
                    path=ctx.rel, line=child.lineno, rule=self.name,
                    message=(
                        "marker file written with a plain open(..., 'w') "
                        "— route .done/.failed/.lease writes through the "
                        "atomic _write_marker helpers "
                        "(shard.scheduler/shard.queue), or a racing host "
                        "reads a torn payload"
                    ),
                ))
            self._scan(ctx, child, child_stack, findings)

    def _is_marker_write(self, call: ast.Call) -> bool:
        if not (isinstance(call.func, ast.Name)
                and call.func.id == "open") or not call.args:
            return False
        mode = None
        if len(call.args) > 1:
            mode = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)
                and ("w" in mode.value or "x" in mode.value
                     or "a" in mode.value)):
            return False
        target = ast.unparse(call.args[0])
        return any(m in target for m in self.MARKERS)
