"""Reanalysis read-only contract rule (ISSUE 17).

``forward-state-mutation-in-smoother`` pins the smoother package's one
architectural invariant: the RTS backward pass is STRICTLY READ WORK
over the forward run's checkpoint chain.  Any replica sharing the chain
may serve ``smoothed=true`` requests precisely because the smoother
never writes — a ``Checkpointer.save`` call (or any ``save``/``savez``
on a checkpoint-ish receiver) from ``kafka_tpu/smoother/`` would let a
reanalysis rewind or fork the warm chain the forward filter resumes
from, and a write to a chain node's analysis/forecast fields would
corrupt the recursion's inputs mid-sweep.

Scope: files under ``kafka_tpu/smoother/`` only — the forward engine
(``engine/checkpoint.py``, ``engine/filter.py``) is the sanctioned
writer and is untouched by this rule.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: the package whose files must never mutate forward state.
SMOOTHER_PREFIX = "kafka_tpu/smoother/"

#: method names that persist state (the Checkpointer write surface and
#: the raw numpy writers it is built on).
_WRITE_METHODS = {"save", "savez", "savez_compressed"}

#: attributes of a chain node / checkpoint set that hold forward state —
#: assigning to any of them from the smoother mutates the recursion's
#: own inputs.
_FORWARD_FIELDS = {
    "x_analysis", "p_analysis_inverse",
    "x_forecast", "p_forecast_inverse", "sidecar",
}


@register
class ForwardStateMutationInSmoother(Rule):
    name = "forward-state-mutation-in-smoother"
    description = (
        "the smoother package writes forward state: a "
        "Checkpointer.save / savez call or an assignment to a chain "
        "node's analysis/forecast fields from kafka_tpu/smoother/ — "
        "the RTS pass is read-only over the checkpoint chain by "
        "contract (that is what makes smoothed=true serveable from "
        "any replica sharing the chain)"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or not ctx.rel.startswith(SMOOTHER_PREFIX):
            return ()
        findings: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"{what} — the smoother is read-only over the "
                    "forward chain; persist derived products through "
                    "the output writers, never the checkpoint store"
                ),
            ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _WRITE_METHODS:
                flag(node, f"call to .{node.func.attr}() writes a "
                           "checkpoint set from the smoother")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr in _FORWARD_FIELDS:
                        flag(node, f"assignment to .{t.attr} mutates "
                                   "forward state on a chain node")
                        break
        return findings
