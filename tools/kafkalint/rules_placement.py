"""Placement-determinism rule: no salted/random routing decisions.

``nondeterministic-placement`` (ISSUE 13) encodes the elastic-fleet
routing convention: the tile keyspace is partitioned by a STABLE
consistent-hash ring (``kafka_tpu/serve/router.py``'s ``stable_hash``,
a blake2b digest), because placement must agree across processes and
across restarts — the router, a restarted router replaying its
journal, and any operator tool reasoning about ownership all have to
land every tile on the same replica.  Python's builtin ``hash()`` is
salted per process (PYTHONHASHSEED): two routers would disagree about
every tile's owner, and a restart would silently re-shuffle the whole
keyspace, turning every warm tile cold.  ``random.*`` placement is the
same bug with extra steps.

The rule flags, in the placement-bearing trees ``kafka_tpu/serve/``
and ``kafka_tpu/shard/``:

- any call of the BUILTIN ``hash()`` (a shadowing local def counts as
  a violation too — don't name things ``hash`` in these trees);
- any ``random.*`` / ``np.random.*`` call.

``kafka_tpu/serve/router.py`` is the ONE sanctioned home of placement
hashing and is exempt.  Entropy for IDENTITY (``os.urandom`` request
ids) is not placement and stays legal everywhere.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: placement-bearing trees where salted/random decisions are banned.
SCOPES = ("kafka_tpu/serve/", "kafka_tpu/shard/")

#: the sanctioned ring module — the one home of placement hashing.
SANCTIONED = ("kafka_tpu/serve/router.py",)


def _dotted(node) -> str:
    """Best-effort dotted name of a call target (``np.random.choice``
    -> "np.random.choice"); empty for non-name expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


@register
class NondeterministicPlacement(Rule):
    name = "nondeterministic-placement"
    description = (
        "builtin hash() (per-process salted) or random.* used in "
        "serve/ or shard/ — routing/partitioning decisions must go "
        "through the stable ring (serve.router.stable_hash) so every "
        "process and every restart agrees on placement"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or \
                not any(ctx.rel.startswith(s) for s in SCOPES) or \
                ctx.rel in SANCTIONED:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._violation(node)
            if msg:
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=msg,
                ))
        return findings

    @staticmethod
    def _violation(call: ast.Call) -> str:
        dotted = _dotted(call.func)
        if dotted == "hash":
            return (
                "builtin hash() is salted per process "
                "(PYTHONHASHSEED): two routers would disagree about "
                "every tile and a restart re-shuffles the keyspace — "
                "use serve.router.stable_hash for placement"
            )
        parts = dotted.split(".")
        if "random" in parts[:-1] or dotted == "random":
            return (
                f"{dotted}() in a placement-bearing module — random "
                "routing/partitioning breaks cross-process agreement "
                "and replay determinism; place via the stable ring "
                "(serve.router) instead"
            )
        return ""
