"""Performance-observability rule: no ad-hoc timing in device code.

``ad-hoc-timing`` (ISSUE 12) encodes the perf-attribution convention:
``kafka_tpu/telemetry/perf.py`` derives the live throughput /
device-fraction / phase gauges from the span histograms and the packed
per-window diagnostic read, so a raw ``time.perf_counter()`` /
``time.monotonic()`` pair (or a ``block_until_ready()`` flush used as a
timing barrier) in the device-adjacent modules (``core/``, ``engine/``,
``shard/``, ``obsops/``) is an interval the attribution plane can never
see — and ``block_until_ready`` in particular forces a device sync the
engine otherwise avoids (the one packed read per window IS the sync
budget).  Timed intervals there go through ``telemetry.spans.span`` (a
histogram + event + timeline span in one) or, where the raw endpoints
are needed (labelled metric observations, ``TraceBuffer.add_span``),
``telemetry.spans.stopwatch`` — both live in ``telemetry/``, which this
rule exempts along with ``bench.py`` and ``tools/`` (measurement code
is allowed to measure).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register
from . import jitscan

#: device-adjacent trees where raw timing is banned.
SCOPES = (
    "kafka_tpu/core/",
    "kafka_tpu/engine/",
    "kafka_tpu/shard/",
    "kafka_tpu/obsops/",
)

#: clock calls that are timing when called raw (time.time() is wall-clock
#: bookkeeping — record timestamps, lease deadlines — and stays legal).
CLOCK_ATTRS = ("perf_counter", "monotonic", "perf_counter_ns",
               "monotonic_ns")


@register
class AdHocTiming(Rule):
    name = "ad-hoc-timing"
    description = (
        "time.perf_counter/time.monotonic/block_until_ready timing in "
        "device-adjacent modules (core/, engine/, shard/, obsops/) — "
        "route intervals through telemetry.spans.span or "
        "telemetry.spans.stopwatch so the perf-attribution plane "
        "(kafka_perf_* gauges, trace timeline) sees them"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or \
                not any(ctx.rel.startswith(s) for s in SCOPES):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._violation(node)
            if msg:
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=msg,
                ))
        return findings

    @staticmethod
    def _violation(call: ast.Call) -> str:
        f = call.func
        tail = jitscan.tail(f)
        if tail in CLOCK_ATTRS:
            base = jitscan.tail(f.value) if isinstance(f, ast.Attribute) \
                else ""
            if not isinstance(f, ast.Attribute) or "time" in (base or ""):
                return (
                    f"raw {tail}() timing in a device-adjacent module — "
                    "use telemetry.spans.span for phase intervals or "
                    "telemetry.spans.stopwatch where the raw endpoints "
                    "are needed (histogram observations, trace spans)"
                )
        if tail == "block_until_ready":
            return (
                "block_until_ready() in a device-adjacent module is an "
                "ad-hoc timing barrier AND an extra device sync — the "
                "engine's sync budget is the one packed diagnostic read "
                "per window; time through telemetry.spans instead"
            )
        return ""
