"""kafkalint command line: human and --json output, stable exit codes."""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import BASELINE_RELPATH, REGISTRY, make_rules, run_lint


def _default_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.kafkalint",
        description=(
            "AST static analysis for JAX/TPU hazards and repo "
            "conventions (BASELINE.md 'Static analysis')"
        ),
    )
    p.add_argument("root", nargs="?", default=None,
                   help="tree to lint (default: this repo)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rules to run")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: "
                        "<root>/tools/kafkalint/baseline.json if present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--baseline-update", action="store_true",
                   help="regenerate the baseline from the current "
                        "findings (grandfather everything; stale "
                        "semantics unchanged — entries that later match "
                        "nothing become stale-baseline findings)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    return p


def _baseline_update(root: str, rule_names: Optional[List[str]],
                     baseline_path: Optional[str]) -> int:
    """Regenerate the baseline file from the current (un-baselined)
    findings.  One entry per distinct (rule, path, message), with the
    full message as ``contains`` so an entry stops matching — and goes
    stale — the moment the finding changes at all."""
    result = run_lint(root, rule_names=rule_names, use_baseline=False)
    path = baseline_path or os.path.join(root, BASELINE_RELPATH)
    entries = []
    seen = set()
    for f in result.findings:
        key = (f.rule, f.path, f.message)
        if key in seen:
            continue
        seen.add(key)
        entries.append({
            "rule": f.rule, "path": f.path, "contains": f.message,
            "reason": "grandfathered by --baseline-update",
        })
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(entries, fh, indent=2)
        fh.write("\n")
    print(
        f"kafkalint: wrote {len(entries)} baseline entr"
        f"{'y' if len(entries) == 1 else 'ies'} to {path}"
    )
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        make_rules()  # import rule modules so REGISTRY is populated
        for name in sorted(REGISTRY):
            print(f"{name}: {REGISTRY[name].description}")
        return 0
    root = args.root or _default_root()
    if not os.path.isdir(root):
        print(f"kafkalint: no such directory: {root}", file=sys.stderr)
        return 2
    rule_names = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    try:
        if args.baseline_update:
            return _baseline_update(root, rule_names, args.baseline)
        result = run_lint(
            root, rule_names=rule_names, baseline_path=args.baseline,
            use_baseline=not args.no_baseline,
        )
    except ValueError as exc:  # unknown rule / malformed baseline
        print(f"kafkalint: {exc}", file=sys.stderr)
        return 2
    if args.as_json:
        payload = result.to_json()
        payload["root"] = os.path.abspath(root)
        json.dump(payload, sys.stdout, indent=2)
        print()
        return 0 if result.clean else 1
    for f in result.findings:
        print(f"kafkalint: {f.format()}", file=sys.stderr)
    if result.findings:
        print(
            f"kafkalint: {len(result.findings)} finding(s) in "
            f"{result.files_scanned} file(s)",
            file=sys.stderr,
        )
        return 1
    grandfathered = (
        f", {result.baseline_matched} grandfathered"
        if result.baseline_matched else ""
    )
    print(
        f"kafkalint: clean ({result.files_scanned} files, "
        f"{len(result.rules)} rules{grandfathered})"
    )
    return 0
