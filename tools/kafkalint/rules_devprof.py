"""Device-plane rule: raw device introspection stays in the telemetry
funnel.

``raw-device-introspection`` (rule 20, ISSUE 18): the device-plane
surfaces — ``Device.memory_stats()``, ``jax.live_arrays()`` and the
``jax.profiler`` capture API — are cheap to call and ruinously easy to
scatter.  A stray ``memory_stats()`` in engine code duplicates the
watermark gauges under ad-hoc names, a ``live_arrays()`` census outside
the ledger races the real one, and a second ``jax.profiler.start_trace``
collides with the ``/profilez`` single-capture contract (one profiler
session per process — a second start raises).  Every consumer reads
these through ``kafka_tpu/telemetry/{device,devprof,perf}.py``, which
publish the results as metrics, census entries and parsed kernel
tables everything else (endpoints, fleet view, flight recorder,
BENCH) consumes.

The rule flags, anywhere OUTSIDE that three-file allowlist:

- any ``.memory_stats()`` attribute call (the per-device PJRT query);
- ``jax.live_arrays()`` (dotted or imported bare);
- any dotted ``jax.profiler.*`` call (``trace``, ``TraceAnnotation``,
  ``start_trace`` ...), including ``profiler.*`` after ``from jax
  import profiler``.

``utils/profiling.py`` predates the funnel and wraps two profiler
entry points as degradable context managers; its sites carry inline
``# kafkalint: disable=raw-device-introspection`` waivers with reasons
rather than an allowlist hole — new call sites must justify themselves
the same way.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from . import jitscan
from .core import FileContext, Finding, Rule, register

#: the telemetry funnel allowed to touch the raw device APIs.
ALLOWED_FILES = (
    "kafka_tpu/telemetry/device.py",
    "kafka_tpu/telemetry/devprof.py",
    "kafka_tpu/telemetry/perf.py",
)


@register
class RawDeviceIntrospection(Rule):
    name = "raw-device-introspection"
    description = (
        "raw device introspection (Device.memory_stats(), "
        "jax.live_arrays(), jax.profiler.*) outside the telemetry "
        "funnel kafka_tpu/telemetry/{device,devprof,perf}.py — go "
        "through the watermark gauges, the buffer census and the "
        "capture plumbing so every consumer reads one accounting"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or ctx.rel in ALLOWED_FILES:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            raw = self._raw_call(node)
            if raw:
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=(
                        f"{raw} outside the telemetry device funnel — "
                        "read device memory through telemetry.device's "
                        "watermark/headroom gauges, live buffers "
                        "through telemetry.devprof's census, and drive "
                        "profiler captures through telemetry.perf "
                        "(/profilez, --profile-windows)"
                    ),
                ))
        return findings

    @staticmethod
    def _raw_call(call: ast.Call) -> str:
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr == "memory_stats":
                return ".memory_stats(...)"
            base = jitscan.dotted(f.value) or ""
            base_tail = base.rsplit(".", 1)[-1]
            if f.attr == "live_arrays" and base_tail == "jax":
                return "jax.live_arrays(...)"
            if base == "jax.profiler" or base_tail == "profiler":
                return f"{base}.{f.attr}(...)"
            return ""
        if isinstance(f, ast.Name) and f.id == "live_arrays":
            return "live_arrays(...)"
        return ""
