"""kafkalint — AST static analysis for JAX/TPU hazards and repo conventions.

Run it with::

    python -m tools.kafkalint [root] [--json] [--rules a,b] [--list-rules]

Exit codes: 0 clean, 1 findings (or a stale baseline entry), 2 usage
error.  See BASELINE.md "Static analysis" for the rule table, the
``# kafkalint: disable=<rule>`` suppression syntax, and the baseline
update flow; ``tests/test_lint.py`` wires the pass into tier-1.
"""

from .core import (  # noqa: F401
    REGISTRY,
    FileContext,
    Finding,
    LintResult,
    Rule,
    iter_files,
    make_rules,
    register,
    run_lint,
)

__all__ = [
    "REGISTRY", "FileContext", "Finding", "LintResult", "Rule",
    "iter_files", "make_rules", "register", "run_lint",
]
