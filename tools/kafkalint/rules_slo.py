"""SLO convention rule (ISSUE 15).

``magic-slo-threshold`` encodes the SLO-layer convention (the rule-14
``magic-quality-threshold`` twin): every objective target, burn-rate
threshold, evaluation-window length and error-budget literal lives in
the sanctioned module-level config block of
``kafka_tpu/telemetry/slo.py``, where BASELINE.md documents it and
every consumer (the evaluator, ``/alertz``, admission's ``slo_burn``
shed, ``tools/slo_report.py``, the BENCH snapshot) reads the SAME
value.  A numeric SLO literal anywhere else is a second, silently-
divergent definition of "burning too fast": the report would then
disagree with the alert that paged.

Detection is vocabulary-based on identifier SEGMENTS (the quality
rule's substring match would false-positive on ``slopes``/``slowest``):
a numeric literal assigned to a name — or passed as a keyword
argument — any of whose underscore-separated segments is ``slo``,
``burn``, ``budget`` or ``objective`` is a finding outside the
sanctuary's module level.  Booleans and non-literal expressions are
out of scope (thresholds are numbers; flags and derived values are
not thresholds).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: the ONE module whose top-level assignments may carry SLO threshold
#: literals (the documented config block).
SLO_SANCTUARY = "kafka_tpu/telemetry/slo.py"

#: identifier segments that mark a name as SLO vocabulary.
_VOCAB = frozenset({"slo", "burn", "budget", "objective"})


def _vocab_name(name: str) -> bool:
    return any(seg in _VOCAB for seg in name.lower().split("_"))


def _numeric_literal(node: ast.AST) -> bool:
    """True for an int/float literal (unary +/- included; bools are
    flags, not thresholds)."""
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.UAdd, ast.USub)):
        node = node.operand
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool))


@register
class MagicSloThreshold(Rule):
    name = "magic-slo-threshold"
    description = (
        "numeric SLO literal (objective target, burn-rate threshold, "
        "window length, error-budget parameter) outside the sanctioned "
        "module-level config block of kafka_tpu/telemetry/slo.py — a "
        "second definition of 'burning too fast' silently diverges "
        "from the one the evaluator, the report and admission all "
        "share"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        sanctuary = ctx.rel == SLO_SANCTUARY
        sanctioned_lines = set()
        if sanctuary:
            # Module-level assignments ARE the config block.
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    sanctioned_lines.add(stmt.lineno)
        findings: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"{what} sets an SLO literal outside the "
                    f"sanctioned config block ({SLO_SANCTUARY}) — "
                    "import the constant (or add it to the block) so "
                    "every consumer shares one definition of the "
                    "objective"
                ),
            ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                if node.lineno in sanctioned_lines:
                    continue
                value = node.value
                if value is None or not _numeric_literal(value):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and _vocab_name(t.id):
                        flag(node, f"assignment to {t.id!r}")
                        break
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and _vocab_name(kw.arg) and \
                            _numeric_literal(kw.value):
                        flag(kw.value, f"keyword argument {kw.arg!r}")
        return findings
