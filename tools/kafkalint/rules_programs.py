"""Program-registry rule: every device entry point is contract-analyzed.

``unregistered-device-program`` (rule 21, ISSUE 19): the programlint
analyzer (``tools/programlint.py``) verifies dtype/transfer/relayout/
collective contracts over the *registered* device programs — a jitted
entry point nobody registered is a device program with no contract, and
its regressions (an f64 upcast, a smuggled callback, a surprise
all-gather) ship silently.  This rule closes the loop from the source
side: any ``jit``/``pjit``/``pmap``/``pallas_call``/``shard_map`` entry
point defined in the device packages (``kafka_tpu/{core,engine,smoother,
obsops,shard}/``) must have its def name listed in
``COVERED_ENTRY_POINTS`` in ``kafka_tpu/analysis/programs.py`` — which in
practice means a registered program traces through it.

The covered set is read by AST (``ast.literal_eval`` on the
``COVERED_ENTRY_POINTS`` assignment) from the linted root's own
``kafka_tpu/analysis/programs.py``, so fixture trees carry their own
small registry and the rule never imports jax.  Host-side training
helpers that are jitted but deliberately not device programs of the
serving engine (e.g. the GP/MLP calibration steps) carry inline
``# kafkalint: disable=unregistered-device-program`` waivers with
reasons, exactly like every other grandfathered exception.
"""

from __future__ import annotations

import ast
import os
from typing import FrozenSet, Iterable, List, Optional

from . import jitscan
from .core import FileContext, Finding, Rule, register

#: packages whose jit entries must be registry-covered.
DEVICE_PACKAGES = (
    "kafka_tpu/core/", "kafka_tpu/engine/", "kafka_tpu/smoother/",
    "kafka_tpu/obsops/", "kafka_tpu/shard/",
)

#: the AST-readable registry twin, relative to the linted root.
REGISTRY_RELPATH = os.path.join("kafka_tpu", "analysis", "programs.py")

#: ``via`` markers that make an entry a compiled device program root
#: (control-flow bodies like ``body of lax.scan`` are inside one of
#: these, never independent programs).
_PROGRAM_MARKERS = ("jit", "pmap", "pallas_call", "shard_map")


def covered_entry_points(root: str) -> Optional[FrozenSet[str]]:
    """``COVERED_ENTRY_POINTS`` parsed from the root's registry module,
    or None when the module (or the literal) is absent/unreadable."""
    path = os.path.join(root, REGISTRY_RELPATH)
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        targets = ()
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = (node.target,)
        for t in targets:
            if (isinstance(t, ast.Name)
                    and t.id == "COVERED_ENTRY_POINTS"):
                try:
                    val = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
                return frozenset(str(v) for v in val)
    return None


@register
class UnregisteredDeviceProgram(Rule):
    name = "unregistered-device-program"
    description = (
        "jit/pjit/pmap/pallas_call/shard_map entry point in the device "
        "packages whose def name is not in COVERED_ENTRY_POINTS of "
        "kafka_tpu/analysis/programs.py — register a program spec so "
        "tools/programlint.py verifies its dtype/transfer/relayout/"
        "collective contracts, or waive it inline with a reason"
    )

    def __init__(self) -> None:
        self._covered: Optional[FrozenSet[str]] = None
        self._covered_root: Optional[str] = None

    def _covered_for(self, root: str) -> Optional[FrozenSet[str]]:
        if self._covered_root != root:
            self._covered_root = root
            self._covered = covered_entry_points(root)
        return self._covered

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        if not any(ctx.rel.startswith(p) for p in DEVICE_PACKAGES):
            return ()
        covered = self._covered_for(ctx.root)
        if covered is None:
            # No registry in this tree: nothing to check against (the
            # production tree always has one; bare tmp trees don't).
            return ()
        findings: List[Finding] = []
        for entry in jitscan.jit_entries(ctx.tree):
            if entry.name == "<lambda>":
                continue
            if not any(m in entry.via for m in _PROGRAM_MARKERS):
                continue
            if entry.name in covered:
                continue
            findings.append(Finding(
                path=ctx.rel, line=entry.func.lineno, rule=self.name,
                message=(
                    f"device program '{entry.name}' (via {entry.via}) "
                    "is not in COVERED_ENTRY_POINTS of "
                    "kafka_tpu/analysis/programs.py — register an "
                    "abstract spec so programlint traces its contracts"
                ),
            ))
        return findings
