"""Coalesced-serving rule: one sanctioned solve-dispatch site.

``unbatched-serve-dispatch`` (PR 20) encodes the coalesced-serving
convention: every solve the serving layer launches goes through the
batch executor module, ``kafka_tpu/serve/batch.py``.  That module is
where the admission micro-window's compatibility contract lives — the
rendezvous that coalesces shape-compatible requests into one device
launch, the solo fallback that keeps the exact unbatched program, and
the batch telemetry (launch counters, ``serve_batch`` spans).

A direct ``session.serve(...)`` call or a raw
``assimilate_date_jit`` dispatch anywhere else in ``serve/`` silently
bypasses all of it: the request never meets its batch peers (the
window waits out its deadline for a member that will not post), the
coalescing metrics under-count, and the AOT bucket manifest no longer
describes what actually runs.  The bypass WORKS — the answer is
bit-identical — which is exactly why it needs a lint: nothing else
would catch it.

The rule flags, in ``kafka_tpu/serve/`` outside the sanctioned
executor module:

- any ``.serve(...)`` attribute call (route through
  ``serve.batch.solve_session``);
- any reference to the raw engine entry points
  ``assimilate_date_jit`` / ``assimilate_date_batch_jit`` — import or
  call; a dispatch that does not exist cannot drift.

``kafka_tpu/serve/batch.py`` is exempt (it IS the executor).
``TileSession.serve`` definitions are out of scope — the rule guards
call sites, not the method itself.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: the tree where serve dispatch lives.
SCOPES = ("kafka_tpu/serve/",)

#: the one sanctioned batch-executor module.
SANCTIONED = ("kafka_tpu/serve/batch.py",)

#: raw engine entry points that must not appear outside the executor.
RAW_DISPATCH = {"assimilate_date_jit", "assimilate_date_batch_jit"}


@register
class UnbatchedServeDispatch(Rule):
    name = "unbatched-serve-dispatch"
    description = (
        "direct session.serve(...) call or raw assimilate_date_jit "
        "dispatch in serve/ outside serve/batch.py — solves launched "
        "around the batch executor never coalesce, starve the "
        "admission micro-window and under-count batch telemetry. "
        "Route through serve.batch.solve_session"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None or \
                not any(ctx.rel.startswith(s) for s in SCOPES) or \
                ctx.rel in SANCTIONED:
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "serve":
                findings.append(Finding(
                    path=ctx.rel, line=node.lineno, rule=self.name,
                    message=(
                        "direct .serve(...) call in serve/ — solve "
                        "dispatch has ONE site (serve.batch."
                        "solve_session); a bypass never meets its "
                        "batch peers and leaves the micro-window "
                        "waiting for a member that will not post"
                    ),
                ))
            elif isinstance(node, (ast.Name, ast.Attribute)):
                name = node.id if isinstance(node, ast.Name) \
                    else node.attr
                if name in RAW_DISPATCH:
                    findings.append(self._raw(ctx, node.lineno, name))
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    base = alias.name.rsplit(".", 1)[-1]
                    if base in RAW_DISPATCH or \
                            (alias.asname or "") in RAW_DISPATCH:
                        findings.append(
                            self._raw(ctx, node.lineno, base)
                        )
        return findings

    def _raw(self, ctx: FileContext, lineno: int, name: str) -> Finding:
        return Finding(
            path=ctx.rel, line=lineno, rule=self.name,
            message=(
                f"raw engine entry point {name} referenced in "
                "serve/ — the batch executor (serve/batch.py) owns "
                "engine dispatch; anywhere else it bypasses "
                "coalescing, batch telemetry and the AOT bucket "
                "manifest"
            ),
        )
