"""Telemetry-vocabulary rules + the legacy check_metric_names API.

The three lints that lived in ``tools/check_metric_names.py`` (metric
registration conventions; emit()/span() casing; near-duplicate and
cross-namespace name collisions) fold into the kafkalint walker here as
three rules sharing its suppression syntax and output.  The original
module-level API (``check``, ``collect_registrations``, ``collect_names``,
the regexes, ``main``) is preserved verbatim-in-behaviour so
``tools/check_metric_names.py`` can stay a thin compatibility shim and
``tests/test_metric_names.py`` passes unchanged.

These rules scan only ``kafka_tpu/`` and ``bench.py`` — the telemetry
vocabulary lives in the engine tree; ``tools/`` scripts never register
metrics (and this module's own regex sources must not lint themselves).
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from .core import FileContext, Finding, Rule, register

#: registration call with a literal first argument.
REGISTRATION_RE = re.compile(
    r"\.\s*(counter|gauge|histogram)\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE
)
NAME_RE = re.compile(r"^kafka_[a-z0-9]+_[a-z0-9_]+$")

#: emit("...") event and span("...") phase call sites with a literal
#: first argument (the lookbehind keeps trace_span()/add_span() out of
#: the span scan — those carry arbitrary span names, not engine phases).
EMIT_RE = re.compile(r"\.\s*emit\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE)
SPAN_RE = re.compile(r"(?<!\w)span\(\s*\n?\s*\"([^\"]+)\"", re.MULTILINE)
EVENT_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: sources the telemetry vocabulary may live in, relative to the root
#: (the legacy scan set — unchanged).
SCAN = ("kafka_tpu", "bench.py")

Site = Tuple[str, int]


def _eligible(rel: str) -> bool:
    return rel == "bench.py" or rel.startswith("kafka_tpu/")


# ---------------------------------------------------------------------------
# Pure error builders shared by the rules and the legacy check().
# ---------------------------------------------------------------------------

def registration_errors(
    regs: Dict[str, List[Tuple[str, int, str]]],
) -> List[Tuple[str, Site]]:
    """(message, anchor site) per metric-registration violation."""
    errors: List[Tuple[str, Site]] = []
    for name, sites in sorted(regs.items()):
        anchor = min((p, ln) for p, ln, _ in sites)
        where = ", ".join(f"{p}:{ln}" for p, ln, _ in sites)
        if not NAME_RE.match(name):
            errors.append((
                f"{name!r} ({where}) does not match "
                "kafka_<subsystem>_<name>",
                anchor,
            ))
        if len(sites) > 1:
            errors.append((
                f"{name!r} registered at {len(sites)} sites ({where}); "
                "each metric must have exactly one owner",
                anchor,
            ))
        kinds = {k for _, _, k in sites}
        if len(kinds) > 1:
            errors.append((
                f"{name!r} registered as multiple kinds "
                f"({sorted(kinds)}; {where})",
                anchor,
            ))
    return errors


def casing_errors(
    events: Dict[str, List[Site]], phases: Dict[str, List[Site]],
) -> List[Tuple[str, Site]]:
    """Off-convention emit()/span() literals."""
    errors: List[Tuple[str, Site]] = []
    for namespace, names in (("event", events), ("phase", phases)):
        for name, sites in names.items():
            if not EVENT_NAME_RE.match(name):
                where = ", ".join(f"{p}:{ln}" for p, ln in sites)
                errors.append((
                    f"{namespace} name {name!r} ({where}) is not "
                    "lower_snake_case",
                    min(sites),
                ))
    return errors


def collision_errors(
    events: Dict[str, List[Site]], phases: Dict[str, List[Site]],
) -> List[Tuple[str, Site]]:
    """Near-duplicate literals and event/phase namespace collisions."""
    by_norm: Dict[str, Dict[Tuple[str, str], List[Site]]] = {}
    for namespace, names in (("event", events), ("phase", phases)):
        for name, sites in names.items():
            norm = name.replace("_", "").lower()
            by_norm.setdefault(norm, {})[(namespace, name)] = sites
    errors: List[Tuple[str, Site]] = []
    for norm, variants in sorted(by_norm.items()):
        literals = {name for _, name in variants}
        namespaces = {ns for ns, _ in variants}
        anchor = min(s for sites in variants.values() for s in sites)
        where = "; ".join(
            f"{ns} {name!r} at " + ", ".join(f"{p}:{ln}" for p, ln in sites)
            for (ns, name), sites in sorted(variants.items())
        )
        if len(literals) > 1:
            errors.append((
                f"near-duplicate names {sorted(literals)} ({where}) — "
                "case/underscore variants of one name",
                anchor,
            ))
        elif len(namespaces) > 1:
            errors.append((
                f"{next(iter(literals))!r} used as both an event and a "
                f"span phase ({where}) — one name, one meaning",
                anchor,
            ))
    return errors


# ---------------------------------------------------------------------------
# kafkalint rules: per-file collection, cross-file finalize.
# ---------------------------------------------------------------------------

class _VocabRule(Rule):
    """Shared collection: registrations, events, phases over the
    eligible subset of the walk."""

    def __init__(self) -> None:
        self.regs: Dict[str, List[Tuple[str, int, str]]] = {}
        self.events: Dict[str, List[Site]] = {}
        self.phases: Dict[str, List[Site]] = {}
        self.saw_eligible = False

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not _eligible(ctx.rel):
            return ()
        self.saw_eligible = True
        text = ctx.text
        for m in REGISTRATION_RE.finditer(text):
            kind, name = m.group(1), m.group(2)
            line = text.count("\n", 0, m.start()) + 1
            self.regs.setdefault(name, []).append((ctx.rel, line, kind))
        for regex, out in ((EMIT_RE, self.events), (SPAN_RE, self.phases)):
            for m in regex.finditer(text):
                line = text.count("\n", 0, m.start()) + 1
                out.setdefault(m.group(1), []).append((ctx.rel, line))
        return ()

    def _findings(self, errors: List[Tuple[str, Site]]
                  ) -> Iterable[Finding]:
        for msg, (path, line) in errors:
            yield Finding(path=path, line=line, rule=self.name,
                          message=msg)


@register
class MetricName(_VocabRule):
    name = "metric-name"
    description = (
        "metric registrations must match kafka_<subsystem>_<name>, have "
        "exactly one owning site, and exactly one kind (the BASELINE.md "
        "Observability contract)"
    )

    def finalize(self) -> Iterable[Finding]:
        if self.saw_eligible and not self.regs:
            yield Finding(
                path="kafka_tpu", line=0, rule=self.name,
                message=(
                    "no metric registrations found — the scanner or the "
                    "telemetry wiring is broken"
                ),
            )
            return
        yield from self._findings(registration_errors(self.regs))


@register
class EventName(_VocabRule):
    name = "event-name"
    description = (
        "emit() event and span() phase literals must be "
        "lower_snake_case — off-convention casing silently forks "
        "grep/dashboard queries"
    )

    def finalize(self) -> Iterable[Finding]:
        yield from self._findings(casing_errors(self.events, self.phases))


@register
class EventCollision(_VocabRule):
    name = "event-collision"
    description = (
        "near-duplicate event/phase literals (case or underscore "
        "variants) and one name used as both an event and a span phase "
        "— one name, one meaning"
    )

    def finalize(self) -> Iterable[Finding]:
        yield from self._findings(
            collision_errors(self.events, self.phases)
        )


# ---------------------------------------------------------------------------
# Legacy check_metric_names API (the shim re-exports all of this).
# ---------------------------------------------------------------------------

def iter_sources(root: str):
    for entry in SCAN:
        path = os.path.join(root, entry)
        if os.path.isfile(path):
            yield path
        else:
            for dirpath, _dirnames, filenames in os.walk(path):
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def collect_registrations(
    root: str,
) -> Dict[str, List[Tuple[str, int, str]]]:
    """name -> [(relative_path, line, kind), ...] over the scanned tree."""
    out: Dict[str, List[Tuple[str, int, str]]] = {}
    for path in iter_sources(root):
        with open(path) as f:
            text = f.read()
        for m in REGISTRATION_RE.finditer(text):
            kind, name = m.group(1), m.group(2)
            line = text.count("\n", 0, m.start()) + 1
            rel = os.path.relpath(path, root)
            out.setdefault(name, []).append((rel, line, kind))
    return out


def collect_names(root: str, regex: re.Pattern,
                  ) -> Dict[str, List[Site]]:
    """literal first-arg -> [(relative_path, line), ...] for ``regex``."""
    out: Dict[str, List[Site]] = {}
    for path in iter_sources(root):
        with open(path) as f:
            text = f.read()
        for m in regex.finditer(text):
            line = text.count("\n", 0, m.start()) + 1
            rel = os.path.relpath(path, root)
            out.setdefault(m.group(1), []).append((rel, line))
    return out


def check_event_and_phase_names(root: str) -> List[str]:
    """emit()/span() vocabulary violations (empty list = clean)."""
    events = collect_names(root, EMIT_RE)
    phases = collect_names(root, SPAN_RE)
    return [m for m, _ in casing_errors(events, phases)] + [
        m for m, _ in collision_errors(events, phases)
    ]


def check(root: str) -> List[str]:
    """All convention violations in ``root`` (empty list = clean)."""
    errors: List[str] = []
    regs = collect_registrations(root)
    if not regs:
        errors.append(
            f"no metric registrations found under {root!r} — the scanner "
            "or the telemetry wiring is broken"
        )
    errors.extend(m for m, _ in registration_errors(regs))
    errors.extend(check_event_and_phase_names(root))
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    errors = check(root)
    regs = collect_registrations(root)
    if errors:
        for e in errors:
            print(f"check_metric_names: {e}", file=sys.stderr)
        return 1
    events = collect_names(root, EMIT_RE)
    phases = collect_names(root, SPAN_RE)
    print(
        f"check_metric_names: {len(regs)} metric names OK "
        f"({sum(len(s) for s in regs.values())} registrations), "
        f"{len(events)} event names, {len(phases)} span phases"
    )
    return 0
