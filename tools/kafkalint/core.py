"""kafkalint framework: rule registry, file walking, suppressions, baseline.

The engine's hardest-won invariants — one device->host read per window,
float32-only device math, TraceContext re-installed on every spawned
thread — are runtime-enforced only on the paths tier-1 happens to execute.
kafkalint checks them statically: one ``ast`` parse per production source,
a plugin rule registry walked over every file, inline suppressions, and a
checked-in baseline for grandfathered findings.

Vocabulary:

- :class:`Finding` — one (rule, path, line, message) violation.
- :class:`Rule` — plugin base class.  ``check_file(ctx)`` yields findings
  for one file; ``finalize()`` yields cross-file findings after the walk
  (the telemetry-vocabulary rules aggregate across the tree).  Register
  concrete rules with :func:`register`.
- :class:`FileContext` — one scanned file: text, lines, parsed AST, and
  the suppression map.
- :func:`run_lint` — the single-pass driver: walk, check, suppress,
  baseline-filter.

Suppressions: ``# kafkalint: disable=<rule>[,<rule>...]`` either trailing
on the flagged line or on a comment line immediately above it
(``disable=all`` silences every rule for that line).  An optional reason
after the rule list is encouraged: ``# kafkalint: disable=implicit-f64 —
host-only constant table``.

Baseline: a JSON list of ``{"rule", "path", "contains", "reason"}``
entries (``tools/kafkalint/baseline.json`` of the linted root).  A finding
is grandfathered when an entry's rule and path match and ``contains`` is a
substring of the message.  Entries that match nothing are STALE and
reported as ``stale-baseline`` findings — the baseline only shrinks.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Type

#: production sources walked, relative to the linted root.
SCAN = ("kafka_tpu", "bench.py", "tools")

#: default baseline location, relative to the linted root.
BASELINE_RELPATH = os.path.join("tools", "kafkalint", "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*kafkalint:\s*disable=([a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)"
)


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    """One violation at a source location (path is root-relative posix)."""

    path: str
    line: int
    rule: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Rule:
    """Plugin base.  Subclasses set ``name``/``description`` and override
    ``check_file`` (per-file findings) and/or ``finalize`` (cross-file
    findings, emitted once after every file was visited).  One instance
    lives per :func:`run_lint` call, so rules may accumulate state."""

    name: str = ""
    description: str = ""

    def check_file(self, ctx: "FileContext") -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()


#: rule name -> rule class (populated by @register at import time).
REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.name:
        raise ValueError(f"{cls.__name__} has no rule name")
    if cls.name in REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    REGISTRY[cls.name] = cls
    return cls


class FileContext:
    """One scanned source file: text, lines, AST, suppression map."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8", errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as exc:
            self.parse_error = exc
        #: 1-based line -> set of rule names disabled on that line.
        self._supp: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                self._supp[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()
                }

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def suppressed(self, line: int, rule: str) -> bool:
        """True when ``rule`` is disabled for ``line`` — by a trailing
        directive on the line itself, or by a directive anywhere in the
        contiguous block of pure-comment lines immediately above it."""
        rules = set(self._supp.get(line, ()))
        above = line - 1
        while above >= 1 and self.line_text(above).lstrip().startswith("#"):
            rules |= self._supp.get(above, set())
            above -= 1
        return "all" in rules or rule in rules


def iter_files(root: str) -> Iterable[str]:
    """Absolute paths of every ``.py`` in the scan set, sorted."""
    for entry in SCAN:
        path = os.path.join(root, entry)
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames.sort()
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_scanned: int
    rules: List[str]
    baseline_path: Optional[str]
    baseline_entries: int
    baseline_matched: int

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_json(self) -> dict:
        """The ``--json`` schema (stable; tests pin it)."""
        return {
            "version": 1,
            "root": None,  # filled by the CLI, which knows the arg form
            "files_scanned": self.files_scanned,
            "rules": list(self.rules),
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "baseline": {
                "path": self.baseline_path,
                "entries": self.baseline_entries,
                "matched": self.baseline_matched,
            },
        }


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError(f"baseline {path}: expected a JSON list")
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "path" not in e:
            raise ValueError(
                f"baseline {path}: each entry needs 'rule' and 'path'"
            )
    return entries


def _apply_baseline(findings: List[Finding], entries: List[dict],
                    baseline_rel: str) -> List[Finding]:
    """Drop grandfathered findings; report stale entries as findings."""
    hits = [0] * len(entries)

    def grandfathered(f: Finding) -> bool:
        ok = False
        for i, e in enumerate(entries):
            if (e["rule"] == f.rule and e["path"] == f.path
                    and e.get("contains", "") in f.message):
                hits[i] += 1
                ok = True
        return ok

    kept = [f for f in findings if not grandfathered(f)]
    for i, e in enumerate(entries):
        if hits[i] == 0:
            kept.append(Finding(
                path=baseline_rel, line=0, rule="stale-baseline",
                message=(
                    f"baseline entry for [{e['rule']}] at {e['path']} "
                    f"matches no current finding — remove it "
                    f"(reason was: {e.get('reason', 'none given')!r})"
                ),
            ))
    return kept


def make_rules(rule_names: Optional[Sequence[str]] = None) -> List[Rule]:
    # Import for the registration side effect; late so core stays
    # importable on its own (the shim path).
    from . import (  # noqa: F401
        rules_batch, rules_devprof, rules_jax, rules_perf,
        rules_placement, rules_programs, rules_publisher,
        rules_quality, rules_request, rules_runtime, rules_slo,
        rules_smoother, rules_telemetry,
    )

    names = sorted(REGISTRY) if rule_names is None else list(rule_names)
    unknown = [n for n in names if n not in REGISTRY]
    if unknown:
        raise ValueError(
            f"unknown rule(s) {unknown}; known: {sorted(REGISTRY)}"
        )
    return [REGISTRY[n]() for n in names]


def run_lint(root: str, rule_names: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             use_baseline: bool = True) -> LintResult:
    """Walk ``root``'s scan set once and return every surviving finding.

    ``baseline_path`` defaults to ``<root>/tools/kafkalint/baseline.json``
    when that file exists (so linting a fixture tree applies no baseline).
    """
    root = os.path.abspath(root)
    rules = make_rules(rule_names)
    findings: List[Finding] = []
    contexts: Dict[str, FileContext] = {}
    n_files = 0
    for path in iter_files(root):
        n_files += 1
        ctx = FileContext(root, path)
        contexts[ctx.rel] = ctx
        if ctx.parse_error is not None:
            findings.append(Finding(
                path=ctx.rel, line=ctx.parse_error.lineno or 0,
                rule="parse-error",
                message=f"could not parse: {ctx.parse_error.msg}",
            ))
            continue
        for rule in rules:
            findings.extend(rule.check_file(ctx))
    for rule in rules:
        findings.extend(rule.finalize())

    kept = [
        f for f in findings
        if f.path not in contexts
        or not contexts[f.path].suppressed(f.line, f.rule)
    ]

    n_entries = matched = 0
    if use_baseline:
        if baseline_path is None:
            candidate = os.path.join(root, BASELINE_RELPATH)
            baseline_path = candidate if os.path.isfile(candidate) else None
        if baseline_path is not None:
            entries = load_baseline(baseline_path)
            n_entries = len(entries)
            before = len(kept)
            kept = _apply_baseline(
                kept, entries,
                os.path.relpath(baseline_path, root).replace(os.sep, "/"),
            )
            matched = before - sum(
                1 for f in kept if f.rule != "stale-baseline"
            )
    else:
        baseline_path = None

    return LintResult(
        findings=sorted(kept),
        files_scanned=n_files,
        rules=[r.name for r in rules],
        baseline_path=baseline_path,
        baseline_entries=n_entries,
        baseline_matched=matched,
    )
