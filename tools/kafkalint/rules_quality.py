"""Assimilation-quality convention rule (ISSUE 11).

``magic-quality-threshold`` encodes the quality-layer convention: every
consistency / drift threshold literal — the chi^2 CONSISTENT band, the
EWMA/CUSUM sentinel parameters, the obs.bias magnitude — lives in the
sanctioned module-level config block of
``kafka_tpu/telemetry/quality.py``, where BASELINE.md documents it and
every consumer (engine ledger, quality_report CLI, serve responses,
admission shedding) reads the SAME value.  A numeric quality-threshold
literal anywhere else is a second, silently-divergent definition of
"consistent": the scorecard would then disagree with the ledger that
fed it.

Detection is vocabulary-based: a numeric literal assigned to a name —
or passed as a keyword argument — whose identifier mentions the quality
vocabulary (``chi2``, ``consistent``/``consistency``, ``ewma``,
``cusum``, ``drift``, ``quality``) is a finding outside the sanctuary's
module level.  Booleans and non-literal expressions are out of scope
(thresholds are numbers; flags and derived values are not thresholds).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from .core import FileContext, Finding, Rule, register

#: the ONE module whose top-level assignments may carry quality
#: threshold literals (the documented config block).
QUALITY_SANCTUARY = "kafka_tpu/telemetry/quality.py"

_VOCAB_RE = re.compile(
    r"(chi2|consistency|consistent|ewma|cusum|drift|quality)", re.I
)


def _numeric_literal(node: ast.AST) -> bool:
    """True for an int/float literal (unary +/- included; bools are
    flags, not thresholds)."""
    if isinstance(node, ast.UnaryOp) and \
            isinstance(node.op, (ast.UAdd, ast.USub)):
        node = node.operand
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool))


@register
class MagicQualityThreshold(Rule):
    name = "magic-quality-threshold"
    description = (
        "numeric consistency/drift threshold literal (chi2 band, "
        "EWMA/CUSUM parameter, quality limit) outside the sanctioned "
        "module-level config block of kafka_tpu/telemetry/quality.py — "
        "a second definition of 'consistent' silently diverges from "
        "the one the ledger, the scorecard and admission all share"
    )

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if ctx.tree is None:
            return ()
        sanctuary = ctx.rel == QUALITY_SANCTUARY
        sanctioned_lines = set()
        if sanctuary:
            # Module-level assignments ARE the config block.
            for stmt in ctx.tree.body:
                if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                    sanctioned_lines.add(stmt.lineno)
        findings: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            findings.append(Finding(
                path=ctx.rel, line=node.lineno, rule=self.name,
                message=(
                    f"{what} sets a quality-threshold literal outside "
                    f"the sanctioned config block "
                    f"({QUALITY_SANCTUARY}) — import the constant (or "
                    "add it to the block) so every consumer shares one "
                    "definition of consistency"
                ),
            ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                if node.lineno in sanctioned_lines:
                    continue
                value = node.value
                if value is None or not _numeric_literal(value):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name) and \
                            _VOCAB_RE.search(t.id):
                        flag(node, f"assignment to {t.id!r}")
                        break
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg and _VOCAB_RE.search(kw.arg) and \
                            _numeric_literal(kw.value):
                        flag(kw.value, f"keyword argument {kw.arg!r}")
        return findings
