"""Repo tooling: benchmark comparison, roofline analysis, static lints.

A package so ``python -m tools.kafkalint`` works from the repo root; the
individual scripts (``bench_compare.py``, ``roofline.py``, ...) remain
directly runnable as before.
"""
