"""slo_report — error-budget + alert-episode report over alerts.jsonl.

Renders the ``alerts.jsonl`` ledgers the SLO engine writes
(``kafka_tpu.telemetry.slo``) into an operator report — from the
ledger ALONE, no live process required:

- per-objective error budget: the last recorded consumed / remaining
  fractions and time-to-exhaustion estimate (full budget when an
  objective never alerted — a clean ledger IS the clean report);
- alert episodes: every pending -> firing -> resolved arc with its
  firing duration (open episodes flagged), reconstructed by
  ``slo.episodes_from`` — the same arithmetic the tier-1 chaos test
  pins against the live engine's state;
- worst burn rates seen per objective (fast and slow window).

Usage:
    python -m tools.slo_report LEDGER_OR_DIR [MORE...] [--json]

Arguments may be ``alerts.jsonl`` files or directories (searched
recursively); rotated ``alerts.jsonl.N`` segments are folded in
oldest-first automatically.  Torn ledger tails are skipped and
counted, never fatal.

Exit codes: 0 (report rendered; a firing alert is a report, not an
error), 2 usage / no ledger found.  Strictly read-only.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List

from kafka_tpu.telemetry import slo


def find_ledgers(paths: List[str]) -> List[str]:
    """Resolve CLI arguments to ledger files (dirs searched recursively
    for ``alerts.jsonl``), sorted and deduplicated."""
    found: List[str] = []
    for arg in paths:
        if os.path.isfile(arg):
            found.append(arg)
        elif os.path.isdir(arg):
            for dirpath, dirnames, filenames in os.walk(arg):
                dirnames.sort()
                if slo.ALERTS_FILENAME in filenames:
                    found.append(
                        os.path.join(dirpath, slo.ALERTS_FILENAME)
                    )
    return sorted(set(found))


def build_report(ledgers: List[str]) -> dict:
    """The ``--json`` payload: per-ledger records folded into one
    per-objective budget/episode/burn view."""
    records: List[dict] = []
    skipped = 0
    sources: List[dict] = []
    for path in ledgers:
        recs, n_skipped = slo.load_alerts(path)
        records.extend(recs)
        skipped += n_skipped
        sources.append({"path": path, "records": len(recs),
                        "skipped": n_skipped})
    records.sort(key=lambda r: float(r.get("ts") or 0.0))
    episodes = slo.episodes_from(records)
    objectives: Dict[str, dict] = {}
    for rec in records:
        name = rec["objective"]
        obj = objectives.setdefault(name, {
            "records": 0,
            "worst_burn_fast": 0.0,
            "worst_burn_slow": 0.0,
            "budget": {"consumed": 0.0, "remaining": 1.0,
                       "tte_s": None},
            "episodes": 0,
            "open_episodes": 0,
        })
        obj["records"] += 1
        obj["worst_burn_fast"] = max(
            obj["worst_burn_fast"], float(rec.get("burn_fast") or 0.0)
        )
        obj["worst_burn_slow"] = max(
            obj["worst_burn_slow"], float(rec.get("burn_slow") or 0.0)
        )
        if isinstance(rec.get("budget"), dict):
            # Records are ts-sorted: the last one wins — the budget
            # remaining at the newest ledger write.
            obj["budget"] = rec["budget"]
    for ep in episodes:
        obj = objectives.get(ep["objective"])
        if obj is None:
            continue
        obj["episodes"] += 1
        if ep["resolved_ts"] is None:
            obj["open_episodes"] += 1
    return {
        "sources": sources,
        "records": len(records),
        "skipped_lines": skipped,
        "objectives": objectives,
        "episodes": episodes,
    }


def render(report: dict) -> str:
    lines = [
        f"slo_report: {report['records']} ledger record(s) from "
        f"{len(report['sources'])} ledger(s)"
        + (f", {report['skipped_lines']} torn line(s) skipped"
           if report["skipped_lines"] else ""),
    ]
    if not report["objectives"]:
        lines.append("  no alert activity recorded — every objective "
                     "holds its full error budget")
        return "\n".join(lines)
    lines.append("error budgets (per objective, last recorded):")
    for name, obj in sorted(report["objectives"].items()):
        b = obj["budget"]
        tte = "-" if b.get("tte_s") is None else f"{b['tte_s']:g}s"
        lines.append(
            f"  {name}: consumed={b.get('consumed', 0):g} "
            f"remaining={b.get('remaining', 1):g} tte={tte}  "
            f"worst burn fast={obj['worst_burn_fast']:g} "
            f"slow={obj['worst_burn_slow']:g}  "
            f"episodes={obj['episodes']}"
            + (f" ({obj['open_episodes']} OPEN)"
               if obj["open_episodes"] else "")
        )
    if report["episodes"]:
        lines.append("alert episodes:")
        for ep in report["episodes"]:
            dur = "OPEN" if ep["resolved_ts"] is None else (
                f"{ep['duration_s']:g}s" if ep.get("duration_s")
                is not None else "?"
            )
            lines.append(
                f"  {ep['objective']} [{ep['severity']}] "
                f"firing@{ep['firing_ts']} duration={dur} "
                f"burn fast={ep.get('burn_fast')} "
                f"slow={ep.get('burn_slow')}"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("paths", nargs="+",
                    help="alerts.jsonl files or directories to search "
                         "recursively")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable dump instead of the report")
    args = ap.parse_args(argv)
    ledgers = find_ledgers(args.paths)
    if not ledgers:
        print("slo_report: no alerts.jsonl found under the given "
              "paths", file=sys.stderr)
        return 2
    report = build_report(ledgers)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
