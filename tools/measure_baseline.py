"""Measure the BASELINE.md table rows — end-to-end, host I/O included.

Each mode generates a synthetic on-disk data tree at the row's problem
scale (PROSAIL-consistent S2 granules, ``testing.fixtures``), runs the
REAL driver path (chunked ``cli.drivers.run_config`` or the engine
directly) on the default JAX device, and prints one JSON line.  Data
generation is excluded from the timed window; reading, warping,
gathering, solving and GeoTIFF writing are all inside it.

Modes
-----
- ``barrax``  — the reference's S2-Barrax problem scale (pivot mask,
  204x235 grid, 2-day grid; ``kafka_test_S2.py:189-205``).
- ``tile``    — one full Sentinel-2 L2A tile (10980x10980 default),
  single date, chunked.
- ``annual``  — an annual series (~50 acquisitions) on one sub-tile,
  chunked, temporal KF chain.
- ``oracle``  — the reference algorithm (SciPy sparse + SuperLU) on this
  host's CPU for px/s context (same solve, no I/O — generous to it).

Usage: ``python tools/measure_baseline.py tile --size 10980 --chunk 1098``
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _granule_tree(root, dates, size, noise=0.002, dtype=np.uint16):
    from kafka_tpu.testing.fixtures import DEFAULT_GEO, make_s2_granule_tree

    if os.path.isdir(f"{root}/s2"):
        print(f"reusing existing granule tree {root}/s2", file=sys.stderr)
        return f"{root}/s2", DEFAULT_GEO
    t0 = time.perf_counter()
    make_s2_granule_tree(
        f"{root}/s2", dates, ny=size, nx=size, noise=noise, dtype=dtype
    )
    print(
        f"generated {len(dates)} x {size}x{size} granules "
        f"in {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )
    return f"{root}/s2", DEFAULT_GEO


def _mask_tif(root, size, geo):
    from kafka_tpu.io import write_geotiff

    path = f"{root}/mask.tif"
    write_geotiff(path, np.ones((size, size), np.uint8), geo)
    return path


def _s2_config(data_folder, mask_path, outdir, dates, chunk):
    from kafka_tpu.cli.run_s2 import default_config

    cfg = default_config()
    cfg.data_folder = data_folder
    cfg.state_mask = mask_path
    cfg.output_folder = outdir
    cfg.chunk_size = (chunk, chunk)
    # Grid boundaries BRACKET the acquisitions (windows are half-open
    # intervals ending at each grid point, so a grid starting ON the first
    # acquisition date would never assimilate it).
    cfg.start = dates[0] - datetime.timedelta(days=1)
    cfg.end = dates[-1] + datetime.timedelta(days=1)
    # The measured configuration opts into the fast float16 wire (on-disk
    # rasters stay float32; sigma clamped at 65504 — io.output): the
    # device link is the e2e bottleneck and this is the documented
    # performance mode.  The DEFAULT stays bit-exact float32.
    cfg.wire_dtype = "float16"
    # Host-path parallelism scales with cores (1 on this bench host):
    # N prefetch readers with ordered delivery; the per-band decode pool
    # inside the S2 reader sizes itself from os.cpu_count().
    cfg.prefetch_workers = min(4, os.cpu_count() or 1)
    return cfg


def _run_chunked(size, chunk, n_dates, step_days=2, keep=None):
    from kafka_tpu.cli.drivers import prosail_aux_builder, run_config

    root = keep or tempfile.mkdtemp(prefix="kafka_baseline_")
    try:
        dates = [
            datetime.datetime(2017, 7, 1) + datetime.timedelta(
                days=step_days * i
            )
            for i in range(n_dates)
        ]
        data, geo = _granule_tree(root, dates, size)
        mask = _mask_tif(root, size, geo)
        cfg = _s2_config(data, mask, f"{root}/out", dates, chunk)
        cfg.step_days = step_days
        t0 = time.perf_counter()
        stats = run_config(cfg, aux_builder=prosail_aux_builder)
        wall = time.perf_counter() - t0
        n_px = stats["pixels"]
        # GUARD: every chunk must actually have assimilated every date —
        # a mis-built time grid silently yields a no-op run and a garbage
        # throughput figure.
        expected = stats["chunks_with_pixels"] * n_dates
        if stats.get("dates_assimilated", -1) != expected:
            raise RuntimeError(
                f"assimilated {stats.get('dates_assimilated')} chunk-dates, "
                f"expected {expected} — time grid/window mismatch"
            )
        px_steps_s = n_px * n_dates / wall
        return {
            "n_pixels": n_px,
            "n_dates": n_dates,
            "chunks": stats["run"],
            "wall_s": round(wall, 2),
            "pixel_steps_per_s": round(px_steps_s, 1),
        }
    finally:
        if keep is None:
            shutil.rmtree(root, ignore_errors=True)


def _run_joint(size, chunk, n_s2, n_s1, keep=None):
    """Multi-sensor row: S2 optical + S1 SAR interleaved on the shared
    11-parameter joint state (``cli.run_joint``)."""
    from kafka_tpu.cli.drivers import prosail_aux_builder, run_config
    from kafka_tpu.cli.run_joint import default_config
    from kafka_tpu.engine.priors import joint_prior
    from kafka_tpu.testing.fixtures import make_s1_series

    root = keep or tempfile.mkdtemp(prefix="kafka_joint_")
    try:
        s2_dates = [
            datetime.datetime(2017, 7, 1) + datetime.timedelta(days=4 * i)
            for i in range(n_s2)
        ]
        s1_dates = [
            datetime.datetime(2017, 7, 3, 17) +
            datetime.timedelta(days=4 * i)
            for i in range(n_s1)
        ]
        truth10 = np.asarray(joint_prior().prior.mean)[:10].copy()
        truth10 = truth10.astype(np.float32)
        truth10[6] = np.float32(np.exp(-1.5))
        data, geo = _granule_tree(root, s2_dates, size)
        if not os.path.isdir(f"{root}/s1"):
            make_s1_series(
                f"{root}/s1", s1_dates, truth_lai=3.0, truth_sm=0.4,
                ny=size, nx=size, geo=geo, noise=0.01,
            )
        mask = _mask_tif(root, size, geo)
        cfg = default_config()
        cfg.data_folder = data
        cfg.extra["s1_folder"] = f"{root}/s1"
        cfg.state_mask = mask
        cfg.output_folder = f"{root}/out"
        cfg.chunk_size = (chunk, chunk)
        all_dates = sorted(s2_dates + s1_dates)
        cfg.start = all_dates[0] - datetime.timedelta(days=1)
        cfg.end = all_dates[-1] + datetime.timedelta(days=1)
        cfg.step_days = 2
        cfg.wire_dtype = "float16"  # fast-wire opt-in (see _s2_config)
        n_dates = len(all_dates)
        t0 = time.perf_counter()
        stats = run_config(cfg, aux_builder=prosail_aux_builder)
        wall = time.perf_counter() - t0
        expected = stats["chunks_with_pixels"] * n_dates
        if stats.get("dates_assimilated", -1) != expected:
            raise RuntimeError(
                f"assimilated {stats.get('dates_assimilated')} chunk-dates,"
                f" expected {expected}"
            )
        return {
            "n_pixels": stats["pixels"],
            "n_dates": n_dates,
            "n_s2": len(s2_dates), "n_s1": len(s1_dates),
            "wall_s": round(wall, 2),
            "pixel_steps_per_s": round(
                stats["pixels"] * n_dates / wall, 1
            ),
        }
    finally:
        if keep is None:
            shutil.rmtree(root, ignore_errors=True)


def main():
    from kafka_tpu.utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode",
                    choices=["barrax", "tile", "annual", "joint", "oracle"])
    ap.add_argument("--size", type=int, default=None)
    # 1098^2 px/chunk: a 2196^2 PROSAIL chunk (4.8M px) exceeds the v5e
    # 16 GB HBM budget (the (n,p,p) information matrices alone are ~2 GB
    # each and several are live through the solve).
    ap.add_argument("--chunk", type=int, default=1098)
    ap.add_argument("--dates", type=int, default=None)
    ap.add_argument("--step-days", type=int, default=2)
    ap.add_argument("--oracle-n", type=int, default=16384)
    ap.add_argument("--keep", default=None,
                    help="keep generated tree/outputs in this directory")
    args = ap.parse_args()

    if args.mode == "barrax":
        sys.path.insert(
            0,
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        from bench import bench_end_to_end

        px_steps_s, device_frac, n_pix = bench_end_to_end()
        row = {
            "row": "barrax", "n_pixels": n_pix,
            "pixel_steps_per_s": round(px_steps_s, 1),
            "device_fraction": round(device_frac, 3),
        }
    elif args.mode == "tile":
        row = {"row": "tile", **_run_chunked(
            args.size or 10980, args.chunk, args.dates or 1,
            keep=args.keep,
        )}
    elif args.mode == "annual":
        row = {"row": "annual", **_run_chunked(
            args.size or 1098, min(args.chunk, args.size or 1098),
            args.dates or 50, step_days=args.step_days, keep=args.keep,
        )}
    elif args.mode == "joint":
        size = args.size or 1098
        row = {"row": "joint", **_run_joint(
            size, min(args.chunk, size),
            n_s2=(args.dates or 12) // 2, n_s1=(args.dates or 12) // 2,
            keep=args.keep,
        )}
    else:
        from bench import bench_oracle

        px_s, ms_median, ms_spread, ms_min = bench_oracle(args.oracle_n)
        row = {
            "row": "oracle", "n_pixels": args.oracle_n,
            "px_per_s": round(px_s, 1),
            "ms_median": round(ms_median, 1),
            "ms_spread": round(ms_spread, 1),
            "ms_min": round(ms_min, 1),
        }
    print(json.dumps(row))


if __name__ == "__main__":
    main()
