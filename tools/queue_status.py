"""queue_status — read-only view of a multi-host chunk-queue outdir.

Renders the lease-queue state (``kafka_tpu.shard.queue``, BASELINE.md
"Multi-host queue") for operators and for the chaos tests to assert
against: done / failed / leased-live / leased-expired / pending counts,
plus per-worker lease ownership.  Strictly read-only — it never touches
a marker, so it is safe to run against a live fleet.

Usage:
    python -m tools.queue_status /path/to/outdir [--json]
        [--telemetry-dir DIR]

``--telemetry-dir`` joins the fleet plane's live heartbeat snapshots
(``kafka_tpu.telemetry.live``) against lease ownership: each worker
line gains its heartbeat age and a DEAD flag when the heartbeat went
stale without a clean shutdown — "who holds this lease" and "is that
worker still breathing" in one view.

Exit codes: 0 (state rendered, whatever it is), 2 usage/missing outdir.
PENDING counts need the ``.queue_manifest.json`` a queue worker writes
at startup; without one, only chunks with marker files are visible and
the render says so.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _liveness_text(info) -> str:
    if info is None:
        return "  (no live snapshot)"
    if info["dead"]:
        return f"  DEAD (heartbeat {info['age_s']:.1f}s stale)"
    if info["final"]:
        return f"  exited cleanly {info['age_s']:.1f}s ago"
    return f"  heartbeat {info['age_s']:.1f}s ago"


def render(status: dict) -> str:
    """Human-readable one-screen summary of a ``queue_status()`` dict
    (plus the optional ``liveness`` join)."""
    c = status["counts"]
    liveness = status.get("liveness")
    lines = [
        f"queue: {status['outdir']}",
        f"chunks: {status['n_chunks']}"
        + ("" if status["manifest"]
           else "  (no manifest — pending chunks invisible)"),
        f"  done            {c['done']}",
        f"  failed          {c['failed']}",
        f"  leased (live)   {c['leased']}",
        f"  leased (expired){c['lease_expired']:>2}   <- reclaimable",
        f"  pending         {c['pending']}",
    ]
    if status["workers"]:
        lines.append("workers:")
        for owner in sorted(status["workers"]):
            w = status["workers"][owner]
            parts = []
            if w["live"]:
                parts.append(f"live={','.join(w['live'])}")
            if w["expired"]:
                parts.append(f"EXPIRED={','.join(w['expired'])}")
            alive = ""
            if liveness is not None:
                alive = _liveness_text(liveness.get(owner))
            lines.append(f"  {owner}: {' '.join(parts)}{alive}")
    interesting = {
        p: e for p, e in status["chunks"].items()
        if e["state"] not in ("done",)
    }
    if interesting:
        lines.append("open chunks:")
        for prefix in sorted(interesting):
            e = interesting[prefix]
            extra = ""
            if "owner" in e:
                extra = (f"  owner={e['owner']}"
                         f" requeues={e.get('requeues', 0)}")
                if "deadline_in_s" in e:
                    extra += f" deadline_in={e['deadline_in_s']:+.1f}s"
            lines.append(f"  {prefix}: {e['state']}{extra}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("outdir", help="queue output directory to inspect")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable dump instead of the summary")
    ap.add_argument("--telemetry-dir", default=None,
                    help="telemetry root holding live_*.json heartbeat "
                         "snapshots; joins worker liveness (heartbeat "
                         "age, dead flag) against lease ownership")
    ap.add_argument("--ttl-s", type=float, default=None,
                    help="heartbeat staleness that flags a worker dead "
                         "(default: 3x each snapshot's own interval)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.outdir):
        print(f"queue_status: no such directory: {args.outdir}",
              file=sys.stderr)
        return 2
    from kafka_tpu.shard.queue import queue_status

    status = queue_status(args.outdir)
    if args.telemetry_dir:
        from kafka_tpu.telemetry.aggregate import (
            load_live_snapshots, worker_liveness,
        )

        status["liveness"] = worker_liveness(
            load_live_snapshots(args.telemetry_dir), ttl_s=args.ttl_s,
        )
    if args.json:
        print(json.dumps(status, indent=2, sort_keys=True))
    else:
        print(render(status))
    return 0


if __name__ == "__main__":
    sys.exit(main())
