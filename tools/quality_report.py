"""quality_report — per-tile assimilation-quality scorecards.

Renders the ``quality.jsonl`` ledgers the engine and the serving daemon
write (``kafka_tpu.telemetry.quality``) into an operator scorecard:
per-tile/per-band consistency timelines, drift episodes, and the
worst-N dates — from the ledger ALONE, no live process required.
Verdicts are re-derived from the recorded per-band chi^2 ratios with
the same ``verdict_for`` bands the engine used, so the report doubles
as a consistency check of the ledger itself (``verdict`` vs
``recomputed`` per date).

Usage:
    python -m tools.quality_report LEDGER_OR_DIR [MORE...] [--json]
        [--worst N]

Arguments may be ``quality.jsonl`` files or directories (searched
recursively).  Torn ledger tails — a process killed mid-append — are
skipped and counted, never fatal.

Exit codes: 0 (report rendered; drift is a report, not an error),
2 usage / no ledger found.  Strictly read-only.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional

from kafka_tpu.telemetry import quality

#: timeline glyphs per verdict (drifting dates are UPPERCASED already;
#: they additionally carry a trailing ``!``).
_GLYPH = {
    quality.CONSISTENT: "C",
    quality.OVERCONFIDENT: "O",
    quality.UNDERCONFIDENT: "U",
    quality.NO_OBS: ".",
}


def find_ledgers(paths: List[str]) -> List[str]:
    """Resolve CLI arguments to ledger files (dirs searched recursively
    for ``quality.jsonl``), sorted and deduplicated."""
    found: List[str] = []
    for arg in paths:
        if os.path.isfile(arg):
            found.append(arg)
        elif os.path.isdir(arg):
            for dirpath, dirnames, filenames in os.walk(arg):
                dirnames.sort()
                if quality.LEDGER_FILENAME in filenames:
                    found.append(
                        os.path.join(dirpath, quality.LEDGER_FILENAME)
                    )
    return sorted(set(found))


def _tile_key(rec: dict, source: str) -> str:
    """Group records by tile/chunk prefix, falling back to the ledger's
    parent directory name for prefix-less (single-run) ledgers.
    Reanalysis (``smoothed``) records get their own timeline per tile so
    the forward filter and the RTS pass are scored separately."""
    key = rec.get("prefix") or os.path.basename(
        os.path.dirname(os.path.abspath(source))
    ) or "-"
    return f"{key} [smoothed]" if rec.get("smoothed") else key


def _deviation(rec: dict) -> float:
    """Drift-agnostic severity score for worst-N ranking: the largest
    |log ratio| over bands carrying signal (0 for NO_OBS records)."""
    worst = 0.0
    for v in rec.get("chi2_per_band") or ():
        v = float(v)
        if math.isfinite(v) and v > 0.0:
            worst = max(worst, abs(math.log(v)))
    return worst


def build_report(paths: List[str], worst_n: int = 5) -> dict:
    """The scorecard as data (the ``--json`` payload)."""
    sources = []
    tiles: Dict[str, List[dict]] = {}
    for path in paths:
        records, skipped = quality.load_ledger(path)
        sources.append({
            "path": os.path.abspath(path),
            "records": len(records),
            "skipped_lines": skipped,
        })
        for rec in records:
            tiles.setdefault(_tile_key(rec, path), []).append(rec)

    report_tiles: Dict[str, dict] = {}
    for tile in sorted(tiles):
        recs = tiles[tile]
        dates = []
        episodes: List[dict] = []
        open_episode: Optional[dict] = None
        for rec in recs:
            drift = rec.get("drift") or {}
            active = bool(drift.get("active"))
            ratios = [float(v) for v in rec.get("chi2_per_band") or ()]
            entry = {
                "date": rec.get("date"),
                "verdict": rec.get("verdict"),
                # Re-derived from the ratios alone: the ledger must be
                # self-contained (acceptance: the report reproduces
                # per-date verdicts with no live process).  Smoothed
                # records score on sigma-shrink instead of chi^2 (the
                # backward pass has no innovations).
                "recomputed": (
                    quality.NO_OBS if rec.get("degraded")
                    else quality.smoothed_verdict_for(
                        [float(v) for v in rec.get("sigma_shrink") or ()]
                    ) if rec.get("smoothed")
                    else quality.verdict_for(ratios)
                ),
                "degraded": bool(rec.get("degraded")),
                "chi2_per_band": ratios,
                "drift_active": active,
                "drift_bands": list(drift.get("bands") or ()),
                "deviation": round(_deviation(rec), 6),
            }
            dates.append(entry)
            if active:
                if open_episode is None:
                    open_episode = {
                        "start": entry["date"], "end": entry["date"],
                        "dates": 1,
                        "bands": set(entry["drift_bands"]),
                    }
                else:
                    open_episode["end"] = entry["date"]
                    open_episode["dates"] += 1
                    open_episode["bands"].update(entry["drift_bands"])
            elif open_episode is not None:
                open_episode["bands"] = sorted(open_episode["bands"])
                episodes.append(open_episode)
                open_episode = None
        if open_episode is not None:
            open_episode["bands"] = sorted(open_episode["bands"])
            episodes.append(open_episode)
        verdict_counts: Dict[str, int] = {}
        for e in dates:
            verdict_counts[e["verdict"]] = \
                verdict_counts.get(e["verdict"], 0) + 1
        worst = sorted(
            (e for e in dates if not e["degraded"]),
            key=lambda e: e["deviation"], reverse=True,
        )[:max(0, worst_n)]
        report_tiles[tile] = {
            "dates": dates,
            "episodes": episodes,
            "worst": worst,
            "verdicts": verdict_counts,
            "overall": quality.worst_verdict(
                e["verdict"] for e in dates
            ),
            "drift_dates": sum(1 for e in dates if e["drift_active"]),
        }
    return {
        "version": 1,
        "bands": {"lo": quality.CONSISTENT_LO,
                  "hi": quality.CONSISTENT_HI},
        "sources": sources,
        "tiles": report_tiles,
    }


def render(report: dict) -> str:
    """Human one-screen scorecard."""
    lines = []
    n_rec = sum(s["records"] for s in report["sources"])
    n_skip = sum(s["skipped_lines"] for s in report["sources"])
    lines.append(
        f"quality report: {len(report['sources'])} ledger(s), "
        f"{n_rec} record(s)"
        + (f", {n_skip} torn line(s) skipped" if n_skip else "")
    )
    for tile, t in report["tiles"].items():
        timeline = "".join(
            _GLYPH.get(e["verdict"], "?") + ("!" if e["drift_active"]
                                             else "")
            for e in t["dates"]
        )
        lines.append(
            f"  {tile}: overall={t['overall']}  "
            f"drift_dates={t['drift_dates']}  [{timeline}]"
        )
        for ep in t["episodes"]:
            lines.append(
                f"    drift episode: {ep['start']} .. {ep['end']} "
                f"({ep['dates']} date(s), bands {ep['bands']})"
            )
        for e in t["worst"]:
            if e["deviation"] <= 0:
                continue
            ratios = ", ".join(f"{v:.3g}" for v in e["chi2_per_band"])
            lines.append(
                f"    worst: {e['date']}  {e['verdict']}"
                f"{' DRIFT' if e['drift_active'] else ''}  "
                f"chi2/n=[{ratios}]"
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("paths", nargs="+",
                    help="quality.jsonl file(s) or directories to "
                         "search recursively")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report instead of the "
                         "scorecard")
    ap.add_argument("--worst", type=int, default=5,
                    help="how many worst dates to list per tile")
    args = ap.parse_args(argv)
    ledgers = find_ledgers(args.paths)
    if not ledgers:
        print(
            f"quality_report: no {quality.LEDGER_FILENAME} found under "
            f"{args.paths}", file=sys.stderr,
        )
        return 2
    report = build_report(ledgers, worst_n=args.worst)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
