"""programlint: IR-level contract analysis of registered device programs.

The static twin of kafkalint one level deeper: where kafkalint pattern-
matches source text, programlint abstractly traces every program
registered in ``kafka_tpu.analysis.programs`` (CPU-only
``jax.make_jaxpr`` / AOT lowering on ``ShapeDtypeStruct`` specs — no
device, no data) and verifies contracts over the actual IR: no f64, no
host transfers, no rank-3 Jacobian relayouts in relayout-clean programs,
no unmanifested collectives in mesh programs, and no silent drift
against the checked-in fingerprint manifests
(``kafka_tpu/analysis/contracts/*.json``).

Usage::

    python -m tools.programlint                # analyze everything
    python -m tools.programlint --programs date_twostream_inkernel
    python -m tools.programlint --update       # accept drift deliberately
    python -m tools.programlint --json         # machine-readable findings
    python -m tools.programlint --list         # registered programs

Exit codes: 0 clean, 1 findings, 2 usage/configuration error.
"""

from __future__ import annotations

import argparse
import importlib
import json
import os
import sys
from typing import List, Optional


def _ensure_cpu_jax() -> None:
    """Force the CPU backend with a multi-device host platform BEFORE
    jax initialises — analysis must never touch an accelerator, and the
    mesh programs need >= 2 devices for a meaningful collective
    inventory.  A no-op when jax is already imported (e.g. under pytest,
    where conftest.py owns the environment)."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.programlint",
        description=(
            "jaxpr/HLO-level contract analysis of registered device "
            "programs (BASELINE.md 'Program contracts')"
        ),
    )
    p.add_argument("--programs", default=None,
                   help="comma-separated subset of registered programs")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable findings on stdout")
    p.add_argument("--update", action="store_true",
                   help="regenerate the contract manifests from the "
                        "current traces (waivers preserved)")
    p.add_argument("--list", action="store_true", dest="list_programs",
                   help="print the registered programs and exit")
    p.add_argument("--contracts-dir", default=None,
                   help="manifest directory (default: "
                        "kafka_tpu/analysis/contracts)")
    p.add_argument("--no-manifest", action="store_true",
                   help="skip manifest comparison (checkers only)")
    p.add_argument("--no-collectives", action="store_true",
                   help="skip the compile step that inventories "
                        "collectives for mesh programs")
    p.add_argument("--spec-module", default=None,
                   help="import this module's REGISTRY instead of the "
                        "production kafka_tpu.analysis.programs (the "
                        "fixture tests use it)")
    return p


def _load_registry(spec_module: Optional[str]):
    from kafka_tpu.analysis import registry as reg_mod

    if spec_module is None:
        from kafka_tpu.analysis import programs  # noqa: F401

        return reg_mod.REGISTRY
    mod = importlib.import_module(spec_module)
    registry = getattr(mod, "REGISTRY", None)
    if not isinstance(registry, dict) or not registry:
        raise ValueError(
            f"spec module {spec_module!r} exposes no non-empty "
            "REGISTRY dict"
        )
    return registry


def main(argv: Optional[List[str]] = None) -> int:
    _ensure_cpu_jax()
    args = build_parser().parse_args(argv)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)

    from kafka_tpu import analysis

    try:
        registry = _load_registry(args.spec_module)
        names = (
            [n.strip() for n in args.programs.split(",") if n.strip()]
            if args.programs else None
        )
        specs = analysis.get_specs(names, registry=registry)
    except (ImportError, KeyError, ValueError) as exc:
        print(f"programlint: {exc}", file=sys.stderr)
        return 2

    if args.list_programs:
        for spec in specs:
            extras = []
            if spec.relayout_clean:
                extras.append("relayout-clean")
            if spec.collectives:
                extras.append(
                    "collectives=" + ",".join(spec.collectives)
                )
            suffix = f" [{'; '.join(extras)}]" if extras else ""
            print(f"{spec.name}: {spec.description}{suffix}")
        return 0

    contracts_dir = (
        None if args.no_manifest
        else args.contracts_dir or analysis.contracts_dir()
    )
    result = analysis.analyze(
        specs, contracts_dir=contracts_dir, update=args.update,
        compile_collectives=not args.no_collectives,
    )

    if args.as_json:
        payload = {
            "version": 1,
            "programs": result.reports,
            "findings": [
                {"program": f.program, "checker": f.checker,
                 "message": f.message}
                for f in result.findings
            ],
            "updated": result.updated,
        }
        json.dump(payload, sys.stdout, indent=2)
        print()
        return 0 if result.clean else 1

    for f in result.findings:
        print(f"programlint: {f.format()}", file=sys.stderr)
    for path in result.updated:
        print(f"programlint: wrote {os.path.relpath(path, repo_root)}")
    if result.findings:
        print(
            f"programlint: {len(result.findings)} finding(s) across "
            f"{len(specs)} program(s)",
            file=sys.stderr,
        )
        return 1
    print(
        f"programlint: clean ({len(specs)} programs, "
        f"{sum(p['eqns'] for p in result.reports.values())} traced "
        "equations)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
