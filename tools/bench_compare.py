"""Compare two BENCH JSON artifacts and gate on device-time regressions.

The repo archives one BENCH JSON per round (``BENCH_r0*.json``) but
nothing ever *read* two of them side by side — the bench trajectory was
write-only.  This tool makes it actionable:

- compares every gated timing row (``device_*_ms`` solve rows and the
  ``serve_p50_ms``/``serve_p99_ms`` serving-latency rows) shared by the
  two artifacts
  and **exits non-zero when any regresses by more than the threshold**
  (default 10%, new > old * 1.10) — the CI gate for perf PRs — or when
  a row the old artifact carried **disappears** from the new one (a
  dropped measurement is a silent path breakage, not a skip; rows
  appearing in the new artifact stay informational);
- refuses to issue a REGRESSION verdict off artifacts flagged
  ``unhealthy`` (rounds 3-5 proved those archive environment weather, not
  code): off-band artifacts downgrade the verdict to UNJUDGEABLE
  (exit 0 with a loud warning) rather than failing a PR on tunnel noise;
- diffs the embedded ``"telemetry"`` registry snapshots (PR 2's compact
  counter/gauge view) and reports the largest relative changes —
  convergence iterations, device reads, compile-cache hits — so a timing
  shift arrives with its likely cause attached;
- diffs the embedded ``"quality"`` snapshots (assimilation-quality
  verdict counts + drift-sentinel state) informationally, with a LOUD
  warning when a previously-CONSISTENT benchmark flips verdict or its
  drift sentinels go 0 -> alarming — mirroring the ``solver_health``
  quarantine warning;
- diffs the embedded ``"slo"`` snapshots and ``serve_slo_*`` rows
  informationally, with the same class of LOUD warning when a
  previously-clean artifact (zero SLO alerts) shows fired burn-rate
  alerts — a bench that got faster by burning its error budget must
  not read as a clean win;
- diffs the embedded ``"device_profile"`` snapshots (ISSUE 18: top
  kernels, collective fraction, HBM peak) informationally, with a
  LOUD warning when the collective-time fraction grows by more than
  10 points absolute — a mesh-balance shift masquerading as a kernel
  result.

Usage:
    python tools/bench_compare.py OLD.json NEW.json [--threshold 0.10]

Exit codes: 0 ok (or unjudgeable), 1 regression, 2 usage/load error.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys
from typing import Dict, List, Optional, Tuple

#: timing rows gated on regression (smaller is better, milliseconds).
#: ``device_*_ms`` are the solve rows; ``serve_p50_ms``/``serve_p99_ms``
#: are the serving-latency rows (tools/loadgen.py) and
#: ``serve_fleet_p50_ms``/``serve_fleet_p99_ms`` their elastic-fleet
#: twins (N replicas behind the consistent-hash router) — the serving
#: story gates like the solve story.  ``serve_cold_ms``/
#: ``serve_*rejected*``/``serve_fleet_rerouted_total`` stay
#: informational (cold start is setup; rejections and re-routes are
#: policy outcomes, not latencies).
GATED_ROW_PATTERNS = ("device_*_ms", "serve_p50_ms", "serve_p99_ms",
                      "serve_fleet_p50_ms", "serve_fleet_p99_ms",
                      "serve_smoothed_p99_ms")
#: gated throughput rows (LARGER is better): the reanalysis sweep's
#: pixel-windows/s and the coalesced-serving launch throughput at the
#: sweep's top concurrency (tools/loadgen.bench_concurrency_sweep).
#: Same disappearance rule; the regression direction is inverted.
GATED_THROUGHPUT_PATTERNS = ("device_smoother_px_s",
                             "serve_batched_px_s")
DEVICE_ROW_PATTERN = GATED_ROW_PATTERNS[0]  # back-compat alias


def device_rows(artifact: dict) -> Dict[str, float]:
    """The artifact's gateable timing rows (nulls — e.g. the Pallas rows
    off-TPU — are dropped; spreads are diagnostics, not gates)."""
    return {
        k: float(v) for k, v in artifact.items()
        if any(fnmatch.fnmatch(k, pat) for pat in
               GATED_ROW_PATTERNS + GATED_THROUGHPUT_PATTERNS)
        and not k.endswith("_spread")
        and isinstance(v, (int, float))
    }


def compare_rows(old: dict, new: dict, threshold: float = 0.10,
                 ) -> Tuple[List[str], List[str]]:
    """(regressions, report_lines) over the device timing rows.

    A row present (non-null) in the old artifact but missing or null in
    the new one is a GATING FAILURE, not a skip: a dropped
    ``device_*_ms`` row means the measurement silently stopped happening
    (the kernel path broke, the TPU gate mis-fired, a rename), which is
    exactly the regression class "compare only shared rows" cannot see.
    Rows that APPEAR in the new artifact remain informational — growing
    coverage must not fail the gate.
    """
    rows_old, rows_new = device_rows(old), device_rows(new)
    regressions: List[str] = []
    lines: List[str] = []
    for key in sorted(set(rows_old) | set(rows_new)):
        a, b = rows_old.get(key), rows_new.get(key)
        larger_better = any(
            fnmatch.fnmatch(key, pat)
            for pat in GATED_THROUGHPUT_PATTERNS
        )
        unit = "px/s" if larger_better else "ms"
        if a is not None and b is None:
            regressions.append(
                f"{key}: {a:.3f} {unit} -> MISSING (row disappeared "
                "from the new artifact — a dropped measurement gates "
                "like a regression)"
            )
            lines.append(f"  {key}: {a:.3f} -> MISSING  REGRESSION")
            continue
        if a is None or b is None:
            lines.append(f"  {key}: only in {'new' if a is None else 'old'} "
                         "artifact — skipped")
            continue
        delta = (b - a) / a if a else 0.0
        # "worse" is the gate's direction: more milliseconds, or fewer
        # pixel-windows per second.
        worse = -delta if larger_better else delta
        verdict = "ok"
        if worse > threshold:
            verdict = "REGRESSION"
            regressions.append(
                f"{key}: {a:.3f} -> {b:.3f} {unit} "
                f"({100 * delta:+.1f}%, worse by more than "
                f"{100 * threshold:.0f}%)"
            )
        elif worse < -threshold:
            verdict = "improved"
        lines.append(
            f"  {key}: {a:.3f} -> {b:.3f} {unit} ({100 * delta:+.1f}%) "
            f"{verdict}"
        )
    if not rows_old or not rows_new:
        lines.append("  (no shared device_*_ms rows to compare)")
    return regressions, lines


def solver_health_deltas(old: dict, new: dict
                         ) -> Tuple[List[str], List[str]]:
    """(warnings, report_lines) over the embedded ``solver_health``
    snapshots (bench.py's compact kafka_solver_* counter view).

    Diffed INFORMATIONALLY like the telemetry snapshots — result
    quality is a property of the data and the solver, not a timing gate
    — with ONE exception loud enough to not scroll past: a NEW nonzero
    ``quarantined_pixels`` count on a previously-clean benchmark is a
    numerical-health break (pixels served as forecast fallbacks), so it
    surfaces as an explicit warning.  Still exit 0: the verdict stays
    with the human, but never silence.
    """
    h_old = old.get("solver_health") or {}
    h_new = new.get("solver_health") or {}
    warnings: List[str] = []
    lines: List[str] = []
    for key in sorted(set(h_old) | set(h_new)):
        a, b = h_old.get(key, 0), h_new.get(key, 0)
        if a == b == 0:
            continue
        lines.append(f"  {key}: {a:g} -> {b:g}")
    old_quar = float(h_old.get("quarantined_pixels") or 0)
    new_quar = float(h_new.get("quarantined_pixels") or 0)
    if new_quar > 0 and old_quar == 0:
        warnings.append(
            f"quarantined_pixels went 0 -> {new_quar:g}: the new "
            "artifact served forecast fallbacks on a previously-clean "
            "benchmark (solve-health break, not a perf question) — "
            "inspect the solver_qa bands before trusting its timings"
        )
    return warnings, lines


def quality_deltas(old: dict, new: dict) -> Tuple[List[str], List[str]]:
    """(warnings, report_lines) over the embedded ``quality`` snapshots
    (bench.py's compact assimilation-quality view).

    Diffed INFORMATIONALLY like ``solver_health`` — consistency is a
    property of the data and the filter configuration, not a timing
    gate — with the same class of loud exception: a benchmark whose
    overall verdict FLIPS away from CONSISTENT (or whose drift
    sentinels started alarming on a previously-quiet run) is a
    statistical-consistency break, so it surfaces as an explicit
    warning.  Still exit 0: the verdict stays with the human, but
    never silence.
    """
    q_old = old.get("quality") or {}
    q_new = new.get("quality") or {}
    warnings: List[str] = []
    lines: List[str] = []
    w_old = q_old.get("windows") or {}
    w_new = q_new.get("windows") or {}
    for key in sorted(set(w_old) | set(w_new)):
        a, b = w_old.get(key, 0), w_new.get(key, 0)
        if a == b == 0:
            continue
        lines.append(f"  windows[{key}]: {a:g} -> {b:g}")
    for key in ("drift_events", "drift_active"):
        a, b = q_old.get(key, 0) or 0, q_new.get(key, 0) or 0
        if a or b:
            lines.append(f"  {key}: {a:g} -> {b:g}")
    v_old, v_new = q_old.get("verdict"), q_new.get("verdict")
    if v_old != v_new and (v_old or v_new):
        lines.append(f"  verdict: {v_old} -> {v_new}")
    if v_old == "CONSISTENT" and v_new not in (None, "CONSISTENT"):
        warnings.append(
            f"assimilation-quality verdict flipped CONSISTENT -> "
            f"{v_new}: the new artifact's filter is statistically "
            "inconsistent (innovation chi^2 outside the consistency "
            "band) on a previously-consistent benchmark — inspect "
            "quality.jsonl (tools/quality_report.py) before trusting "
            "its timings"
        )
    old_drift = float(q_old.get("drift_events") or 0)
    new_drift = float(q_new.get("drift_events") or 0)
    if new_drift > 0 and old_drift == 0:
        warnings.append(
            f"quality drift_events went 0 -> {new_drift:g}: the drift "
            "sentinels started alarming on a previously-quiet "
            "benchmark (sensor/R/Q drift class, not a perf question)"
        )
    return warnings, lines


def slo_deltas(old: dict, new: dict) -> Tuple[List[str], List[str]]:
    """(warnings, report_lines) over the embedded ``slo`` snapshots
    (bench.py's compact alert/budget view) plus the serve_slo_* rows.

    Diffed INFORMATIONALLY like ``solver_health``/``quality`` — an
    alert is an operations signal, not a timing gate — with the same
    class of loud exception: a previously-clean artifact (zero alerts
    fired) whose new run FIRED alerts burned error budget to get its
    numbers, so it surfaces as an explicit warning.  Still exit 0.
    """
    s_old = old.get("slo") or {}
    s_new = new.get("slo") or {}
    warnings: List[str] = []
    lines: List[str] = []
    for key in ("alerts_fired", "alerts_resolved"):
        a, b = s_old.get(key, 0) or 0, s_new.get(key, 0) or 0
        if a or b:
            lines.append(f"  {key}: {a:g} -> {b:g}")
    f_old = s_old.get("firing") or []
    f_new = s_new.get("firing") or []
    if f_old or f_new:
        lines.append(
            f"  firing: {','.join(f_old) or '-'} -> "
            f"{','.join(f_new) or '-'}"
        )
    for key in ("serve_slo_alerts_total", "serve_slo_budget_remaining"):
        a, b = old.get(key), new.get(key)
        if a is None and b is None:
            continue
        fmt = (lambda v: "-" if v is None else f"{v:g}")
        lines.append(f"  {key}: {fmt(a)} -> {fmt(b)}")
    old_fired = float(s_old.get("alerts_fired") or 0) + \
        float(old.get("serve_slo_alerts_total") or 0)
    new_fired = float(s_new.get("alerts_fired") or 0) + \
        float(new.get("serve_slo_alerts_total") or 0)
    if new_fired > 0 and old_fired == 0:
        warnings.append(
            f"SLO alerts fired went 0 -> {new_fired:g}: the new "
            "artifact burned error budget (burn-rate alerts fired "
            "during the bench) on a previously-clean benchmark — "
            "inspect alerts.jsonl (tools/slo_report.py) before "
            "trusting its timings"
        )
    return warnings, lines


#: absolute growth of the collective-time fraction beyond this is the
#: mesh-balance red flag device_profile_deltas warns LOUDLY about.
COLLECTIVE_FRACTION_WARN = 0.10


def device_profile_deltas(old: dict, new: dict,
                          ) -> Tuple[List[str], List[str]]:
    """(warnings, report_lines) over the embedded ``device_profile``
    snapshots (bench.py's compact kernel/HBM view, ISSUE 18).

    Diffed INFORMATIONALLY like the other observability snapshots —
    where device time went is attribution, not a timing gate — with
    one loud exception: the collective-time fraction growing by more
    than :data:`COLLECTIVE_FRACTION_WARN` absolute means the new
    artifact spends materially more of its device time waiting on the
    mesh (a sharding/topology change, not a kernel win), so it
    surfaces as an explicit warning.  Still exit 0.
    """
    d_old = old.get("device_profile") or {}
    d_new = new.get("device_profile") or {}
    warnings: List[str] = []
    lines: List[str] = []
    if not d_old and not d_new:
        return warnings, lines
    a, b = d_old.get("captures_parsed", 0), d_new.get(
        "captures_parsed", 0)
    if a or b:
        lines.append(f"  captures_parsed: {a:g} -> {b:g}")
        lines.append(
            f"  device_ms: {d_old.get('device_ms', 0):g} -> "
            f"{d_new.get('device_ms', 0):g}"
        )
    cf_old = d_old.get("collective_fraction")
    cf_new = d_new.get("collective_fraction")
    if cf_old is not None or cf_new is not None:
        fmt = (lambda v: "-" if v is None else f"{v:.1%}")
        lines.append(
            f"  collective_fraction: {fmt(cf_old)} -> {fmt(cf_new)}"
        )
    top_old = (d_old.get("kernels") or [{}])[0].get("name")
    top_new = (d_new.get("kernels") or [{}])[0].get("name")
    if top_old != top_new and (top_old or top_new):
        lines.append(
            f"  top kernel: {top_old or '-'} -> {top_new or '-'}"
        )
    if cf_new is not None and \
            (cf_new - (cf_old or 0.0)) > COLLECTIVE_FRACTION_WARN:
        warnings.append(
            f"collective-time fraction grew {cf_old or 0.0:.1%} -> "
            f"{cf_new:.1%} (more than "
            f"{COLLECTIVE_FRACTION_WARN:.0%} absolute): the new "
            "artifact spends materially more device time waiting on "
            "the mesh — inspect the kernel table "
            "(tools/device_report.py) for the collective that grew "
            "before reading its timings as a kernel-level result"
        )
    return warnings, lines


def program_contracts_deltas(old: dict, new: dict,
                             ) -> Tuple[List[str], List[str]]:
    """(warnings, report_lines) over the embedded ``program_contracts``
    snapshots (bench.py's per-program trace fingerprints, ISSUE 19).

    Informational lines, but fingerprint drift on a shared program
    warns LOUDLY: the two artifacts compiled DIFFERENT device programs
    under the same name, so their timing rows are not the same
    measurement — accept the drift deliberately (python -m
    tools.programlint --update) before trusting the comparison.  A
    finding count going 0 -> N warns too (the new run's programs
    violate contracts the old run's did not).  Still exit 0.
    """
    c_old = old.get("program_contracts") or {}
    c_new = new.get("program_contracts") or {}
    warnings: List[str] = []
    lines: List[str] = []
    if not c_old and not c_new:
        return warnings, lines
    for side, c in (("old", c_old), ("new", c_new)):
        if c.get("error"):
            lines.append(f"  {side}: analysis error: {c['error']}")
    p_old = c_old.get("programs") or {}
    p_new = c_new.get("programs") or {}
    drifted = sorted(
        name for name in set(p_old) & set(p_new)
        if p_old[name] != p_new[name]
    )
    lines.append(
        f"  programs: {len(p_old)} -> {len(p_new)} "
        f"({len(drifted)} fingerprint(s) drifted)"
    )
    for name in sorted(set(p_new) - set(p_old)):
        lines.append(f"  new program: {name} ({p_new[name]})")
    for name in sorted(set(p_old) - set(p_new)):
        lines.append(f"  removed program: {name}")
    for name in drifted:
        lines.append(
            f"  {name}: fingerprint {p_old[name]} -> {p_new[name]}"
        )
    if drifted:
        warnings.append(
            f"program fingerprint(s) drifted for {', '.join(drifted)}: "
            "the compared artifacts traced DIFFERENT device programs "
            "under the same name, so their timing rows are not the same "
            "measurement — review the drift (python -m tools.programlint) "
            "and accept it deliberately with --update before reading "
            "these rows as a like-for-like comparison"
        )
    f_old, f_new = c_old.get("findings"), c_new.get("findings")
    if f_old is not None or f_new is not None:
        lines.append(f"  contract findings: {f_old} -> {f_new}")
    if not f_old and f_new:
        warnings.append(
            f"contract findings went {f_old or 0} -> {f_new}: the new "
            "artifact's device programs violate contracts the old one "
            "satisfied — run python -m tools.programlint for the "
            "finding list before trusting the new numbers"
        )
    return warnings, lines


def live_telemetry_deltas(old: dict, new: dict) -> List[str]:
    """Informational diff of the embedded ``live_telemetry`` mid-run
    scrape series (tools/loadgen): per shared series, the peak and the
    final sample side by side.  Never gated — the series show HOW a
    latency shift happened (queue build-up vs admission shedding), they
    are not themselves a timing."""
    s_old = (old.get("live_telemetry") or {}).get("series") or {}
    s_new = (new.get("live_telemetry") or {}).get("series") or {}
    lines: List[str] = []
    for key in sorted(set(s_old) & set(s_new)):
        a, b = s_old[key], s_new[key]
        if not a or not b:
            continue
        try:
            peak_a, peak_b = max(a), max(b)
            last_a, last_b = a[-1], b[-1]
        except TypeError:
            continue
        if (peak_a, last_a) == (peak_b, last_b):
            continue
        lines.append(
            f"  {key}: peak {peak_a:g} -> {peak_b:g}, "
            f"final {last_a:g} -> {last_b:g}"
        )
    only = [
        f"  ({side} artifact carries no live_telemetry scrape)"
        for side, art in (("old", old), ("new", new))
        if not (art.get("live_telemetry") or {}).get("series")
    ]
    if only and (s_old or s_new):
        lines.extend(only)
    return lines


def trace_coverage_deltas(old: dict, new: dict) -> List[str]:
    """Informational diff of the request-tracing rows (ISSUE 14):
    ``serve_trace_coverage`` (fraction of requests whose per-request
    trace attributes >=95% of wall time) and ``serve_slowest_ms`` (the
    worst single request).  NOT gated yet — the rows establish the
    trend first; a coverage drop is called out loudly because it means
    the tracing itself regressed (latency became unexplainable), which
    is an observability break, not a perf question."""
    lines: List[str] = []
    for key in ("serve_trace_coverage", "serve_slowest_ms"):
        a, b = old.get(key), new.get(key)
        if a is None and b is None:
            continue
        fmt = (lambda v: "-" if v is None else f"{v:g}")
        lines.append(f"  {key}: {fmt(a)} -> {fmt(b)}")
    a, b = old.get("serve_trace_coverage"), \
        new.get("serve_trace_coverage")
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
            and b < a:
        lines.append(
            f"  WARNING serve_trace_coverage dropped {a:g} -> {b:g}: "
            "requests with unexplained latency appeared (run "
            "tools/trace_report.py --unattributed on the new run)"
        )
    return lines


def telemetry_deltas(old: dict, new: dict, top: int = 8) -> List[str]:
    """Largest relative changes between the embedded registry snapshots
    (context for a timing shift; never gated on)."""
    t_old = old.get("telemetry") or {}
    t_new = new.get("telemetry") or {}
    changes: List[Tuple[float, str]] = []
    for key in sorted(set(t_old) & set(t_new)):
        a, b = t_old[key], t_new[key]
        if not isinstance(a, (int, float)) or \
                not isinstance(b, (int, float)) or a == b:
            continue
        rel = abs(b - a) / max(abs(a), 1e-12)
        changes.append((rel, f"  {key}: {a:g} -> {b:g}"))
    changes.sort(reverse=True)
    out = [line for _, line in changes[:top]]
    missing = [k for k in ("telemetry",) if k not in old or k not in new]
    if missing:
        out.append("  (one artifact carries no telemetry snapshot)")
    return out


def _unwrap_artifact(doc):
    """``tools.bench_history.unwrap_artifact`` (the one owner of the
    archive-wrapper format), resolved across every way this file gets
    loaded: package module, ``python tools/bench_compare.py`` script,
    or a bare file-path import."""
    try:
        from .bench_history import unwrap_artifact
    except ImportError:
        try:  # script mode: tools/ itself is sys.path[0]
            from bench_history import unwrap_artifact
        except ImportError:  # file-path import: resolve the sibling file
            import importlib.util
            import os

            spec = importlib.util.spec_from_file_location(
                "_bench_history_sibling",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_history.py"),
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            unwrap_artifact = mod.unwrap_artifact
    return unwrap_artifact(doc)


def load(path: str) -> Optional[dict]:
    """One artifact, unwrapping the harness archive wrapper format
    ``{"n","cmd","rc","tail","parsed"}`` the checked-in BENCH_r0*.json
    use (tools/bench_history.py owns the unwrap) — so comparing two
    archived rounds works directly instead of silently finding no rows."""
    try:
        with open(path) as f:
            return _unwrap_artifact(json.load(f))
    except (OSError, json.JSONDecodeError) as exc:
        print(f"bench_compare: cannot load {path}: {exc}",
              file=sys.stderr)
        return None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("old", help="baseline BENCH JSON")
    ap.add_argument("new", help="candidate BENCH JSON")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative regression gate on device_*_ms rows "
                         "(default 0.10 = 10%%)")
    args = ap.parse_args(argv)
    old, new = load(args.old), load(args.new)
    if old is None or new is None:
        return 2
    regressions, lines = compare_rows(old, new, args.threshold)
    print(f"bench_compare: {args.old} -> {args.new}")
    for line in lines:
        print(line)
    deltas = telemetry_deltas(old, new)
    if deltas:
        print("telemetry deltas (context, not gated):")
        for line in deltas:
            print(line)
    live_lines = live_telemetry_deltas(old, new)
    if live_lines:
        print("live telemetry deltas (mid-run scrape, not gated):")
        for line in live_lines:
            print(line)
    trace_lines = trace_coverage_deltas(old, new)
    if trace_lines:
        print("request-tracing deltas (attribution coverage, "
              "not gated):")
        for line in trace_lines:
            print(line)
    health_warnings, health_lines = solver_health_deltas(old, new)
    if health_lines:
        print("solver-health deltas (result quality, not gated):")
        for line in health_lines:
            print(line)
    for w in health_warnings:
        print(f"bench_compare: WARNING {w}", file=sys.stderr)
    quality_warnings, quality_lines = quality_deltas(old, new)
    if quality_lines:
        print("assimilation-quality deltas (consistency, not gated):")
        for line in quality_lines:
            print(line)
    for w in quality_warnings:
        print(f"bench_compare: WARNING {w}", file=sys.stderr)
    slo_warnings, slo_lines = slo_deltas(old, new)
    if slo_lines:
        print("slo deltas (alerts / error budget, not gated):")
        for line in slo_lines:
            print(line)
    for w in slo_warnings:
        print(f"bench_compare: WARNING {w}", file=sys.stderr)
    devprof_warnings, devprof_lines = device_profile_deltas(old, new)
    if devprof_lines:
        print("device-profile deltas (kernel attribution, not gated):")
        for line in devprof_lines:
            print(line)
    for w in devprof_warnings:
        print(f"bench_compare: WARNING {w}", file=sys.stderr)
    contract_warnings, contract_lines = program_contracts_deltas(
        old, new)
    if contract_lines:
        print("program-contract deltas (traced programs, not gated):")
        for line in contract_lines:
            print(line)
    for w in contract_warnings:
        print(f"bench_compare: WARNING {w}", file=sys.stderr)
    unhealthy = [
        name for name, art in (("old", old), ("new", new))
        if art.get("unhealthy")
    ]
    if regressions and unhealthy:
        print(
            f"bench_compare: UNJUDGEABLE — {' and '.join(unhealthy)} "
            "artifact(s) flagged unhealthy (environment weather, not "
            "code); re-measure in a healthy window",
            file=sys.stderr,
        )
        return 0
    if regressions:
        for r in regressions:
            print(f"bench_compare: REGRESSION {r}", file=sys.stderr)
        return 1
    print("bench_compare: OK — no device timing regression "
          f"beyond {100 * args.threshold:.0f}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
