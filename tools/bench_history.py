"""bench_history — the multi-artifact BENCH trend ledger.

``tools/bench_compare.py`` reads exactly TWO artifacts; the repo
archives one per round (``BENCH_r0*.json``), so the bench trajectory as
a SERIES was unreadable — nobody could answer "is the e2e row actually
regressing, or is it just the tunnel?" from the data we already ship.
This tool reads any number of artifacts (oldest -> newest) and renders
one trend table:

- **wrapper-aware loading** (:func:`unwrap_artifact`): the checked-in
  rounds are archived in the harness wrapper format
  ``{"n", "cmd", "rc", "tail", "parsed"}`` with the real BENCH dict
  under ``"parsed"`` — both wrapped and bare artifacts load, in any
  mix (``bench_compare`` unwraps through the same helper now);
- **per-row trends** over every shared numeric row (device/serve/oracle
  timings, throughput rows, device fraction), with a ROBUST verdict:
  the newest value against the MEDIAN of the prior rounds, direction-
  aware (``*_ms`` rows regress upward, ``*_px_s``/``*per_s`` rows
  regress downward);
- **spread-aware unjudgeability**: a row that swung BOTH directions by
  more than :data:`NOISY_SWING` across rounds (the e2e row's
  35.7k -> 72.8k -> 44.0k px-steps/s) or whose artifacts' own recorded
  ``*_spread`` rivals its value is flagged ``unjudgeable`` instead of
  trended — environment weather must not be read as a perf trajectory
  (the same lesson as bench_compare's unhealthy-artifact rule, applied
  longitudinally).  A monotone improvement staircase (the 26.8M -> 81M
  px/s throughput row) swings one way only and stays judgeable.

Usage:
    python tools/bench_history.py BENCH_r01.json BENCH_r02.json ...
        [--json] [--threshold 0.10]

Exit codes: 0 (report rendered — history is a report, not a gate; use
bench_compare for gating), 2 usage/no-loadable-artifacts.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

#: numeric rows worth trending (higher-better unless matched by
#: LOWER_BETTER); everything else in an artifact is context, not a row.
TREND_ROW_PATTERNS = (
    "value",
    "vs_baseline_at_scale",
    "device_*_ms", "device_*_px_s", "device_px_s_matched",
    "device_ms_matched_median",
    "oracle_ms_median", "oracle_ms_min",
    "e2e_pixel_steps_per_s", "e2e_device_fraction",
    "serve_p50_ms", "serve_p99_ms", "serve_cold_ms",
)

#: rows where smaller is better (milliseconds).
LOWER_BETTER_PATTERNS = ("*_ms", "*_ms_median", "*_ms_min")

#: a row that moved BOTH directions by more than this (relative) across
#: rounds is noise, not a trend.
NOISY_SWING = 0.20

#: artifact-recorded spread rivalling the value itself (spread/value
#: beyond this on a typical round) also flags the row unjudgeable.
NOISY_RECORDED_SPREAD = 0.50

#: |delta| of the newest value vs the prior median below this is flat.
DEFAULT_THRESHOLD = 0.10


def unwrap_artifact(doc):
    """Unwrap the harness archive format ``{"n","cmd","rc","tail",
    "parsed"}`` to the BENCH dict under ``"parsed"``; a bare BENCH dict
    passes through.  Returns ``{}`` for anything else (a wrapper whose
    parse failed is row-less, not an error)."""
    if not isinstance(doc, dict):
        return {}
    if "parsed" in doc and ("cmd" in doc or "tail" in doc or "rc" in doc):
        parsed = doc["parsed"]
        return parsed if isinstance(parsed, dict) else {}
    return doc


def load_artifact(path: str) -> Optional[dict]:
    """One artifact, unwrapped; None (with a stderr note) when the file
    is unreadable."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as exc:
        print(f"bench_history: cannot load {path}: {exc}",
              file=sys.stderr)
        return None
    return unwrap_artifact(doc)


def _is_trend_row(key: str) -> bool:
    return any(fnmatch.fnmatch(key, pat) for pat in TREND_ROW_PATTERNS) \
        and not key.endswith("_spread")


def lower_is_better(key: str) -> bool:
    return any(fnmatch.fnmatch(key, pat) for pat in LOWER_BETTER_PATTERNS)


def _series(artifacts: List[dict], key: str,
            ) -> List[Tuple[int, float]]:
    """(artifact index, value) for every artifact carrying the row as a
    number (nulls — e.g. Pallas rows off-TPU — are absent rounds)."""
    out = []
    for i, art in enumerate(artifacts):
        v = art.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out.append((i, float(v)))
    return out


def _median(values: List[float]) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def judge_row(key: str, artifacts: List[dict],
              threshold: float = DEFAULT_THRESHOLD) -> Optional[dict]:
    """One row's trend entry, or None when no artifact carries it.

    Verdicts: ``improving`` / ``flat`` / ``regressing`` (newest vs the
    median of the prior rounds, direction-aware), ``single`` (one data
    point), or ``unjudgeable`` with the reason — the row swung both
    directions beyond :data:`NOISY_SWING`, or its own recorded spread
    rivals its value.
    """
    pts = _series(artifacts, key)
    if not pts:
        return None
    values = [v for _, v in pts]
    entry = {
        "row": key,
        "n": len(values),
        "rounds": [i for i, _ in pts],
        "values": values,
        "lower_is_better": lower_is_better(key),
    }
    if len(values) == 1:
        entry.update(verdict="single", reason="one round only")
        return entry

    # Longitudinal noise: successive relative deltas that swing BOTH
    # ways beyond the band mean the row measures weather, not code.
    deltas = [
        (b - a) / abs(a) if a else 0.0
        for a, b in zip(values, values[1:])
    ]
    swung_up = max(deltas) > NOISY_SWING
    swung_down = min(deltas) < -NOISY_SWING
    if swung_up and swung_down:
        entry.update(
            verdict="unjudgeable",
            reason=(
                f"swung both directions beyond {NOISY_SWING:.0%} "
                f"across rounds ({min(deltas):+.0%} .. "
                f"{max(deltas):+.0%}) — environment noise, not a trend"
            ),
        )
        return entry

    # Artifact-recorded dispersion: a row whose own *_spread rivals its
    # value (the r05 oracle's 1922 ms spread on a 662 ms median) is not
    # trendable either, whichever way its medians drift.
    spreads = _series(artifacts, key + "_spread")
    if spreads:
        ratios = [
            abs(s) / abs(v)
            for (i, s) in spreads
            for (j, v) in pts if i == j and v
        ]
        if ratios and _median(ratios) > NOISY_RECORDED_SPREAD:
            entry.update(
                verdict="unjudgeable",
                reason=(
                    f"recorded spread is {_median(ratios):.0%} of the "
                    "value (median across rounds) — single-round "
                    "dispersion rivals the signal"
                ),
            )
            return entry

    prior_median = _median(values[:-1])
    last = values[-1]
    delta = (last - prior_median) / abs(prior_median) if prior_median \
        else 0.0
    entry["delta_vs_prior_median"] = delta
    if abs(delta) <= threshold:
        entry.update(verdict="flat",
                     reason=f"{delta:+.1%} vs prior median")
        return entry
    better = (delta < 0) if entry["lower_is_better"] else (delta > 0)
    entry.update(
        verdict="improving" if better else "regressing",
        reason=f"{delta:+.1%} vs prior median of {len(values) - 1}",
    )
    return entry


def build_history(paths: List[str],
                  threshold: float = DEFAULT_THRESHOLD) -> Optional[dict]:
    """The full trend document (the ``--json`` payload): artifact
    metadata in the given order + one entry per trendable row."""
    artifacts: List[dict] = []
    meta: List[dict] = []
    for path in paths:
        art = load_artifact(path)
        if art is None:
            continue
        artifacts.append(art)
        meta.append({
            "path": path,
            "name": os.path.basename(path),
            "rows": sum(1 for k in art if _is_trend_row(k)),
            "unhealthy": bool(art.get("unhealthy")),
        })
    if not artifacts:
        return None
    keys = sorted({
        k for art in artifacts for k in art if _is_trend_row(k)
    })
    rows = {}
    for key in keys:
        entry = judge_row(key, artifacts, threshold)
        if entry is not None:
            rows[key] = entry
    return {
        "n_artifacts": len(artifacts),
        "artifacts": meta,
        "threshold": threshold,
        "rows": rows,
    }


def _fmt(v: float) -> str:
    return f"{v:g}" if abs(v) < 1e5 else f"{v:.4g}"


def render(history: dict) -> str:
    """Human-readable trend table."""
    lines = [
        f"bench_history: {history['n_artifacts']} artifact(s), "
        f"oldest -> newest",
    ]
    for m in history["artifacts"]:
        flag = "  UNHEALTHY" if m["unhealthy"] else ""
        lines.append(f"  {m['name']}: {m['rows']} trend row(s){flag}")
    width = max((len(k) for k in history["rows"]), default=10)
    for key, e in sorted(history["rows"].items()):
        arrow = " -> ".join(_fmt(v) for v in e["values"])
        verdict = e["verdict"].upper() if e["verdict"] in (
            "regressing", "unjudgeable"
        ) else e["verdict"]
        lines.append(
            f"  {key:<{width}}  [{verdict}] {arrow}  ({e['reason']})"
        )
    if not history["rows"]:
        lines.append("  (no trendable rows found)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("artifacts", nargs="+",
                    help="BENCH JSON artifacts, oldest first (wrapped "
                         "archive format or bare bench output)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable trend document instead of "
                         "the table")
    ap.add_argument("--threshold", type=float,
                    default=DEFAULT_THRESHOLD,
                    help="|delta| vs prior median below this is flat "
                         "(default 0.10)")
    args = ap.parse_args(argv)
    history = build_history(args.artifacts, threshold=args.threshold)
    if history is None:
        print("bench_history: no loadable artifacts", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(history, indent=2, sort_keys=True))
    else:
        print(render(history))
    return 0


if __name__ == "__main__":
    sys.exit(main())
