"""Roofline accounting for the hot solver kernels (round-4 item #1).

The cross-round bench compares the device rate only to the CPU oracle;
this tool answers the other question — how close is the kernel to what
the *chip* can do?  For each component of the hot path it reports:

- measured ms/call at the operating size (queued-slope method — the
  tunneled client defers execution and poisons dispatch latency, so
  naive timings are fiction; see BASELINE.md methodology),
- XLA's post-fusion cost model (``compiled.cost_analysis()``): HBM bytes
  accessed + flops of the optimised HLO.  NOTE the cost model counts one
  logical array once PER FUSION that touches it, so its byte totals are
  an inefficiency signal (traffic amplification), NOT achieved bandwidth
  — deriving utilisation from them produced impossible >100%-of-roof
  numbers in earlier rounds,
- the *analytic minimum* HBM traffic (read every live input once, write
  every output once) and the utilisation LOWER BOUND it implies against
  the v5e's public roofs (819 GB/s HBM, 197 TFLOP/s bf16 MXU — the
  packed path is float32 VPU work, so bandwidth is the binding roof;
  flops show arithmetic intensity, not a utilisation claim).

Components, at n = 2^19 pixels (the benchmark operating size):

- ``linearize``: the operator's batched value+Jacobian (twostream p=7
  and exact-SAIL PROSAIL p=10),
- ``update``: packed normal-equations assembly + packed Cholesky +
  substitutions, given a linearisation (``core.solvers.kalman_update``),
- ``gn_full``: the production Gauss-Newton ``lax.while_loop``
  (``assimilate_date_jit``, 2 iterations on this problem),
- ``gn_full_pallas`` / ``gn_inkernel`` (TPU only): the same loop on the
  two fused-kernel generations — whole-update kernel with out-of-kernel
  linearisation, and the whole GN loop (analytic in-kernel
  linearisation, VMEM-resident carry) as one launch; ``gn_inkernel``
  carries its own re-derived traffic bound (packed-triangle prior and
  information matrix, diagnostics counted).

Usage:  python tools/roofline.py [--n 524288] [--json out.json]

Single-process, serialized with nothing else on the TPU (host is
1-core; concurrent compute skews queued-slope timings).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

# Script-mode bootstrap: `python tools/roofline.py` puts tools/ (not the
# repo root) on sys.path, so the kafka_tpu import below needs the root
# added explicitly; `python -m tools.roofline` and test imports already
# have it and skip this.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# Roofs and analytic minimum-traffic bounds live in the TELEMETRY layer
# now (kafka_tpu.telemetry.perf) so the runtime publishes the same
# utilisation lower bound as a live gauge
# (kafka_perf_roofline_utilization{component=}) that this tool prints as
# a table — one derivation, two consumers.  Re-exported here so existing
# imports of tools.roofline.HBM_GBPS keep working.
from kafka_tpu.telemetry.perf import (  # noqa: F401 — re-export
    HBM_GBPS,
    PEAK_TFLOPS_BF16,
    min_traffic_gn_full,
    min_traffic_gn_inkernel,
    min_traffic_linearize,
    min_traffic_update,
)


def slope_time(fn, flush, k1=5, k2=25, reps=5, target_s=1.5):
    """Sustained per-call seconds via the queued-slope method."""

    def run_k(k):
        t0 = time.perf_counter()
        r = None
        for _ in range(k):
            r = fn()
        flush(r)
        return time.perf_counter() - t0

    while (run_k(k2) - run_k(k1)) < target_s and k2 < 8000:
        k2 = min(k2 * 4, 8000)
    slopes = [(run_k(k2) - run_k(k1)) / (k2 - k1) for _ in range(reps)]
    return float(np.median(slopes)), float(max(slopes) - min(slopes))


def cost_of(compiled):
    # The cost model has no entry for custom-call HLO (the Pallas kernel
    # lowers to one) and some backends raise instead of skipping — NaN
    # keeps the measured-ms row while dropping the model-derived columns.
    try:
        ca = compiled.cost_analysis()
    except Exception:  # backend-specific raise on custom-call HLO
        return float("nan"), float("nan")
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return float(ca.get("bytes accessed", float("nan"))), float(
        ca.get("flops", float("nan"))
    )


def nbytes_tree(tree):
    import jax

    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree.leaves(tree)
        if hasattr(l, "shape")
    )


def measure(name, jitted, args, flush_leaf, rows, min_traffic=None,
            note=""):
    import jax

    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    xla_bytes, xla_flops = cost_of(compiled)
    out = jitted(*args)  # warm
    flush_leaf(out)
    dt, spread = slope_time(lambda: jitted(*args), flush_leaf)
    # Utilisation is derived from the ANALYTIC minimum traffic (live
    # inputs read once, outputs written once), never from the XLA cost
    # model: ``cost_analysis()`` sums per-fusion byte accounting in which
    # one logical array read by N fusions counts N times, so cost-model
    # "achieved GB/s" exceeded the physical HBM roof (>100% reported in
    # rounds 4-5 — impossible numbers).  min_traffic/dt is a true LOWER
    # bound on achieved bandwidth; the cost-model bytes stay in the row
    # as the fusion-inefficiency signal they actually are (their ratio
    # to min_traffic ~= how many times XLA re-touches each byte).
    row = {
        "component": name,
        "ms": dt * 1e3,
        "ms_spread": spread * 1e3,
        "xla_bytes": xla_bytes,
        "xla_flops": xla_flops,
        "achieved_gflops": xla_flops / dt / 1e9,
        "min_traffic_bytes": min_traffic,
        "note": note,
    }
    pct = ""
    if min_traffic:
        row["min_traffic_gbps"] = min_traffic / dt / 1e9
        row["pct_hbm_roof_lower_bound"] = (
            100.0 * row["min_traffic_gbps"] / HBM_GBPS
        )
        row["traffic_amplification_xla"] = xla_bytes / min_traffic
        # Time the kernel would take if it only moved the live inputs and
        # outputs once at the full bandwidth roof.
        row["fusion_perfect_ms"] = min_traffic / (HBM_GBPS * 1e9) * 1e3
        pct = (
            f"-> >= {row['min_traffic_gbps']:6.1f} GB/s "
            f">= {row['pct_hbm_roof_lower_bound']:.1f}% of HBM roof, "
            f"{row['traffic_amplification_xla']:.1f}x cost-model traffic"
        )
    print(
        f"{name:24s} {dt*1e3:8.2f} ms  (spread {spread*1e3:.2f})  "
        f"XLA {xla_bytes/1e6:8.1f} MB  {xla_flops/1e9:7.2f} GFLOP  {pct}",
        file=sys.stderr,
    )
    rows.append(row)
    return row


def tip_components(n_pix, rows):
    import jax
    import jax.numpy as jnp

    from kafka_tpu.core.solvers import assimilate_date_jit, kalman_update
    from kafka_tpu.testing.synthetic import make_tip_problem

    op, bands, x0, p_inv0 = make_tip_problem(n_pix)
    p = op.n_params
    n_bands = op.n_bands
    opts = {
        "state_bounds": (
            jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
        )
    }

    # -- linearize: reads x (n,p), writes h0 (B,n) + jac (B,n,p).
    lin_jit = jax.jit(lambda x: op.linearize(None, x))
    measure(
        f"tip/linearize", lin_jit, (x0,),
        lambda o: np.asarray(o.h0[0, :1]), rows,
        min_traffic_linearize(n_pix, p, n_bands),
        note=f"value+jacfwd, p={p}, {n_bands} bands",
    )

    # -- update: reads lin + obs + x_lin + x_f + p_inv_f, writes x + A.
    lin = jax.block_until_ready(lin_jit(x0))
    upd_jit = jax.jit(
        lambda l, b, xl, xf, pf: kalman_update(l, b, xl, xf, pf)
    )
    measure(
        f"tip/update", upd_jit, (lin, bands, x0, x0, p_inv0),
        lambda o: np.asarray(o[0][:1, 0]), rows,
        min_traffic_update(n_pix, p, n_bands),
        note="packed assembly + packed Cholesky + substitution",
    )

    # -- full GN while_loop (production path).
    args = (op.linearize, bands, x0, p_inv0, None, opts)
    full = lambda: assimilate_date_jit(*args)
    out = full()
    n_iters = int(out[2].n_iterations)
    # Fusion-perfect traffic for the WHOLE solve: inputs once, outputs
    # once — iterations live in VMEM/registers in the ideal kernel.
    min_full = min_traffic_gn_full(n_pix, p, n_bands)
    row = measure(
        f"tip/gn_full", _full_jit(op, opts), (bands, x0, p_inv0),
        lambda o: np.asarray(o[0][:1, 0]), rows, min_full,
        note=f"{n_iters} GN iterations (lax.while_loop)",
    )
    row["n_iterations"] = n_iters

    # -- the same full GN loop on the fused Pallas paths (use_pallas):
    # the BASELINE.md "Roofline" rows.  Real-chip only — the CPU
    # interpreter times the Pallas interpreter, not the kernel.
    # inkernel_linearize is pinned False here so this row keeps
    # measuring the PR 1 kernel generation (out-of-kernel linearise).
    if jax.default_backend() == "tpu":
        row_pl = measure(
            "tip/gn_full_pallas",
            _full_jit(op, {**opts, "use_pallas": True,
                           "inkernel_linearize": False}),
            (bands, x0, p_inv0),
            lambda o: np.asarray(o[0][:1, 0]), rows, min_full,
            note=f"{n_iters} GN iterations, fused VMEM-resident kernel",
        )
        row_pl["n_iterations"] = n_iters
        # -- the in-kernel-linearise generation: the WHOLE loop as one
        # launch.  Re-derived analytic bound (perf.min_traffic_gn_inkernel):
        # with linearisation, iteration carry and packed A all
        # VMEM-resident, the only HBM traffic left is the observations
        # in, the forecast in (the packed prior triangle — the dense
        # (p, p) batch never needs to cross for the kernel proper), and
        # the solution + diagnostics out.  Unlike min_full above this
        # bound COUNTS the diagnostic outputs (fwd, innovations,
        # per-block counters) the solve emits — gn_full's bound
        # conservatively omitted them.
        min_inkernel = min_traffic_gn_inkernel(n_pix, p, n_bands)
        row_ik = measure(
            "tip/gn_inkernel",
            _full_jit(op, {**opts, "use_pallas": True,
                           "inkernel_linearize": True}),
            (bands, x0, p_inv0),
            lambda o: np.asarray(o[0][:1, 0]), rows, min_inkernel,
            note=(
                f"whole GN loop ({n_iters} iters) + analytic "
                "linearisation in ONE kernel launch"
            ),
        )
        row_ik["n_iterations"] = n_iters
    else:
        print(
            "tip/gn_full_pallas       skipped - no TPU (interpret-mode "
            "timings measure the interpreter, not the kernel)",
            file=sys.stderr,
        )
        print(
            "tip/gn_inkernel          skipped - no TPU (interpret-mode "
            "timings measure the interpreter, not the kernel)",
            file=sys.stderr,
        )
    return rows


def _full_jit(op, opts):
    import jax

    from kafka_tpu.core.solvers import assimilate_date_jit

    def f(bands, x0, p_inv0):
        return assimilate_date_jit(op.linearize, bands, x0, p_inv0,
                                   None, opts)

    return jax.jit(f)


def prosail_components(n_pix, rows):
    import jax
    import jax.numpy as jnp

    from kafka_tpu.cli.drivers import prosail_aux_builder
    from kafka_tpu.core.solvers import kalman_update
    from kafka_tpu.core.types import BandBatch
    from kafka_tpu.engine.priors import sail_prior
    from kafka_tpu.obsops.prosail import ProsailOperator

    op = ProsailOperator()
    p = op.n_params
    n_bands = op.n_bands
    prior = sail_prior()
    rng = np.random.default_rng(0)
    mean = np.asarray(prior.prior.mean, np.float32)
    inv = np.asarray(prior.prior.inv_cov, np.float32)
    x0 = jnp.asarray(
        np.clip(mean + rng.normal(0, 0.02, (n_pix, p)), 0.02, 0.98)
        .astype(np.float32)
    )
    p_inv0 = jnp.broadcast_to(jnp.asarray(inv), (n_pix, p, p))
    aux = prosail_aux_builder(
        {"sza": 30.0, "saa": 120.0, "vza": 5.0, "vaa": 200.0}, None
    )

    lin_jit = jax.jit(lambda x: op.linearize(aux, x))
    measure(
        "prosail/linearize", lin_jit, (x0,),
        lambda o: np.asarray(o.h0[0, :1]), rows,
        min_traffic_linearize(n_pix, p, n_bands),
        note=f"exact-SAIL value+jacfwd, p={p}, {n_bands} bands",
    )

    lin = jax.block_until_ready(lin_jit(x0))
    y = np.asarray(lin.h0) + rng.normal(
        0, 0.005, (n_bands, n_pix)
    ).astype(np.float32)
    mask = np.ones((n_bands, n_pix), bool)
    bands = BandBatch(
        y=jnp.asarray(y.astype(np.float32)),
        r_inv=jnp.asarray(np.full((n_bands, n_pix), 1 / 0.005**2, np.float32)),
        mask=jnp.asarray(mask),
    )
    upd_jit = jax.jit(
        lambda l, b, xl, xf, pf: kalman_update(l, b, xl, xf, pf)
    )
    measure(
        "prosail/update", upd_jit, (lin, bands, x0, x0, p_inv0),
        lambda o: np.asarray(o[0][:1, 0]), rows,
        min_traffic_update(n_pix, p, n_bands),
        note="packed assembly + packed Cholesky + substitution",
    )
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 19)
    ap.add_argument("--json", type=str, default=None)
    ap.add_argument("--skip-prosail", action="store_true")
    args = ap.parse_args()

    from kafka_tpu.utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    import jax

    np.asarray(jax.jit(lambda v: v + 1)(jax.numpy.zeros(8)))  # sync regime

    rows: list = []
    tip_components(args.n, rows)
    if not args.skip_prosail:
        prosail_components(args.n, rows)

    out = {
        "n_pix": args.n,
        "hbm_gbps_roof": HBM_GBPS,
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }
    print(json.dumps(out, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
