"""fleet_status — one live view over a multi-process kafka_tpu fleet.

Merges every ``live_<host>_<pid>.json`` heartbeat snapshot under a
telemetry root (``kafka_tpu.telemetry.live``) into the fleet view
(``kafka_tpu.telemetry.aggregate``): per-worker liveness (heartbeat
age; a stale heartbeat without a clean-shutdown marker flags the host
DEAD), counters summed across processes, gauges per host, serve/phase
latency histograms merged into fleet p50/p99, crash-dump pointers, and
— when the workers ran the PR 7 lease queue — the queue's chunk counts
(auto-discovered from worker status, or ``--queue-dir``).

``--stitch-trace OUT.json`` additionally merges the per-process
``trace.json`` fragments under the root into ONE Chrome trace (each
process its own named pid track, timestamps aligned on the shared
wall-clock epoch) — open it at https://ui.perfetto.dev.

``--watch N`` turns the one-shot view into a live dashboard: clear the
screen and re-render every N seconds until Ctrl-C (clean exit 0);
``--watch-count M`` stops after M redraws (the smoke-test hook).

Usage:
    python -m tools.fleet_status /path/to/telemetry [--json]
        [--ttl-s 6] [--queue-dir DIR] [--stitch-trace OUT] [--run-id ID]
        [--watch N [--watch-count M]]

Exit codes: 0 (view rendered, dead hosts included — liveness is a
report, not an error), 2 usage/missing root.  Strictly read-only apart
from the optional stitched-trace output file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def render(fleet: dict) -> str:
    """Human-readable one-screen summary of an ``aggregate_fleet``
    view (+ the optional ``queue`` section)."""
    lines = [
        f"fleet: {fleet['n_workers']} worker(s), "
        f"run_ids={','.join(fleet['run_ids']) or '-'}",
    ]
    for w in fleet["workers"]:
        state = "DEAD" if w["dead"] else \
            ("exited" if w["final"] else "live")
        extra = ""
        q = w.get("quality") or {}
        if q.get("last_verdict"):
            extra += f"  quality={q['last_verdict']}" + \
                ("(DRIFT)" if q.get("drift_active") else "")
        p = w.get("perf") or {}
        if p.get("px_steps_per_s"):
            extra += f"  perf={p['px_steps_per_s']:.3g}px/s"
            if p.get("device_fraction") is not None:
                extra += f",df={p['device_fraction']:.2f}"
        # Per-worker device-plane column (telemetry.devprof): mesh
        # axes, collective fraction of the newest parsed capture, and
        # the top kernel — the mesh-balance glance.
        dp = w.get("devprof") or {}
        if dp.get("mesh") and (dp["mesh"].get("axes") or {}):
            axes = ",".join(
                f"{k}={v}" for k, v in dp["mesh"]["axes"].items()
            )
            extra += f"  mesh[{axes}]"
        if dp.get("collective_fraction") is not None:
            extra += f"  coll={dp['collective_fraction']:.0%}"
        elif dp.get("top_kernel"):
            extra += f"  kern={dp['top_kernel']['name'][:24]}"
        # Per-worker SLO alert state (telemetry.slo): name the firing
        # objectives inline; the deduped fleet line renders below.
        s = w.get("slo") or {}
        if s.get("firing"):
            shown = ",".join(
                f"{a.get('objective')}({a.get('severity')})"
                for a in s["firing"][:4]
            )
            extra += f"  slo=FIRING[{shown}]"
        if w["crash_dumps"]:
            extra += f"  crash={w['crash_dumps'][-1]}"
        lines.append(
            f"  {w['key']} [{w['role']}] {state}  "
            f"heartbeat {w['age_s']:.1f}s ago{extra}"
        )
        # Per-request view (ISSUE 14): the compact recent_requests
        # status fact both kafka-serve and kafka-route publish — the
        # fleet-level echo of their /requestz endpoints.
        recent = (w.get("status") or {}).get("recent_requests") or ()
        if recent:
            shown = ", ".join(
                f"{r.get('request_id')}"
                f"({r.get('status')}"
                + (f",{r['served_from']}" if r.get("served_from")
                   else "")
                + (f",{r['e2e_ms']:.0f}ms"
                   if isinstance(r.get("e2e_ms"), (int, float))
                   else "")
                + ")"
                for r in recent[-3:]
            )
            lines.append(f"    recent: {shown}")
    if fleet["dead_hosts"]:
        lines.append(f"dead hosts: {', '.join(fleet['dead_hosts'])}")
    lines.extend(_render_routers(fleet))
    fq = fleet.get("quality") or {}
    if fq.get("drifting_workers"):
        lines.append(
            "quality drift ACTIVE on: "
            + ", ".join(fq["drifting_workers"])
        )
    # Fleet SLO alert line (telemetry.aggregate roll-up): an objective
    # firing on ANY worker fires fleet-wide, deduped per (objective,
    # severity) with the workers it fires on.
    fs = fleet.get("slo") or {}
    for a in fs.get("firing") or ():
        lines.append(
            f"SLO ALERT FIRING: {a['objective']} [{a['severity']}] "
            f"on {', '.join(a['workers'])}"
        )
    queue = fleet.get("queue")
    if queue:
        c = queue["counts"]
        lines.append(
            f"queue: {queue['outdir']}  done={c['done']} "
            f"failed={c['failed']} leased={c['leased']} "
            f"expired={c['lease_expired']} pending={c['pending']}"
        )
    interesting = [
        (k, v) for k, v in sorted(fleet["counters"].items())
        if not k.startswith("kafka_live_")
    ]
    if interesting:
        lines.append("counters (fleet totals):")
        for k, v in interesting[:24]:
            lines.append(f"  {k} {v:g}")
        if len(interesting) > 24:
            lines.append(f"  ... {len(interesting) - 24} more "
                         "(use --json)")
    hists = {
        k: h for k, h in sorted(fleet["histograms"].items())
        if h["count"]
    }
    if hists:
        lines.append("histograms (fleet-merged):")
        for k, h in hists.items():
            p50 = "-" if h["p50"] is None else f"{h['p50']:.4g}"
            p99 = "-" if h["p99"] is None else f"{h['p99']:.4g}"
            lines.append(
                f"  {k}  n={h['count']} p50={p50} p99={p99}"
            )
    if fleet["crash_dumps"]:
        lines.append("crash dumps:")
        for c in fleet["crash_dumps"]:
            lines.append(f"  {c['worker']}: {c['file']}")
    return "\n".join(lines)


def _render_routers(fleet: dict) -> list:
    """The router view (ISSUE 13): for every ``kafka-route`` worker in
    the fleet, its ring ownership per replica, tiles in flight, the
    re-route / rebalance counters and the last failover timestamp —
    read from the ``router_*`` status facts the router publishes with
    each live snapshot."""
    import datetime

    lines = []
    for w in fleet.get("workers") or ():
        st = w.get("status") or {}
        if w.get("role") != "route" and "router_ring" not in st:
            continue
        failover = st.get("router_last_failover_ts")
        failover_txt = "-" if not failover else \
            datetime.datetime.fromtimestamp(failover).isoformat(
                timespec="seconds"
            )
        lines.append(
            f"router {w['key']}: "
            f"routable={len(st.get('router_routable') or ())}/"
            f"{len(st.get('router_replicas') or ())} "
            f"inflight={st.get('router_inflight', 0)} "
            f"rerouted={st.get('router_rerouted_total', 0)} "
            f"rebalanced={st.get('router_rebalanced_total', 0)} "
            f"last_failover={failover_txt}"
        )
        dead = st.get("router_dead") or []
        if dead:
            lines.append(f"  dead replicas: {', '.join(dead)}")
        ring = st.get("router_ring") or {}
        for rid in sorted(ring):
            tiles = ring[rid]
            shown = ",".join(tiles[:6]) + \
                (",..." if len(tiles) > 6 else "")
            marker = " DEAD" if rid in dead else ""
            lines.append(
                f"  ring {rid}{marker}: {len(tiles)} tile(s)"
                + (f" [{shown}]" if tiles else "")
            )
    return lines


def build_view(root: str, ttl_s=None, queue_dir=None) -> dict:
    """The fleet view dict (the ``--json`` payload), importable for
    tests and other tools."""
    from kafka_tpu.telemetry.aggregate import (
        aggregate_fleet, discover_queue_outdir, load_live_snapshots,
        worker_liveness,
    )

    snaps = load_live_snapshots(root)
    fleet = aggregate_fleet(snaps, ttl_s=ttl_s)
    fleet["telemetry_root"] = os.path.abspath(root)
    queue_dir = queue_dir or discover_queue_outdir(snaps)
    fleet["queue"] = None
    if queue_dir and os.path.isdir(queue_dir):
        from kafka_tpu.shard.queue import queue_status

        status = queue_status(queue_dir)
        liveness = worker_liveness(snaps, ttl_s=ttl_s)
        for owner, w in status["workers"].items():
            w["liveness"] = liveness.get(owner)
        fleet["queue"] = status
    return fleet


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    ap.add_argument("root", help="telemetry root holding live_*.json "
                                 "snapshots (searched recursively)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable dump instead of the summary")
    ap.add_argument("--ttl-s", type=float, default=None,
                    help="heartbeat staleness beyond which a non-final "
                         "snapshot flags its host dead (default: 3x "
                         "each snapshot's own publish interval)")
    ap.add_argument("--queue-dir", default=None,
                    help="lease-queue outdir to fold in (default: "
                         "auto-discovered from worker snapshots)")
    ap.add_argument("--stitch-trace", default=None, metavar="OUT",
                    help="also merge per-process trace.json fragments "
                         "under the root into OUT (one Chrome trace)")
    ap.add_argument("--run-id", default=None,
                    help="only stitch trace fragments carrying this "
                         "run id")
    ap.add_argument("--request-id", default=None,
                    help="with --stitch-trace: stitch ONE request's "
                         "cross-process waterfall (router + replica "
                         "tracks, flow arrows across the hops)")
    ap.add_argument("--watch", type=float, default=None,
                    metavar="SECONDS",
                    help="live dashboard mode: clear the screen and "
                         "re-render every SECONDS until Ctrl-C")
    ap.add_argument("--watch-count", type=int, default=0,
                    help="with --watch: stop after this many redraws "
                         "(0 = until Ctrl-C; the smoke-test hook)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.root):
        print(f"fleet_status: no such directory: {args.root}",
              file=sys.stderr)
        return 2
    if args.watch is None:
        return _render_once(args)
    # Live dashboard: fixed-cadence redraw, Ctrl-C = clean exit.  The
    # ANSI clear keeps it dependency-free (no curses).
    import time

    n = 0
    try:
        while True:
            print("\x1b[2J\x1b[H", end="")
            _render_once(args)
            n += 1
            if args.watch_count and n >= args.watch_count:
                return 0
            # kafkalint: disable=ad-hoc-retry — fixed-cadence dashboard
            # redraw, not a retry/backoff loop
            time.sleep(max(0.0, args.watch))
    except (KeyboardInterrupt, BrokenPipeError):
        # Ctrl-C, or the consumer of a piped dashboard went away —
        # both are clean ends of a watch session.
        return 0


def _render_once(args) -> int:
    """One view build + render (the body of the non-watch mode and of
    each watch iteration)."""
    fleet = build_view(args.root, ttl_s=args.ttl_s,
                       queue_dir=args.queue_dir)
    if args.stitch_trace:
        from kafka_tpu.telemetry.aggregate import stitch_traces

        doc = stitch_traces(args.root, run_id=args.run_id,
                            request_id=args.request_id)
        with open(args.stitch_trace, "w") as f:
            json.dump(doc, f)
        fleet["stitched_trace"] = {
            "path": os.path.abspath(args.stitch_trace),
            "sources": doc["otherData"]["sources"],
            "events": len(doc["traceEvents"]),
        }
    if args.json:
        print(json.dumps(fleet, indent=2, sort_keys=True))
    else:
        print(render(fleet))
        if fleet.get("stitched_trace"):
            st = fleet["stitched_trace"]
            print(f"stitched trace: {st['path']} "
                  f"({len(st['sources'])} process track(s), "
                  f"{st['events']} events)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
