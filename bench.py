"""Benchmark: assimilation throughput (pixels/sec) vs the CPU reference path.

The reference publishes no numbers (SURVEY.md §6), so the baseline is
*measured*: the NumPy/SciPy-sparse oracle of its solver path
(``kafka_tpu.testing.oracle`` — sparse block-diagonal normal equations +
SuperLU, the exact algorithm of
``/root/reference/kafka/inference/solvers.py:100-145`` with the
``linear_kf.py:245-307`` Gauss-Newton loop) on this host's CPU, on the
reference's own chunk size (16384 pixels = one 128x128 chunk,
``kafka_test_S2.py:202``).  Ours is the identical problem solved by the
jitted batched-dense TPU path.

Prints ONE JSON line:
    {"metric": "assimilation_throughput", "value": <device px/s>,
     "unit": "pixels/sec", "vs_baseline": <speedup over SciPy CPU>, ...}
plus (a) the fused-Pallas device rows (``device_pallas_ms`` vs
``device_xla_ms`` at 2^19 px — the BASELINE.md "Roofline" pair, ~3.8 vs
~6.4 ms on a healthy v5e — and ``device_pallas_fused_lin_ms``, the
in-kernel-linearise generation that keeps the whole Gauss-Newton loop
VMEM-resident; all null off-TPU where interpret-mode timings would
be fiction) and (b) the bench health layer (``probe_device_ms``,
``probe_host_ms``, ``unhealthy`` — see ``probe_health``), which exists
because rounds 3-5 archived 35.7k/72.8k/44.0k e2e px-steps/s with no code
change: tunnel/host weather, now measured and flagged instead of trusted.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Bench health layer — PR 2 moved the probes into the shared telemetry
# subsystem (``kafka_tpu.telemetry.health``): every probe records its
# reading into the metrics registry and ``probe_health`` sources its
# verdict FROM the registry, so the bench and production runs read the
# same gauges.  The re-exports below keep the long-standing bench API
# (``bench.probe_health`` etc.) and thresholds importable from here.
#
# The r03-r05 e2e rows swung 35.7k / 72.8k / 44.0k px-steps/s with NO code
# change — tunnel congestion and host load, not the software under test.
# Every bench run therefore probes BOTH noise sources and records the
# readings next to the numbers they contaminate, so an off-band artifact is
# flagged instead of silently archived as a regression (or an improvement).
# ---------------------------------------------------------------------------

from kafka_tpu.telemetry import get_registry
from kafka_tpu.telemetry.health import (  # noqa: F401 — bench API re-export
    DEVICE_BAND,
    HEALTHY_DEVICE_MS,
    HEALTHY_HOST_MS,
    probe_device,
    probe_health,
    probe_host,
)


def bench_device_sizes(sizes, ks=(5, 25), use_pallas=False,
                       inkernel_linearize=None):
    """Jitted batched-dense iterated solve on the default JAX device.

    Measurement methodology (matters on a tunneled TPU): before the first
    device->host read the tunnel client DEFERS execution —
    ``block_until_ready`` returns immediately and naive timings are
    fiction; after it, every synchronous round-trip costs ~13 ms of
    latency that queued work does not pay.  So we (a) force the
    synchronous regime up front with one tiny D2H, then (b) measure the
    sustained pipelined rate by timing ``k`` queued solves flushed by one
    scalar read, for two values of ``k`` — the slope
    ``(T(k2)-T(k1))/(k2-k1)`` is the true per-solve time, with flush and
    round-trip fixed costs cancelled.  This is also the honest model of
    production use: the engine queues per-date programs and syncs rarely.
    Returns ``{n_pix: (pixels_per_sec, median_ms_per_solve,
    slope_spread_ms)}`` with the median pooled over every burst of that
    size in ``sizes``.

    ``use_pallas`` measures the fused VMEM-resident Pallas path instead
    of the XLA-fused one — the same jitted GN loop with the per-date
    update as ONE kernel launch (BASELINE.md "Roofline": 6.45 -> 3.80 ms
    at 2^19 px on a healthy v5e window).  ``inkernel_linearize`` pins the
    solver's same-named static flag so the two kernel generations stay
    separable rows: False = the PR 1 whole-update kernel (out-of-kernel
    linearise, ``device_pallas_ms``), True = the in-kernel Gauss-Newton
    path (``device_pallas_fused_lin_ms`` — linearisation, iteration carry
    and packed A all VMEM-resident).
    """
    import jax
    import jax.numpy as jnp

    from kafka_tpu.core.solvers import assimilate_date_jit
    from kafka_tpu.testing.synthetic import make_tip_problem

    np.asarray(jax.jit(lambda v: v + 1)(jnp.zeros(8)))  # sync regime on
    slopes_by_size: dict = {}
    k2_by_size: dict = {}
    # Small batches are latency-dominated and the tunnel's per-dispatch
    # overhead drifts at minute scale (observed 10x swings between
    # invocations); repeated sizes in ``sizes`` therefore measure in
    # SEPARATE bursts spread across the run and pool their slopes.
    for n_pix in sizes:
        op, bands, x0, p_inv0 = make_tip_problem(n_pix)
        opts = {"state_bounds": (
            jnp.asarray(op.state_bounds[0]), jnp.asarray(op.state_bounds[1])
        )}
        if use_pallas:
            opts["use_pallas"] = True
        if inkernel_linearize is not None:
            opts["inkernel_linearize"] = bool(inkernel_linearize)
        args = (op.linearize, bands, x0, p_inv0, None, opts)
        x, p_inv, diags = assimilate_date_jit(*args)  # compile
        np.asarray(x[0][:1])  # flush

        def run_k(k):
            t0 = time.perf_counter()
            for _ in range(k):
                r, _, _ = assimilate_date_jit(*args)
            np.asarray(r[0][:1])  # flush the queue
            return time.perf_counter() - t0

        # Grow k2 until the measured k2-k1 delta itself clearly exceeds
        # the flush round-trip noise (~0.1 s on the tunnel): a fixed-size
        # pilot can't be trusted for sub-millisecond solves, where a few
        # solves' worth of work is buried in that noise.  Then median of
        # 5 slope estimates.  A later burst of the same size reuses the
        # k2 its first burst discovered (still valid under drift — k2
        # only ever needs to be LARGE enough) instead of re-paying the
        # escalation's thousands of extra solves.
        k1, k2 = ks
        k2 = max(k2, k2_by_size.get(n_pix, k2))
        if n_pix not in k2_by_size:
            while (run_k(k2) - run_k(k1)) < 1.5 and k2 < 8000:
                k2 = min(k2 * 4, 8000)
            k2_by_size[n_pix] = k2
        burst = [
            (run_k(k2) - run_k(k1)) / (k2 - k1) for _ in range(5)
        ]
        slopes_by_size.setdefault(n_pix, []).extend(burst)
        dt = float(np.median(burst))
        tag = "xla"
        if use_pallas:
            tag = "pallas+inlin" if inkernel_linearize else "pallas"
        print(
            f"device[{tag}]: {n_pix} px, "
            f"{int(diags.n_iterations)} GN iters, "
            f"{dt*1e3:.2f} ms/solve sustained on "
            f"{jax.devices()[0].platform}",
            file=sys.stderr,
        )
    out = {}
    for n_pix, slopes in slopes_by_size.items():
        dt = float(np.median(slopes))
        out[n_pix] = (
            n_pix / dt, dt * 1e3,
            (max(slopes) - min(slopes)) * 1e3,
        )
    return out


def bench_oracle(n_pix: int, reps: int = 5):
    """The reference algorithm (sparse block-diag + SuperLU) on host CPU.

    A WARM-UP solve runs before the timed reps: the first call pays
    SuperLU's symbolic factorisation and lazy-import costs, which are
    setup, not solve — BENCH_r05 recorded an ``oracle_ms_spread`` of
    1922 ms against a 662 ms median because that first call sat inside
    the timed window and dominated the spread.  Median of ``reps`` timed
    runs with the spread AND the min reported (min-of-k is the classic
    load-noise-robust statistic: host-load contamination only ever adds
    time, so the minimum is the cleanest single observation and the
    cross-round comparator ``tools/bench_compare.py`` consumers should
    prefer when the spread is wide).  Returns
    ``(pixels_per_sec_median, median_ms, spread_ms, min_ms)``.
    """
    import jax

    from kafka_tpu.testing.oracle import iterated_sparse_solve
    from kafka_tpu.testing.synthetic import make_tip_problem

    op, bands, x0, p_inv0 = make_tip_problem(n_pix, host=True)
    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError as e:
        # Fail loudly: silently falling back to the default device would
        # run the "CPU baseline" on the TPU and poison the device
        # timings that follow (see bench_device_sizes).
        raise RuntimeError(
            "bench_oracle needs the JAX CPU platform for the baseline"
        ) from e
    y_b = list(bands.y)
    r_b = list(bands.r_inv)
    m_b = list(bands.mask)

    def linearize(x):
        # CPU backend on purpose: this is the CPU baseline, and a TPU
        # round-trip here would also poison the later device timings.
        with jax.default_device(cpu):
            lin = op.linearize(
                None, jax.device_put(np.asarray(x, np.float32), cpu)
            )
            return list(np.asarray(lin.h0)), list(np.asarray(lin.jac))

    x0_np = np.asarray(x0)
    p_inv_np = np.asarray(p_inv0)
    # Untimed warm-up: symbolic factorisation + imports happen here, not
    # inside the first timed rep (see docstring).
    iterated_sparse_solve(linearize, y_b, r_b, m_b, x0_np, p_inv_np)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        _, _, n_iters = iterated_sparse_solve(
            linearize, y_b, r_b, m_b, x0_np, p_inv_np
        )
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))
    spread = float(max(times) - min(times))
    best = float(min(times))
    print(
        f"oracle: {n_pix} px, {n_iters} GN iters, {dt*1e3:.1f} ms/solve "
        f"median of {reps} warm (spread {spread*1e3:.1f} ms, "
        f"min {best*1e3:.1f} ms, SciPy SuperLU)",
        file=sys.stderr,
    )
    return n_pix / dt, dt * 1e3, spread * 1e3, best * 1e3


def bench_end_to_end(ny: int = 204, nx: int = 235, n_dates: int = 3,
                     outdir=None, full_mask: bool = False,
                     noise: float = 0.002, passes: int = 5):
    """Full-pipeline throughput INCLUDING host I/O (SURVEY §7(d)):
    on-disk S2 granule tree -> read/decode -> gather -> jitted PROSAIL
    assimilation -> GeoTIFF outputs, at the Barrax problem scale
    (``kafka_test_S2.py:189-205``).  Returns (pixel_steps/sec median of
    ``passes``, device fraction of the median pass's wall, n_pixels,
    pixel_steps/sec spread).

    The e2e row is the bench's noisiest: rounds 3-5 archived
    35.7k/72.8k/44.0k px-steps/s with NO code change (tunnel + host
    weather at sub-second walls).  The row is therefore the MEDIAN of
    ``passes`` measured rates with the max-min spread reported
    alongside (``e2e_pixel_steps_per_s_spread``), so a cross-round
    consumer (tools/bench_history.py) can see when the number is too
    dispersed to trend instead of trusting one roll of the dice."""
    import datetime
    import shutil
    import tempfile

    from kafka_tpu.engine import KalmanFilter
    from kafka_tpu.engine.priors import sail_prior
    from kafka_tpu.io import GeoTIFFOutput
    from kafka_tpu.io.sentinel2 import Sentinel2Observations
    from kafka_tpu.cli.drivers import prosail_aux_builder
    from kafka_tpu.obsops.prosail import ProsailOperator
    from kafka_tpu.testing.fixtures import (
        DEFAULT_GEO, make_pivot_mask, make_s2_granule_tree,
    )

    tmp = outdir or tempfile.mkdtemp(prefix="kafka_bench_")
    try:
        dates = [
            datetime.datetime(2017, 7, 1) + datetime.timedelta(days=2 * i)
            for i in range(n_dates)
        ]
        make_s2_granule_tree(
            f"{tmp}/s2", dates, ny=ny, nx=nx, noise=noise
        )
        mask = (np.ones((ny, nx), bool) if full_mask
                else make_pivot_mask(ny, nx, n_pivots=5, seed=0))
        prior = sail_prior()
        obs = Sentinel2Observations(
            f"{tmp}/s2", ProsailOperator(),
            (DEFAULT_GEO.geotransform, DEFAULT_GEO.epsg),
            aux_builder=prosail_aux_builder,
        )
        output = GeoTIFFOutput(
            prior.parameter_list, list(DEFAULT_GEO.geotransform),
            DEFAULT_GEO.projection, folder=f"{tmp}/out",
            epsg=DEFAULT_GEO.epsg, async_writes=True,
            # Fast-wire opt-in (the benchmarked performance mode; the
            # DEFAULT wire is bit-exact float32 — io.output).
            wire_dtype="float16",
        )
        kf = KalmanFilter(
            obs, output, mask, prior.parameter_list,
            state_propagation=None, prior=prior,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.zeros(10, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [
            dates[0] - datetime.timedelta(days=1),
            *[d + datetime.timedelta(days=1) for d in dates],
        ]
        # Warm-up compile on the full grid so BOTH programs (the
        # single-window solve and the fused multi-window scan) are built
        # and cache-loaded before timing; then MEDIAN of ``passes``
        # measured rates — single-pass e2e walls at this size swing ~2x
        # with tunnel/host noise (observed 0.35-0.78 s across rounds).
        kf.run(grid, x0, None, p_inv0)
        # Drain the warm-up's async writes BEFORE timing, or the first
        # pass's flush pays the warm-up backlog and inflates the spread.
        output.flush()
        walls, devices = [], []
        for _ in range(max(1, passes)):
            kf.diagnostics_log.clear()
            t0 = time.perf_counter()
            kf.run(grid, x0, None, p_inv0)
            output.flush()
            walls.append(time.perf_counter() - t0)
            devices.append(sum(r["wall_s"] for r in kf.diagnostics_log))
        output.close()
        order = int(np.argsort(walls)[len(walls) // 2])
        wall, device_s = walls[order], devices[order]
        n_pix = kf.gather.n_valid
        steps = len(kf.diagnostics_log)
        rates = [n_pix * steps / w for w in walls]
        px_steps_s = n_pix * steps / wall
        spread = float(max(rates) - min(rates))
        print(
            f"e2e: {n_pix} px x {steps} dates incl. host I/O: "
            f"{wall:.2f}s wall median of {len(walls)} (rate spread "
            f"{spread:.0f} px-steps/s), {device_s:.2f}s in solves "
            f"({100 * device_s / wall:.0f}%)",
            file=sys.stderr,
        )
        return px_steps_s, device_s / wall, n_pix, spread
    finally:
        if outdir is None:
            shutil.rmtree(tmp, ignore_errors=True)


def assemble_result(
    health: dict,
    *,
    oracle,                # (px_s, ms_median, ms_spread, ms_min) @ n_matched
    device_matched,        # (px_s, ms_median, ms_spread) @ n_matched
    device,                # (px_s, ms_median, ms_spread) @ n_device
    pallas,                # same triple or None (off-TPU)
    e2e,                   # (px_steps_s, device_fraction, n_pixels[, spread])
    host_after_ms: float,
    fused_lin=None,        # (px_s, ms_median, ms_spread) or None (off-TPU)
    serve=None,            # tools/loadgen rows dict or None
    fleet=None,            # tools/loadgen bench_fleet rows dict or None
    sweep=None,            # tools/loadgen bench_concurrency_sweep dict or None
    smoother=None,         # bench_smoother_rows dict or None
    n_matched: int = 16384,
    n_device: int = 1 << 19,
    registry=None,
) -> dict:
    """Assemble the one-line BENCH JSON from measured rows.

    Split out of ``main`` so the off-TPU schema smoke test
    (tests/test_bench_schema.py) exercises the EXACT artifact-assembly
    path — key set, null conventions, health fields — without paying for
    the measurements.  The health fields keep the PR 1 schema unchanged;
    ``telemetry`` embeds the registry's compact counter/gauge snapshot
    (including the health gauges the probes recorded).
    """
    base_px_s, oracle_ms, oracle_spread_ms, oracle_min_ms = oracle
    dev_matched_px_s, matched_ms, matched_spread_ms = device_matched
    dev_px_s, xla_ms, xla_spread_ms = device
    pallas_px_s, pallas_ms, pallas_spread_ms = \
        pallas if pallas is not None else (None, None, None)
    fl_px_s, fl_ms, fl_spread_ms = \
        fused_lin if fused_lin is not None else (None, None, None)
    # Back-compat: pre-denoise callers hand a 3-tuple (no spread).
    e2e_px_steps_s, device_frac, e2e_pix = e2e[:3]
    e2e_spread = e2e[3] if len(e2e) > 3 else None
    reg = registry if registry is not None else get_registry()
    # Close the health bracket: a window that degraded DURING the run is
    # as contaminating as one that started bad (r03-r05 e2e noise).
    unhealthy = bool(health["unhealthy"]) or \
        host_after_ms > HEALTHY_HOST_MS
    return {
        "metric": "assimilation_throughput",
        "value": round(dev_px_s, 1),
        "unit": "pixels/sec",
        "vs_baseline": round(dev_matched_px_s / base_px_s, 2),
        # The matched-size ratio above is honest but DOUBLY noisy: both
        # the 16384-px device row (tunnel dispatch latency, drifts 4x at
        # hour scale) and the CPU oracle (host load, 3x between rounds)
        # wander; their spreads are reported.  The ratio of the two
        # STABLE quantities — device throughput at its operating size
        # (+-1% all day) over the oracle's per-pixel rate (size-linear
        # for a block-diagonal solve) — is the comparable cross-round
        # number.
        "vs_baseline_at_scale": round(dev_px_s / base_px_s, 2),
        "oracle_ms_median": round(oracle_ms, 1),
        "oracle_ms_spread": round(oracle_spread_ms, 1),
        # Min-of-k over the WARM reps (first-call SuperLU symbolic
        # factorisation excluded by a warm-up solve): host-load noise
        # only ever ADDS time, so the min is the robust cross-round
        # comparator when the spread is wide (BENCH_r05: 1922 ms spread
        # was first-call cost, not solve variance).
        "oracle_ms_min": round(oracle_min_ms, 1),
        "n_pix_device": n_device,
        "n_pix_matched": n_matched,
        "device_px_s_matched": round(dev_matched_px_s, 1),
        "device_ms_matched_median": round(matched_ms, 3),
        "device_ms_matched_spread": round(matched_spread_ms, 3),
        # The one true perf story at the operating size: XLA vs fused
        # Pallas, same GN loop, same problem (BASELINE.md "Roofline";
        # healthy v5e: ~6.4 vs ~3.8 ms).  Pallas fields are null off-TPU.
        "device_xla_ms": round(xla_ms, 3),
        "device_xla_ms_spread": round(xla_spread_ms, 3),
        "device_pallas_ms": None if pallas_ms is None
        else round(pallas_ms, 3),
        "device_pallas_ms_spread": None if pallas_spread_ms is None
        else round(pallas_spread_ms, 3),
        "device_pallas_px_s": None if pallas_px_s is None
        else round(pallas_px_s, 1),
        # Third-generation row: the WHOLE Gauss-Newton loop (analytic
        # in-kernel linearisation, VMEM-resident carry) as one launch —
        # null off-TPU, and null for problems whose operator does not
        # advertise inkernel_linearize.  Acceptance for the in-kernel
        # path is this row strictly below device_pallas_ms on a
        # healthy-window artifact.
        "device_pallas_fused_lin_ms": None if fl_ms is None
        else round(fl_ms, 3),
        "device_pallas_fused_lin_ms_spread": None if fl_spread_ms is None
        else round(fl_spread_ms, 3),
        "device_pallas_fused_lin_px_s": None if fl_px_s is None
        else round(fl_px_s, 1),
        # Reanalysis solve rows (bench_smoother_rows: the jitted RTS
        # backward sweep over a synthetic in-memory chain).  The _ms row
        # gates in tools/bench_compare.py via the device_*_ms pattern;
        # the px_s twin gates larger-is-better (its own pattern there) —
        # both null when the smoother bench failed.
        "device_smoother_ms": None if smoother is None
        else smoother.get("device_smoother_ms"),
        "device_smoother_px_s": None if smoother is None
        else smoother.get("device_smoother_px_s"),
        "e2e_pixel_steps_per_s": round(e2e_px_steps_s, 1),
        # Max-min over the measured passes (bench_end_to_end medians k
        # passes): the r03-r05 rows swung ~2x with no code change, so
        # the dispersion travels WITH the number — tools/bench_history
        # flags a row unjudgeable instead of trending its noise.
        "e2e_pixel_steps_per_s_spread": None if e2e_spread is None
        else round(e2e_spread, 1),
        "e2e_device_fraction": round(device_frac, 3),
        "e2e_n_pixels": e2e_pix,
        # Serving rows (tools/loadgen.py against the in-process
        # assimilation service — warm-path request latency, BASELINE.md
        # "Serving").  Gated by tools/bench_compare.py like the
        # device_*_ms rows: disappearance or >10% regression fails.
        "serve_p50_ms": None if serve is None
        else serve.get("serve_p50_ms"),
        "serve_p99_ms": None if serve is None
        else serve.get("serve_p99_ms"),
        "serve_cold_ms": None if serve is None
        else serve.get("serve_cold_ms"),
        "serve_rejected_total": None if serve is None
        else serve.get("serve_rejected_total"),
        "serve_requests_total": None if serve is None
        else serve.get("serve_requests_total"),
        # Request-tracing rows (ISSUE 14, tools/loadgen): fraction of
        # served requests whose per-request trace attributes >=95% of
        # their wall time, and the single slowest request — the
        # observability-coverage health of the serving path, diffed
        # informationally by tools/bench_compare.py (no gate yet).
        # Reanalysis serving rows (tools/loadgen's --smoothed mix: every
        # Kth request reads the RTS-smoothed state off the checkpoint
        # chain).  serve_smoothed_p99_ms gates in bench_compare like the
        # forward serving rows.
        "serve_smoothed_p50_ms": None if serve is None
        else serve.get("serve_smoothed_p50_ms"),
        "serve_smoothed_p99_ms": None if serve is None
        else serve.get("serve_smoothed_p99_ms"),
        "serve_trace_coverage": None if serve is None
        else serve.get("serve_trace_coverage"),
        "serve_slowest_ms": None if serve is None
        else serve.get("serve_slowest_ms"),
        # Mid-run /metrics scrape of the serving bench (tools/loadgen's
        # _MetricsScraper against the ephemeral telemetry.httpd
        # endpoint): how queue depth and admission counters MOVED under
        # load, diffed informationally by tools/bench_compare.py.
        "live_telemetry": None if serve is None
        else serve.get("live_telemetry"),
        # SLO rows (tools/loadgen's fast-windowed evaluator over the
        # serving bench, kafka_tpu.telemetry.slo): alert episodes fired
        # during the bench and the worst per-objective error-budget
        # remainder — a bench that got faster by burning its budget
        # must not read as a clean win (bench_compare warns LOUDLY on
        # a 0 -> nonzero alert flip).
        "serve_slo_alerts_total": None if serve is None
        else serve.get("serve_slo_alerts_total"),
        "serve_slo_budget_remaining": None if serve is None
        else serve.get("serve_slo_budget_remaining"),
        # Coalesced-serving rows (tools/loadgen.bench_concurrency_sweep,
        # BASELINE.md "Coalesced serving"): the concurrency ladder with
        # per-level p99/queue_wait/batch-size, the device launch
        # throughput at the top level (serve_batched_px_s GATES in
        # tools/bench_compare.py — disappearance or regression fails)
        # and the unbatched same-run baseline the queue_wait shrink is
        # measured against.
        "serve_sweep": None if sweep is None
        else sweep.get("serve_sweep"),
        "serve_batched_px_s": None if sweep is None
        else sweep.get("serve_batched_px_s"),
        "serve_batch_mean_size": None if sweep is None
        else sweep.get("serve_batch_mean_size"),
        "serve_queue_wait_p99_ms": None if sweep is None
        else sweep.get("serve_queue_wait_p99_ms"),
        "serve_unbatched_p99_ms": None if sweep is None
        else sweep.get("serve_unbatched_p99_ms"),
        "serve_unbatched_queue_wait_p99_ms": None if sweep is None
        else sweep.get("serve_unbatched_queue_wait_p99_ms"),
        # Elastic-fleet serving rows (tools/loadgen.bench_fleet: N
        # in-process replicas behind the consistent-hash router, one
        # client-visible serving surface).  serve_fleet_p50/p99_ms gate
        # in tools/bench_compare.py like the single-daemon rows;
        # rerouted/backoff are context (a policy outcome, not a
        # latency).
        "serve_fleet_p50_ms": None if fleet is None
        else fleet.get("serve_fleet_p50_ms"),
        "serve_fleet_p99_ms": None if fleet is None
        else fleet.get("serve_fleet_p99_ms"),
        "serve_fleet_replicas": None if fleet is None
        else fleet.get("serve_fleet_replicas"),
        "serve_fleet_requests_total": None if fleet is None
        else fleet.get("serve_fleet_requests_total"),
        "serve_fleet_rerouted_total": None if fleet is None
        else fleet.get("serve_fleet_rerouted_total"),
        "serve_backoff_total": None if fleet is None
        else fleet.get("serve_backoff_total"),
        # Bench health layer (see telemetry.health.probe_health): off-band
        # probes flag the whole artifact so cross-round consumers discard
        # it instead of reading environment weather as a perf change.
        **health,
        "probe_host_after_ms": round(host_after_ms, 3),
        "unhealthy": unhealthy,
        # Compact registry snapshot: counters/gauges (+histogram
        # count/sum) from the run — convergence, prefetch, io and the
        # health gauges the probes recorded.
        "telemetry": reg.flat(),
        # Compact SOLVER-health snapshot (BASELINE.md "Numerical
        # resilience"): the kafka_solver_* counters pulled out of the
        # registry so tools/bench_compare.py can diff result QUALITY
        # alongside timing — a benchmark that got faster by silently
        # quarantining pixels must not read as a clean win.  Always
        # present (zeros on a healthy run).
        "solver_health": solver_health_snapshot(reg),
        # Compact ASSIMILATION-quality snapshot (BASELINE.md
        # "Assimilation quality"): filter-consistency verdict counts and
        # drift-sentinel state from the run's quality ledger, so a
        # benchmark whose filter went statistically inconsistent cannot
        # archive as a clean number — tools/bench_compare.py warns
        # LOUDLY when a previously-CONSISTENT benchmark flips verdict.
        "quality": quality_snapshot(reg),
        # Compact PERFORMANCE-attribution snapshot (BASELINE.md
        # "Performance observability"): the live kafka_perf_* gauges at
        # artifact-assembly time — rolling throughput, device fraction,
        # per-phase busy fractions, and the per-component roofline
        # utilization lower bound — so the artifact carries the same
        # attribution a dashboard watched during the run.
        "perf": perf_snapshot(reg),
        # Compact SLO snapshot (BASELINE.md "SLOs & alerting"): alert
        # counts, firing objectives and the per-objective error-budget
        # remainder from the registry-bound engine — always present
        # (the stable disabled shape when no evaluator ran), diffed
        # informationally by tools/bench_compare.py.
        "slo": slo_snapshot(reg),
        # Compact DEVICE-plane snapshot (BASELINE.md "Device-plane
        # observability"): top kernels / collective fraction from the
        # newest parsed profiler capture plus the HBM peak watermark —
        # so the artifact records WHERE device time went, not just how
        # much; tools/bench_compare.py warns LOUDLY when the
        # collective fraction grows.
        "device_profile": devprof_snapshot(reg),
        # Compact PROGRAM-CONTRACT snapshot (BASELINE.md "Program
        # contracts"): per-program trace fingerprints plus the contract
        # finding count from tools/programlint's analyzer — so the
        # artifact records WHICH device programs it measured;
        # tools/bench_compare.py warns LOUDLY when a fingerprint drifts
        # between compared runs (the numbers describe different
        # programs).
        "program_contracts": program_contracts_snapshot(),
    }


def program_contracts_snapshot() -> dict:
    """Trace-level contract snapshot (``kafka_tpu.analysis``): cached
    after the first artifact of the run — the registered programs don't
    change mid-process — and never raises (analysis failure becomes an
    ``error`` field, not a dead benchmark)."""
    from kafka_tpu.analysis import contracts_snapshot

    return contracts_snapshot()


def devprof_snapshot(registry=None) -> dict:
    """The run's device-plane state (``telemetry.devprof``): capture
    count, the top-kernel table (bounded), bucket totals, collective
    fraction, and the per-device HBM peak from the watermark gauges —
    always present (zeros/None before any capture or watermark)."""
    from kafka_tpu.telemetry import devprof as _devprof

    reg = registry if registry is not None else get_registry()
    ks = _devprof.kernel_summary(reg, n=8)
    hbm_peak = {}
    for key, val in reg.flat().items():
        if key.startswith("kafka_device_memory_peak_bytes"):
            hbm_peak[key] = val
    return {
        "captures_parsed": ks["captures_parsed"],
        "device_ms": ks["device_ms"],
        "collective_fraction": ks["collective_fraction"],
        "kernels": [
            {"name": k["name"], "bucket": k["bucket"], "ms": k["ms"],
             "fraction": k["fraction"]}
            for k in ks["kernels"]
        ],
        "hbm_peak_bytes": hbm_peak,
        "live_buffer_bytes": _devprof.summary(reg)["live_buffer_bytes"],
    }


def perf_snapshot(registry=None) -> dict:
    """The run's performance-attribution state (``telemetry.perf``):
    rolling throughput/device-fraction gauges, phase busy fractions and
    roofline-utilization components — always present, gauges None when
    the run assimilated no windows."""
    from kafka_tpu.telemetry import perf as _perf

    return _perf.summary(
        registry if registry is not None else get_registry()
    )


def slo_snapshot(registry=None) -> dict:
    """The run's SLO state as a compact dict: alert counts, firing
    objectives and the per-objective budget remainder — the stable
    disabled shape when no evaluator ran on this registry."""
    from kafka_tpu.telemetry import slo as _slo

    reg = registry if registry is not None else get_registry()
    summary = _slo.summary(reg)
    objectives = {
        name: {
            "status": o.get("status"),
            "budget_remaining": (o.get("budget") or {}).get(
                "remaining"
            ),
        }
        for name, o in (summary.get("objectives") or {}).items()
    }
    return {
        "enabled": bool(summary.get("enabled")),
        "alerts_fired": int(summary.get("alerts_fired") or 0),
        "alerts_resolved": int(summary.get("alerts_resolved") or 0),
        "firing": sorted(
            f"{a.get('objective')}:{a.get('severity')}"
            for a in summary.get("firing") or ()
        ),
        "objectives": objectives,
    }


def quality_snapshot(registry=None) -> dict:
    """The run's assimilation-quality state as a compact dict: window
    counts per consistency verdict (``kafka_quality_windows_total``),
    drift-sentinel totals, and the run's overall (worst) verdict — None
    when the run recorded no quality windows."""
    from kafka_tpu.telemetry import quality as _quality

    reg = registry if registry is not None else get_registry()
    windows = {}
    for v in _quality.VERDICTS:
        n = reg.value("kafka_quality_windows_total", verdict=v)
        windows[v] = 0 if n is None else int(n)
    events_total = 0.0
    for key, val in reg.flat().items():
        if key.startswith("kafka_quality_drift_events_total"):
            events_total += float(val)
    return {
        "verdict": _quality.worst_verdict(
            v for v, n in windows.items() if n
        ),
        "windows": windows,
        "drift_events": int(events_total),
        "drift_active": int(reg.value("kafka_quality_drift_active")
                            or 0),
    }


def solver_health_snapshot(registry=None) -> dict:
    """The run's ``kafka_solver_*`` counter totals as a compact dict
    (labelled series summed — e.g. clip_saturated over parameters)."""
    reg = registry if registry is not None else get_registry()
    out = {
        "quarantined_pixels": 0.0,
        "cap_bailouts": 0.0,
        "damped_recoveries": 0.0,
        "nonfinite": 0.0,
        "clip_saturated": 0.0,
    }
    for key, val in reg.flat().items():
        if not key.startswith("kafka_solver_"):
            continue
        short = key[len("kafka_solver_"):].split("{", 1)[0]
        if short.endswith("_total"):
            short = short[: -len("_total")]
        out[short] = out.get(short, 0.0) + float(val)
    return out


def main():
    import jax

    from kafka_tpu.telemetry import (
        flight_recorder, install_compile_listeners, tracing,
    )
    from kafka_tpu.utils.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    install_compile_listeners()
    # Crash forensics next to the BENCH artifact: a bench killed mid-run
    # (or flagged unhealthy by the probes) leaves crash_<ts>.json in the
    # working directory instead of nothing.
    recorder = flight_recorder.install(".")
    with tracing.push(run_id=tracing.new_run_id()), recorder:
        _bench_rows()


def _bench_rows():
    import jax

    # Health first: an off-band tunnel/host window contaminates every row
    # below; probe (with one retry) BEFORE spending minutes measuring.
    health = probe_health()
    # Baseline on the reference's chunk size (16384 px = one 128x128
    # chunk).  vs_baseline compares both backends at that SAME size so it
    # measures the backend, not batch scaling; the headline value is the
    # device's full-tile-scale throughput (its realistic operating point),
    # with both sizes reported.
    n_matched = 16384
    n_device = 1 << 19
    oracle = bench_oracle(n_matched)
    # The matched size measures in two bursts bracketing the large-size
    # run: the tunnel's per-dispatch overhead drifts at minute scale, and
    # the pooled median (+ reported spread) bounds that drift's effect
    # on the headline speedup.
    dev = bench_device_sizes([n_matched, n_device, n_matched])
    # The fused-Pallas rows, first-class next to the XLA one.  Real-chip
    # only: the CPU interpreter times the Pallas INTERPRETER, not the
    # kernel, and archiving that as a perf row would be fiction.  Two
    # kernel generations measured separately: device_pallas_ms pins
    # inkernel_linearize=False (the PR 1 whole-update kernel, Jacobian
    # relayout + while_loop carry still crossing HBM) so the new
    # device_pallas_fused_lin_ms row (whole GN loop in-kernel) is an
    # apples-to-apples delta against it.
    pallas = fused_lin = None
    if jax.default_backend() == "tpu":
        dev_pl = bench_device_sizes(
            [n_device], use_pallas=True, inkernel_linearize=False
        )
        pallas = dev_pl[n_device]
        dev_fl = bench_device_sizes(
            [n_device], use_pallas=True, inkernel_linearize=True
        )
        fused_lin = dev_fl[n_device]
    else:
        print(
            "device[pallas]: skipped — no TPU (interpret-mode timings "
            "measure the interpreter, not the kernel)",
            file=sys.stderr,
        )
    e2e = bench_end_to_end()
    smoother = bench_smoother_rows()
    serve = bench_serve_rows()
    fleet = bench_fleet_rows()
    sweep = bench_sweep_rows()
    host_after_ms = probe_host()
    print(json.dumps(assemble_result(
        health,
        oracle=oracle,
        device_matched=dev[n_matched],
        device=dev[n_device],
        pallas=pallas,
        fused_lin=fused_lin,
        e2e=e2e,
        serve=serve,
        fleet=fleet,
        sweep=sweep,
        smoother=smoother,
        host_after_ms=host_after_ms,
        n_matched=n_matched,
        n_device=n_device,
    )))


def bench_smoother_rows(n_pix: int = 16384, windows: int = 8,
                        n_params: int = 2, reps: int = 5):
    """Time the jitted RTS backward sweep (``kafka_tpu.smoother``) over
    a synthetic in-memory chain — ``windows`` checkpoint nodes of
    ``n_pix`` pixels, every node carrying a forecast sidecar so the
    measurement is the pure sweep (no propagator re-derivation, no IO).
    ``device_smoother_px_s`` counts pixel-windows per second.  Failure
    degrades to null rows with a loud stderr note rather than killing
    the solve rows."""
    import datetime

    try:
        from kafka_tpu.smoother import ChainNode, smooth_chain

        rng = np.random.default_rng(0)
        idx = np.arange(n_params)
        base = datetime.datetime(2017, 7, 1)
        nodes = []
        for t in range(windows):
            x = rng.standard_normal(
                (n_pix, n_params)).astype(np.float32)
            p_inv = np.zeros((n_pix, n_params, n_params), np.float32)
            p_inv[:, idx, idx] = \
                (1.0 + rng.random((n_pix, n_params))).astype(np.float32)
            sidecar = None
            if t > 0:
                xf = rng.standard_normal(
                    (n_pix, n_params)).astype(np.float32)
                pf_inv = np.zeros(
                    (n_pix, n_params, n_params), np.float32)
                pf_inv[:, idx, idx] = (
                    0.5 + rng.random((n_pix, n_params))
                ).astype(np.float32)
                sidecar = (xf, pf_inv)
            nodes.append(ChainNode(
                base + datetime.timedelta(days=4 * t), x, p_inv,
                sidecar,
            ))
        smooth_chain(nodes)  # warm-up: pay the compile outside the reps
        times = []
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            smooth_chain(nodes)
            times.append(time.perf_counter() - t0)
        med = float(np.median(times))
        rows = {
            "device_smoother_ms": round(med * 1e3, 3),
            "device_smoother_px_s": round(n_pix * windows / med, 1),
        }
        print(
            f"smoother: {rows['device_smoother_ms']} ms / "
            f"{windows}x{n_pix} px chain "
            f"({rows['device_smoother_px_s']} px-windows/s)",
            file=sys.stderr,
        )
        return rows
    except Exception as exc:  # degrade to null rows: the smoother bench must never cost the solve rows
        print(f"smoother bench failed ({exc!r}) — smoother rows null",
              file=sys.stderr)
        return None


def bench_serve_rows(requests: int = 24, concurrency: int = 4):
    """The serving latency rows via tools/loadgen's self-contained
    in-process harness (host-side orchestration — meaningful on CPU and
    TPU alike).  Failure degrades to null rows with a loud stderr note
    rather than killing the solve rows."""
    import shutil
    import tempfile

    from tools.loadgen import bench_serve

    tmp = tempfile.mkdtemp(prefix="kafka_bench_serve_")
    try:
        rows = bench_serve(tmp, requests=requests,
                           concurrency=concurrency)
        print(
            f"serve: p50 {rows['serve_p50_ms']} ms, "
            f"p99 {rows['serve_p99_ms']} ms over "
            f"{rows['serve_ok_total']} ok / "
            f"{rows['serve_requests_total']} requests "
            f"(cold {rows['serve_cold_ms']} ms)",
            file=sys.stderr,
        )
        return rows
    except Exception as exc:  # degrade to null rows: the serving bench must never cost the solve rows
        print(f"serve bench failed ({exc!r}) — serving rows null",
              file=sys.stderr)
        return None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_sweep_rows(concurrencies=(1, 8, 32)):
    """The coalesced-serving concurrency-sweep rows via
    tools/loadgen's self-contained in-process harness (host-side
    orchestration — meaningful on CPU and TPU alike).  Failure degrades
    to null rows with a loud stderr note rather than killing the solve
    rows."""
    import shutil
    import tempfile

    from tools.loadgen import bench_concurrency_sweep

    tmp = tempfile.mkdtemp(prefix="kafka_bench_sweep_")
    try:
        rows = bench_concurrency_sweep(tmp, concurrencies=concurrencies)
        print(
            f"serve sweep: batched px/s {rows['serve_batched_px_s']}, "
            f"mean batch {rows['serve_batch_mean_size']} @ "
            f"c={rows['serve_sweep_concurrencies'][-1]}, queue_wait "
            f"p99 {rows['serve_queue_wait_p99_ms']} ms batched vs "
            f"{rows['serve_unbatched_queue_wait_p99_ms']} ms unbatched",
            file=sys.stderr,
        )
        return rows
    except Exception as exc:  # degrade to null rows like the other serving benches
        print(f"serve sweep failed ({exc!r}) — sweep rows null",
              file=sys.stderr)
        return None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_fleet_rows(replicas: int = 3, requests: int = 24,
                     concurrency: int = 4):
    """The elastic-fleet serving rows via tools/loadgen's in-process
    N-replica + consistent-hash-router harness — the serve_fleet_*
    BENCH rows bench_compare gates.  Failure degrades to null rows with
    a loud stderr note rather than killing the solve rows."""
    import shutil
    import tempfile

    from tools.loadgen import bench_fleet

    tmp = tempfile.mkdtemp(prefix="kafka_bench_fleet_")
    try:
        rows = bench_fleet(tmp, replicas=replicas, requests=requests,
                           concurrency=concurrency)
        print(
            f"fleet: p50 {rows['serve_fleet_p50_ms']} ms, "
            f"p99 {rows['serve_fleet_p99_ms']} ms over "
            f"{rows['serve_fleet_ok_total']} ok / "
            f"{rows['serve_fleet_requests_total']} requests across "
            f"{rows['serve_fleet_replicas']} replicas "
            f"(rerouted {rows['serve_fleet_rerouted_total']})",
            file=sys.stderr,
        )
        return rows
    except Exception as exc:  # degrade to null rows: the fleet bench must never cost the solve rows
        print(f"fleet bench failed ({exc!r}) — fleet rows null",
              file=sys.stderr)
        return None
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
