"""Assimilation-quality observability (ISSUE 11): the innovation-
consistency ledger, verdicts, drift sentinels, the obs.bias chaos
site, the quality_report scorecard, and the outward wiring (serve
responses, admission shedding, statusz/live/fleet views, fleet_status
--watch).

The chaos acceptance test pins the contract end to end: a run with
``obs.bias`` armed on k trailing dates is flagged by the drift
sentinel on exactly those dates (verdict flips + ``quality_drift``
events), while unbiased dates' outputs stay BIT-IDENTICAL to a
fault-free run — and the ledger costs zero additional device->host
transfers (``kafka_engine_device_reads_total == dispatches``
re-asserted with the ledger active).
"""

import datetime
import json
import math
import os
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry, quality
from kafka_tpu.resilience import faults


def day(i):
    return datetime.datetime(2017, 7, 1) + datetime.timedelta(days=i)


def run_identity_engine(telemetry_dir=None, scan_window=1,
                        prefetch_depth=2):
    """A small identity-operator engine run whose clean chi^2 ratios
    idle near 1 (the textbook-consistent configuration): 8 observation
    dates, grid of 5 windows.  Returns ``(kf, out, reg)``."""
    import jax.numpy as jnp

    from kafka_tpu.core.propagators import (
        PixelPrior, propagate_information_filter_approx,
    )
    from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
    from kafka_tpu.obsops.identity import IdentityOperator
    from kafka_tpu.testing.fixtures import make_pivot_mask
    from kafka_tpu.testing.synthetic import (
        MemoryOutput, SyntheticObservations,
    )

    mask = make_pivot_mask(20, 20, seed=0)
    p = 2
    op = IdentityOperator(n_params=p, obs_indices=(0, 1))
    cov = np.diag(np.full(p, 0.4 ** 2)).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.full((p,), 0.5, jnp.float32),
            cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        ("a", "b"),
    )
    truth = np.broadcast_to(
        np.array([0.3, 0.7], np.float32), mask.shape + (2,)
    ).astype(np.float32)
    with telemetry.use(MetricsRegistry(telemetry_dir)) as reg:
        obs = SyntheticObservations(
            dates=[day(i) for i in range(1, 16, 2)], operator=op,
            truth_fn=lambda d: truth, sigma=0.02, mask_prob=0.1, seed=0,
        )
        out = MemoryOutput()
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter_approx,
            prior=None, solver_options={"relaxation": 0.5},
            scan_window=scan_window, prefetch_depth=prefetch_depth,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.full(p, 1e-3, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        kf.run([day(i) for i in range(0, 20, 4)], x0, None, p_inv0)
    return kf, out, reg


# ---------------------------------------------------------------------------
# Verdicts.
# ---------------------------------------------------------------------------

class TestVerdicts:
    def test_bands(self):
        assert quality.verdict_for([0.9, 1.1]) == quality.CONSISTENT
        assert quality.verdict_for([0.9, 40.0]) == quality.OVERCONFIDENT
        assert quality.verdict_for([0.004, 1.0]) == \
            quality.UNDERCONFIDENT
        # Over wins over under: an exploded band is the louder signal.
        assert quality.verdict_for([0.001, 99.0]) == \
            quality.OVERCONFIDENT

    def test_no_signal_bands_are_skipped(self):
        # 0 = fully-masked band (no observations), NaN = no signal.
        assert quality.verdict_for([]) == quality.NO_OBS
        assert quality.verdict_for([0.0, 0.0]) == quality.NO_OBS
        assert quality.verdict_for([float("nan"), 1.0]) == \
            quality.CONSISTENT
        assert quality.verdict_for([0.0, 30.0]) == quality.OVERCONFIDENT

    def test_custom_bands(self):
        assert quality.verdict_for([1.8], hi=1.5) == \
            quality.OVERCONFIDENT
        assert quality.verdict_for([0.3], lo=0.5) == \
            quality.UNDERCONFIDENT

    def test_worst_verdict_severity(self):
        q = quality
        assert q.worst_verdict([]) is None
        assert q.worst_verdict([q.CONSISTENT, q.NO_OBS]) == q.NO_OBS
        assert q.worst_verdict(
            [q.CONSISTENT, q.UNDERCONFIDENT, q.NO_OBS]
        ) == q.UNDERCONFIDENT
        assert q.worst_verdict(
            [q.OVERCONFIDENT, q.UNDERCONFIDENT]
        ) == q.OVERCONFIDENT


# ---------------------------------------------------------------------------
# Drift sentinels.
# ---------------------------------------------------------------------------

class TestDriftSentinel:
    def test_calibration_never_alarms(self):
        s = quality.DriftSentinel(window=3)
        for x in (1.0, 80.0, 0.01):
            st = s.update(x)
            assert st["phase"] == "calibrating"
            assert not st["drifting"]

    def test_step_change_alarms_and_sustains_then_heals(self):
        s = quality.DriftSentinel(window=4)
        for _ in range(6):
            st = s.update(1.0)
            assert not st["drifting"]
        st = s.update(50.0)  # log-dev ~3.9 >> h_high
        assert st["drifting"] and st["trigger"] == "cusum_high"
        # NO reset-after-alarm: a sustained fault stays flagged on
        # every affected date even as its magnitude decays...
        st = s.update(20.0)
        assert st["drifting"] and st["trigger"] == "cusum_high"
        # ...and the first clean date flushes the episode (the alarm
        # samples never entered the baseline window).
        st = s.update(1.0)
        assert not st["drifting"]
        assert s.cusum_pos == 0.0

    def test_downward_shift_alarms_low_side(self):
        s = quality.DriftSentinel(window=4)
        for _ in range(4):
            s.update(1.0)
        s.update(0.05)  # accumulates but below h_low
        st = s.update(0.05)
        assert st["drifting"] and st["trigger"] == "cusum_low"

    def test_self_baselining_accepts_low_operating_level(self):
        """A tight-prior configuration idling near 0.05 (the TIP
        problem) is ITS OWN baseline — no alarms on a stationary
        series, which an absolute target-1 CUSUM would false-flag."""
        s = quality.DriftSentinel()
        for x in (0.051, 0.042, 0.041, 0.054) * 6:
            st = s.update(x)
            assert not st["drifting"], st

    def test_spin_up_decay_is_absorbed_not_flagged(self):
        """The filter's spin-up transient — posterior chi^2 starting
        high and decaying to its settled level (observed on the
        run_synthetic identity driver: 6.4, 4.6, 1.4, 0.8 then ~0.5) —
        must NOT read as drift: the rolling baseline window follows
        the decay instead of freezing over the transient head."""
        s = quality.DriftSentinel()
        series = [6.38, 4.63, 1.39, 0.80, 0.595, 0.52, 0.524, 0.52,
                  0.55, 0.50, 0.53]
        for x in series:
            st = s.update(x)
            assert not st["drifting"], (x, st)

    def test_ewma_flags_sustained_moderate_shift(self):
        s = quality.DriftSentinel(window=6, k=10.0, h_high=1e9,
                                  h_low=1e9)
        # CUSUM disabled by its slack/threshold: only the EWMA watches.
        for _ in range(6):
            s.update(1.0)
        triggers = [s.update(100.0)["trigger"] for _ in range(8)]
        assert "ewma" in triggers


# ---------------------------------------------------------------------------
# The ledger.
# ---------------------------------------------------------------------------

class TestLedger:
    def test_records_metrics_and_jsonl(self, tmp_path):
        d = str(tmp_path)
        with telemetry.use(MetricsRegistry(d)) as reg:
            led = quality.get_ledger(reg)
            assert led is quality.get_ledger(reg)  # one per registry
            r1 = led.record_window(day(1), [0.9, 1.2], n_valid=64)
            r2 = led.record_window(
                day(2), [30.0, 1.0], n_valid=64,
                solver_health={"quarantined": 3}, prefix="0001",
            )
            r3 = led.record_missing(day(3), prefix="0001")
            assert r1["verdict"] == quality.CONSISTENT
            assert r2["verdict"] == quality.OVERCONFIDENT
            assert r2["solver_health"] == {"quarantined": 3}
            assert r3["verdict"] == quality.NO_OBS and r3["degraded"]
            assert reg.value(
                "kafka_quality_windows_total",
                verdict=quality.CONSISTENT,
            ) == 1
            assert reg.value(
                "kafka_quality_windows_total", verdict=quality.NO_OBS,
            ) == 1
        records, skipped = quality.load_ledger(
            os.path.join(d, quality.LEDGER_FILENAME)
        )
        assert skipped == 0
        assert [r["verdict"] for r in records] == [
            quality.CONSISTENT, quality.OVERCONFIDENT, quality.NO_OBS,
        ]
        assert records[1]["prefix"] == "0001"
        assert records[0]["schema"] == quality.LEDGER_SCHEMA

    def test_in_memory_without_directory(self):
        with telemetry.use(MetricsRegistry()) as reg:
            led = quality.get_ledger(reg)
            led.record_window(day(1), [1.0], n_valid=4)
            assert led.path is None
            assert led.summary()["records"] == 1

    def test_sentinel_streams_keyed_by_prefix_and_band(self):
        """Two chunks' (or tiles') series must not pollute each other:
        a chunk idling at 0.05 next to one idling at 1.0 is two
        healthy baselines, not a drift."""
        with telemetry.use(MetricsRegistry()) as reg:
            led = quality.get_ledger(reg)
            for _ in range(10):
                ra = led.record_window(day(1), [0.05], n_valid=4,
                                       prefix="a")
                rb = led.record_window(day(1), [1.0], n_valid=4,
                                       prefix="b")
                assert not ra["drift"]["active"]
                assert not rb["drift"]["active"]
            # A jump on stream b alarms b alone.
            rb = led.record_window(day(2), [60.0], n_valid=4,
                                   prefix="b")
            assert rb["drift"]["active"]
            assert reg.value("kafka_quality_drift_active") == 1
            assert led.summary()["drifting"] == ["b:band0"]

    def test_drift_gauge_clears_when_series_heals(self):
        with telemetry.use(MetricsRegistry()) as reg:
            led = quality.get_ledger(reg)
            for _ in range(6):
                led.record_window(day(1), [1.0], n_valid=4)
            led.record_window(day(2), [70.0], n_valid=4)
            assert reg.value("kafka_quality_drift_active") == 1
            for _ in range(6):
                led.record_window(day(3), [1.0], n_valid=4)
            assert reg.value("kafka_quality_drift_active") == 0

    def test_torn_tail_skipped(self, tmp_path):
        path = tmp_path / quality.LEDGER_FILENAME
        with telemetry.use(MetricsRegistry(str(tmp_path))) as reg:
            led = quality.get_ledger(reg)
            led.record_window(day(1), [1.0], n_valid=4)
            led.record_window(day(2), [1.1], n_valid=4)
        # A process killed mid-append leaves a torn final line.
        with open(path, "a") as f:
            f.write('{"schema": 1, "date": "2017-07-0')
        records, skipped = quality.load_ledger(str(path))
        assert len(records) == 2
        assert skipped == 1

    def test_non_record_lines_skipped(self, tmp_path):
        path = tmp_path / quality.LEDGER_FILENAME
        path.write_text('42\n{"no_verdict": true}\n'
                        '{"verdict": "CONSISTENT", "date": "d"}\n')
        records, skipped = quality.load_ledger(str(path))
        assert len(records) == 1 and skipped == 2


# ---------------------------------------------------------------------------
# Engine integration: the ledger rides the existing packed read.
# ---------------------------------------------------------------------------

class TestEngineQuality:
    def test_ledger_written_with_zero_added_device_reads(self, tmp_path):
        """THE invariant, re-asserted with the quality ledger active:
        one packed device->host read per solve dispatch, ledger or no
        ledger — the quality record is built from scalars the engine
        already fetched."""
        for scan_window in (1, 4):
            d = str(tmp_path / f"sw{scan_window}")
            kf, out, reg = run_identity_engine(
                telemetry_dir=d, scan_window=scan_window,
            )
            dispatches = sum(
                1.0 / rec.get("fused", 1) for rec in kf.diagnostics_log
            )
            assert reg.value("kafka_engine_device_reads_total") == \
                int(dispatches)
            records, skipped = quality.load_ledger(
                os.path.join(d, quality.LEDGER_FILENAME)
            )
            assert skipped == 0
            assert len(records) == len(kf.diagnostics_log)
            for rec, led in zip(kf.diagnostics_log, records):
                assert rec["quality_verdict"] == led["verdict"]
                assert led["chi2_per_band"] == pytest.approx(
                    rec["chi2_per_band"], abs=1e-6,
                )
                assert led["n_valid"] == kf.gather.n_valid
            # The clean identity configuration is textbook-consistent.
            assert all(
                r["verdict"] == quality.CONSISTENT for r in records
            )
            assert all(not r["drift"]["active"] for r in records)

    def test_degraded_date_lands_as_missing_record(self, tmp_path):
        from kafka_tpu.resilience import RetryPolicy

        d = str(tmp_path)
        faults.reset()
        try:
            faults.script("prefetch.read_date", "3", faults.TRANSIENT)
            import jax.numpy as jnp

            from kafka_tpu.core.propagators import (
                PixelPrior, propagate_information_filter_approx,
            )
            from kafka_tpu.engine import (
                FixedGaussianPrior, KalmanFilter,
            )
            from kafka_tpu.obsops.identity import IdentityOperator
            from kafka_tpu.testing.fixtures import make_pivot_mask
            from kafka_tpu.testing.synthetic import (
                MemoryOutput, SyntheticObservations,
            )

            mask = make_pivot_mask(12, 12, seed=0)
            op = IdentityOperator(n_params=2, obs_indices=(0, 1))
            cov = np.diag(np.full(2, 0.16)).astype(np.float32)
            prior = FixedGaussianPrior(
                PixelPrior(
                    mean=jnp.full((2,), 0.5, jnp.float32),
                    cov=jnp.asarray(cov),
                    inv_cov=jnp.asarray(np.linalg.inv(cov)),
                ),
                ("a", "b"),
            )
            truth = np.broadcast_to(
                np.array([0.3, 0.7], np.float32), mask.shape + (2,)
            ).astype(np.float32)
            with telemetry.use(MetricsRegistry(d)) as reg:
                obs = SyntheticObservations(
                    dates=[day(i) for i in (1, 3, 5)], operator=op,
                    truth_fn=lambda dd: truth, sigma=0.02, seed=0,
                )
                kf = KalmanFilter(
                    obs, MemoryOutput(), mask, ("a", "b"),
                    state_propagation=(
                        propagate_information_filter_approx
                    ),
                    prior=None, prefetch_depth=0,
                    read_retry_policy=RetryPolicy(
                        max_attempts=1, base_delay=0.0,
                    ),
                )
                kf.set_trajectory_model()
                kf.set_trajectory_uncertainty(
                    np.full(2, 1e-3, np.float32)
                )
                x0, p_inv0 = prior.process_prior(None, kf.gather)
                kf.run([day(0), day(2), day(4), day(6)], x0, None,
                       p_inv0)
        finally:
            faults.reset()
        records, _ = quality.load_ledger(
            os.path.join(d, quality.LEDGER_FILENAME)
        )
        degraded = [r for r in records if r["degraded"]]
        assert len(degraded) == 1
        assert degraded[0]["verdict"] == quality.NO_OBS
        assert reg.value(
            "kafka_quality_windows_total", verdict=quality.NO_OBS,
        ) == 1


# ---------------------------------------------------------------------------
# The obs.bias chaos acceptance.
# ---------------------------------------------------------------------------

class TestObsBiasChaos:
    def test_bias_grammar_parses_from_env_spec(self):
        specs = faults.parse_spec("obs.bias@7-8")
        assert specs[0].site == "obs.bias"
        assert specs[0].first == 7 and specs[0].last == 8

    def test_disarmed_bias_is_none(self):
        faults.reset()
        assert quality.observation_bias(1) is None
        faults.script("solver.pixel", "1-2")  # some OTHER site armed
        try:
            assert quality.observation_bias(1) is None
        finally:
            faults.reset()

    def test_armed_dates_flagged_clean_dates_bit_identical(
            self, tmp_path):
        """THE acceptance: obs.bias armed on the two trailing
        observation dates (fetch numbers 7-8 of 8).  The drift sentinel
        flags exactly those dates' ledger records (verdict flips to
        OVERCONFIDENT + quality_drift events), every clean date stays
        CONSISTENT with no drift, and every output timestep before the
        armed dates is BIT-IDENTICAL to the fault-free run."""
        faults.reset()
        clean_dir = str(tmp_path / "clean")
        bias_dir = str(tmp_path / "bias")
        kf_c, out_c, reg_c = run_identity_engine(
            telemetry_dir=clean_dir
        )
        faults.script("obs.bias", "7-8")
        try:
            kf_b, out_b, reg_b = run_identity_engine(
                telemetry_dir=bias_dir
            )
        finally:
            faults.reset()
        recs_c, _ = quality.load_ledger(
            os.path.join(clean_dir, quality.LEDGER_FILENAME)
        )
        recs_b, _ = quality.load_ledger(
            os.path.join(bias_dir, quality.LEDGER_FILENAME)
        )
        assert len(recs_b) == len(recs_c) == 8
        armed_dates = {str(day(13)), str(day(15))}  # fetch #7 and #8
        for rc, rb in zip(recs_c, recs_b):
            assert rb["date"] == rc["date"]
            if rb["date"] in armed_dates:
                assert rb["verdict"] == quality.OVERCONFIDENT
                assert rb["drift"]["active"], rb
            else:
                assert rb["verdict"] == quality.CONSISTENT
                assert not rb["drift"]["active"]
                # Unbiased windows: identical scalars too.
                assert rb["chi2_per_band"] == rc["chi2_per_band"]
        # quality_drift events fired on exactly the armed dates.
        ev_dates = {
            e["date"] for e in reg_b.events
            if e["event"] == "quality_drift"
        }
        assert ev_dates == armed_dates
        assert not any(
            e["event"] == "quality_drift" for e in reg_c.events
        )
        assert reg_b.value("kafka_quality_drift_active") >= 1
        assert reg_b.value(
            "kafka_resilience_faults_injected_total", site="obs.bias",
        ) == 2
        # Clean-date outputs bit-identical: the bias only enters armed
        # dates' y, and those land in the LAST grid window.
        timesteps = sorted(out_c.output)
        assert len(timesteps) == 4  # 5 grid points -> 4 dumped windows
        biased_windows = {timesteps[-1]}
        for ts in timesteps:
            for key, arr in out_c.output[ts].items():
                same = np.array_equal(
                    arr, out_b.output[ts][key], equal_nan=True,
                )
                if ts in biased_windows:
                    continue  # the armed window legitimately differs
                assert same, f"{ts} {key} differs on an unbiased window"
        # ... and the armed window's state DID move (the bias is real).
        last = timesteps[-1]
        assert not np.array_equal(
            out_c.output[last]["a"], out_b.output[last]["a"],
            equal_nan=True,
        )

    def test_device_reads_invariant_under_chaos(self, tmp_path):
        """Arming obs.bias adds zero device reads: the bias rides the
        traced y data, the ledger rides the packed read."""
        faults.reset()
        faults.script("obs.bias", "7-8")
        try:
            kf, out, reg = run_identity_engine(
                telemetry_dir=str(tmp_path)
            )
        finally:
            faults.reset()
        assert reg.value("kafka_engine_device_reads_total") == \
            len(kf.diagnostics_log)


class TestRunSyntheticLedger:
    def test_driver_writes_quality_ledger_under_env_chaos(
            self, tmp_path, monkeypatch):
        """Acceptance plumbing: the run_synthetic driver (telemetry-dir
        configured, KAFKA_TPU_FAULTS env spec) writes quality.jsonl,
        and the env-armed obs.bias dates come back flagged."""
        from kafka_tpu.cli.run_synthetic import main
        from kafka_tpu.telemetry import get_registry, set_registry

        tel = str(tmp_path / "tel")
        monkeypatch.setenv("KAFKA_TPU_FAULTS", "obs.bias@7-8")
        prev = get_registry()
        faults.reset()
        try:
            summary = main([
                "--operator", "identity", "--ny", "40", "--nx", "40",
                "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
            ])
        finally:
            faults.reset()
            set_registry(prev)
        assert summary["n_pixels"] > 0
        records, skipped = quality.load_ledger(
            os.path.join(tel, quality.LEDGER_FILENAME)
        )
        assert skipped == 0
        assert len(records) == summary["n_dates"] == 8
        flagged = [r for r in records if r["drift"]["active"]]
        assert [r["date"] for r in flagged] == \
            [str(day(13)), str(day(15))]
        assert all(
            r["verdict"] == quality.OVERCONFIDENT for r in flagged
        )


# ---------------------------------------------------------------------------
# quality_report: the scorecard CLI.
# ---------------------------------------------------------------------------

class TestQualityReport:
    def _ledger_dir(self, tmp_path, name="run"):
        d = tmp_path / name
        d.mkdir()
        with telemetry.use(MetricsRegistry(str(d))) as reg:
            led = quality.get_ledger(reg)
            for i in range(6):
                led.record_window(day(i), [1.0, 0.9], n_valid=10)
            led.record_window(day(6), [55.0, 1.0], n_valid=10)
            led.record_missing(day(7))
        return d

    def test_json_reproduces_verdicts_from_ledger_alone(
            self, tmp_path, capsys):
        from tools import quality_report

        d = self._ledger_dir(tmp_path)
        rc = quality_report.main([str(d), "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["version"] == 1
        assert payload["bands"] == {
            "lo": quality.CONSISTENT_LO, "hi": quality.CONSISTENT_HI,
        }
        (tile,) = payload["tiles"].values()
        assert len(tile["dates"]) == 8
        for entry in tile["dates"]:
            # Acceptance: per-date verdicts reproduce from the ledger
            # alone (recomputed from the stored ratios with the same
            # bands).
            assert entry["recomputed"] == entry["verdict"]
        assert tile["overall"] == quality.OVERCONFIDENT
        assert tile["verdicts"][quality.CONSISTENT] == 6
        assert tile["verdicts"][quality.NO_OBS] == 1
        assert tile["drift_dates"] == 1
        assert len(tile["episodes"]) == 1
        assert tile["episodes"][0]["start"] == str(day(6))
        assert tile["worst"][0]["date"] == str(day(6))

    def test_torn_tail_counted_not_fatal(self, tmp_path, capsys):
        from tools import quality_report

        d = self._ledger_dir(tmp_path)
        with open(d / quality.LEDGER_FILENAME, "a") as f:
            f.write('{"torn": ')
        rc = quality_report.main([str(d), "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["sources"][0]["skipped_lines"] == 1
        assert payload["sources"][0]["records"] == 8

    def test_multiple_ledgers_and_prefix_grouping(self, tmp_path,
                                                  capsys):
        from tools import quality_report

        d = tmp_path / "multi"
        d.mkdir()
        with telemetry.use(MetricsRegistry(str(d))) as reg:
            led = quality.get_ledger(reg)
            led.record_window(day(0), [1.0], n_valid=4,
                              prefix="tile:alpha")
            led.record_window(day(0), [1.1], n_valid=4,
                              prefix="tile:beta")
        rc = quality_report.main([str(tmp_path), "--json"])
        assert rc == 0
        payload = json.loads(capsys.readouterr().out)
        assert set(payload["tiles"]) >= {"tile:alpha", "tile:beta"}

    def test_human_render_smoke(self, tmp_path, capsys):
        from tools import quality_report

        d = self._ledger_dir(tmp_path)
        assert quality_report.main([str(d)]) == 0
        out = capsys.readouterr().out
        assert "quality report" in out
        assert "drift episode" in out
        assert "O!" in out  # the drifting OVERCONFIDENT date's glyph

    def test_no_ledger_is_usage_error(self, tmp_path, capsys):
        from tools import quality_report

        empty = tmp_path / "empty"
        empty.mkdir()
        assert quality_report.main([str(empty)]) == 2
        assert "no quality.jsonl" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Serve wiring: responses, ledger, admission.
# ---------------------------------------------------------------------------

class TestServeQuality:
    def _session(self, tmp_path):
        from kafka_tpu.serve import TileSession, make_synthetic_tile

        spec = make_synthetic_tile(
            "tile0", ckpt_dir=str(tmp_path / "ckpt_tile0"),
            operator="identity", ny=16, nx=16, days=8,
        )
        return TileSession(spec)

    def test_response_carries_quality_next_to_solver_health(
            self, tmp_path):
        from kafka_tpu.serve.synthetic import synthetic_dates

        with telemetry.use(MetricsRegistry(str(tmp_path / "tel"))):
            sess = self._session(tmp_path)
            dates = synthetic_dates(day(0), 8, 2)
            body = sess.serve(dates[-1])
            assert body["status"] == "ok"
            assert "solver_health" in body
            q = body["quality"]
            assert q["verdict"] in quality.VERDICTS
            assert sum(q["windows"].values()) >= 1
            assert q["drift_active"] is False
            # A warm_noop serve runs zero windows: no verdict.
            body2 = sess.serve(dates[-1])
            assert body2["served_from"] == "warm_noop"
            assert body2["quality"]["verdict"] is None
            assert body2["quality"]["windows"] == {}
        # Acceptance: the serving path writes the same quality.jsonl
        # ledger the batch drivers do, keyed by tile.
        records, _ = quality.load_ledger(
            str(tmp_path / "tel" / quality.LEDGER_FILENAME)
        )
        assert records
        assert all(r["prefix"] == "tile:tile0" for r in records)

    def test_admission_sheds_on_quality_drift_when_opted_in(self):
        from kafka_tpu.serve.admission import (
            AdmissionController, AdmissionPolicy,
        )

        with telemetry.use(MetricsRegistry()) as reg:
            ctl = AdmissionController(
                AdmissionPolicy(shed_on_quality_drift=True)
            )
            assert ctl.decide(queue_depth=0) is None
            reg.gauge("kafka_quality_drift_active").set(2)
            assert ctl.decide(queue_depth=0) == "quality_degraded"
            reg.gauge("kafka_quality_drift_active").set(0)
            assert ctl.decide(queue_depth=0) is None
            # Default policy: drift never sheds.
            default = AdmissionController(AdmissionPolicy())
            reg.gauge("kafka_quality_drift_active").set(2)
            assert default.decide(queue_depth=0) is None


# ---------------------------------------------------------------------------
# Observability wiring: statusz, live snapshots, fleet view, --watch.
# ---------------------------------------------------------------------------

class TestQualityObservability:
    def test_live_snapshot_carries_quality(self):
        from kafka_tpu.telemetry.live import build_snapshot

        with telemetry.use(MetricsRegistry()) as reg:
            led = quality.get_ledger(reg)
            for _ in range(6):
                led.record_window(day(1), [1.0], n_valid=4)
            led.record_window(day(2), [70.0], n_valid=4)
            snap = build_snapshot(reg)
        q = snap["quality"]
        assert q["last_verdict"] == quality.OVERCONFIDENT
        assert q["drift_active"] == 1

    def test_statusz_reports_quality(self):
        import urllib.request

        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        with telemetry.use(MetricsRegistry()) as reg:
            quality.get_ledger(reg).record_window(
                day(1), [1.0], n_valid=4,
            )
            httpd = TelemetryHTTPd(port=0, registry=reg).start()
            try:
                with urllib.request.urlopen(
                        httpd.url + "/statusz", timeout=5) as resp:
                    body = json.loads(resp.read())
            finally:
                httpd.close()
        assert body["quality"]["last_verdict"] == quality.CONSISTENT
        assert body["quality"]["drift_active"] == 0

    def _snap(self, ts, host, quality_summary):
        return {
            "schema": 1, "ts": ts, "host": host, "pid": 1,
            "role": "engine", "seq": 1, "interval_s": 2.0,
            "final": True, "run_id": "r", "chunk_id": None,
            "health": {"unhealthy": None},
            "quality": quality_summary,
            "counters": {}, "gauges": {}, "histograms": {},
            "series_truncated": 0, "crash_dumps": [], "status": {},
        }

    def test_fleet_view_folds_quality(self):
        import time as _time

        from kafka_tpu.telemetry.aggregate import aggregate_fleet

        now = _time.time()
        fleet = aggregate_fleet([
            self._snap(now, "a", {
                "last_verdict": quality.CONSISTENT, "windows": {},
                "drift_active": 0, "drifting": [], "records": 3,
                "ledger_path": None,
            }),
            self._snap(now, "b", {
                "last_verdict": quality.OVERCONFIDENT, "windows": {},
                "drift_active": 2, "drifting": ["-:band0"],
                "records": 3, "ledger_path": None,
            }),
        ], now=now)
        assert fleet["quality"]["drifting_workers"] == ["b:1"]
        assert fleet["quality"]["last_verdicts"] == {
            quality.CONSISTENT: 1, quality.OVERCONFIDENT: 1,
        }
        by_key = {w["key"]: w for w in fleet["workers"]}
        assert by_key["b:1"]["quality"]["drift_active"] == 2

    def test_fleet_status_renders_quality_and_watch_loops(
            self, tmp_path, capsys):
        from tools import fleet_status

        snap = self._snap(0, "h", {
            "last_verdict": quality.OVERCONFIDENT, "windows": {},
            "drift_active": 1, "drifting": ["-:band0"], "records": 1,
            "ledger_path": None,
        })
        snap["ts"] = __import__("time").time()
        with open(tmp_path / "live_h_1.json", "w") as f:
            json.dump(snap, f)
        assert fleet_status.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "quality=OVERCONFIDENT(DRIFT)" in out
        assert "quality drift ACTIVE on: h:1" in out
        # --watch N: periodic redraw; the single-iteration smoke hook.
        rc = fleet_status.main([
            str(tmp_path), "--watch", "0.01", "--watch-count", "2",
        ])
        assert rc == 0
        watched = capsys.readouterr().out
        assert watched.count("quality drift ACTIVE") == 2
        assert "\x1b[2J" in watched


# ---------------------------------------------------------------------------
# Bench artifact + bench_compare wiring.
# ---------------------------------------------------------------------------

class TestBenchQuality:
    def test_quality_snapshot_reads_registry(self):
        import bench

        with telemetry.use(MetricsRegistry()) as reg:
            led = quality.get_ledger(reg)
            for _ in range(6):
                led.record_window(day(1), [1.0], n_valid=4)
            led.record_window(day(2), [70.0], n_valid=4)
            snap = bench.quality_snapshot(reg)
        assert snap["verdict"] == quality.OVERCONFIDENT
        assert snap["windows"][quality.CONSISTENT] == 6
        assert snap["windows"][quality.OVERCONFIDENT] == 1
        assert snap["drift_events"] == 1
        assert snap["drift_active"] == 1

    def _artifact(self, tmp_path, name, verdict, drift_events=0):
        art = {
            "device_xla_ms": 6.4,
            "unhealthy": False,
            "quality": {
                "verdict": verdict,
                "windows": {},
                "drift_events": drift_events,
                "drift_active": 0,
            },
        }
        path = tmp_path / name
        path.write_text(json.dumps(art))
        return str(path)

    def test_bench_compare_warns_on_verdict_flip(self, tmp_path,
                                                 capsys):
        from tools import bench_compare

        old = self._artifact(tmp_path, "old.json", quality.CONSISTENT)
        new = self._artifact(tmp_path, "new.json",
                             quality.OVERCONFIDENT, drift_events=3)
        rc = bench_compare.main([old, new])
        captured = capsys.readouterr()
        assert rc == 0  # informational, never a timing gate
        assert "verdict flipped CONSISTENT -> OVERCONFIDENT" in \
            captured.err
        assert "drift_events went 0 -> 3" in captured.err
        assert "assimilation-quality deltas" in captured.out

    def test_bench_compare_quiet_when_consistent(self, tmp_path,
                                                 capsys):
        from tools import bench_compare

        old = self._artifact(tmp_path, "old.json", quality.CONSISTENT)
        new = self._artifact(tmp_path, "new.json", quality.CONSISTENT)
        rc = bench_compare.main([old, new])
        captured = capsys.readouterr()
        assert rc == 0
        assert "WARNING" not in captured.err
