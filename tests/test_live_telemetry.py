"""Fleet observability plane (ISSUE 10): the live snapshot publisher,
the stdlib HTTP endpoint, Prometheus text-exposition conformance
(parser round-trip), cross-process aggregation with dead-host
detection, trace stitching, the queue-status liveness join, and the
fleet-gauge admission signal.  The multi-process acceptance lives in
tests/test_fleet_chaos.py."""

import json
import math
import os
import time
import urllib.error
import urllib.request

import pytest

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry, live, tracing
from kafka_tpu.telemetry.aggregate import (
    aggregate_fleet,
    discover_queue_outdir,
    load_live_snapshots,
    parse_prom_text,
    quantile_from_buckets,
    stitch_traces,
    worker_liveness,
)
from kafka_tpu.telemetry.httpd import TelemetryHTTPd, maybe_start


@pytest.fixture(autouse=True)
def _clean_publisher():
    yield
    live.stop_publisher()
    live._status.clear()


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode("utf-8")


# ---------------------------------------------------------------------------
# Prometheus exposition conformance: the round-trip pins it.
# ---------------------------------------------------------------------------

class TestPromExposition:
    def test_round_trip_counters_and_gauges(self):
        reg = MetricsRegistry()
        reg.counter("kafka_test_total", "requests").inc(3, band="b1")
        reg.counter("kafka_test_total").inc(2, band="b2")
        reg.gauge("kafka_test_depth", "queue depth").set(2.5)
        fams = parse_prom_text(reg.prom_text())
        assert fams["kafka_test_total"]["type"] == "counter"
        assert fams["kafka_test_total"]["help"] == "requests"
        by_band = {
            s["labels"]["band"]: s["value"]
            for s in fams["kafka_test_total"]["samples"]
        }
        assert by_band == {"b1": 3.0, "b2": 2.0}
        assert fams["kafka_test_depth"]["samples"][0]["value"] == 2.5

    def test_label_escaping_round_trips(self):
        """Backslash, quote and newline in label values must survive
        the text format — chunk prefixes and error strings land in
        labels."""
        ugly = 'a"b\\c\nd'
        reg = MetricsRegistry()
        reg.counter("kafka_test_total").inc(1, err=ugly)
        fams = parse_prom_text(reg.prom_text())
        assert fams["kafka_test_total"]["samples"][0]["labels"]["err"] \
            == ugly

    def test_nonfinite_values_spelled_prometheus_style(self):
        reg = MetricsRegistry()
        reg.gauge("kafka_test_inf").set(math.inf)
        reg.gauge("kafka_test_ninf").set(-math.inf)
        text = reg.prom_text()
        assert "kafka_test_inf +Inf" in text
        assert "kafka_test_ninf -Inf" in text
        fams = parse_prom_text(text)
        assert fams["kafka_test_inf"]["samples"][0]["value"] == math.inf

    def test_histogram_buckets_cumulative_with_sum_count(self):
        """The scraped histogram must satisfy the Prometheus contract:
        cumulative nondecreasing ``_bucket{le=}`` counts, the ``+Inf``
        bucket equal to ``_count``, and a ``_sum`` series — otherwise
        ``histogram_quantile`` over a scrape is garbage."""
        reg = MetricsRegistry()
        h = reg.histogram("kafka_test_seconds", "lat",
                          buckets=(0.1, 0.5, 1.0))
        for v in (0.05, 0.3, 0.3, 0.7, 5.0):
            h.observe(v)
        fams = parse_prom_text(reg.prom_text())
        assert fams["kafka_test_seconds"]["type"] == "histogram"
        buckets = {
            s["labels"]["le"]: s["value"]
            for s in fams["kafka_test_seconds_bucket"]["samples"]
        }
        assert buckets == {"0.1": 1, "0.5": 3, "1": 4, "+Inf": 5}
        ordered = [buckets["0.1"], buckets["0.5"], buckets["1"],
                   buckets["+Inf"]]
        assert ordered == sorted(ordered)  # cumulative, nondecreasing
        assert fams["kafka_test_seconds_count"]["samples"][0]["value"] \
            == 5
        assert fams["kafka_test_seconds_sum"]["samples"][0]["value"] \
            == pytest.approx(6.35)

    def test_parser_rejects_garbage(self):
        with pytest.raises(ValueError):
            parse_prom_text("this is not exposition format\n")


# ---------------------------------------------------------------------------
# Live snapshot publisher.
# ---------------------------------------------------------------------------

class TestLivePublisher:
    def test_snapshot_contents_and_final_marker(self, tmp_path):
        d = str(tmp_path)
        with telemetry.use(MetricsRegistry(d)) as reg:
            reg.counter("kafka_test_total").inc(4)
            reg.gauge("kafka_test_depth").set(7)
            reg.histogram("kafka_test_seconds",
                          buckets=(0.1, 1.0)).observe(0.5)
            reg.gauge("kafka_health_unhealthy").set(0.0)
            live.update_status(queue_outdir="/q", worker_id="w")
            # A crash dump on disk must be indexed by the snapshot.
            open(os.path.join(d, "crash_x_1.json"), "w").write("{}")
            with tracing.push(run_id="r-pub", chunk_id="00aa"):
                pub = live.LivePublisher(
                    d, role="queue_worker", interval_s=30.0
                ).start()
                path = pub.publish_now()
                snap = json.load(open(path))  # pre-stop state
                pub.stop()
        assert snap["schema"] == live.SCHEMA_VERSION
        assert snap["pid"] == os.getpid()
        assert snap["role"] == "queue_worker"
        assert snap["run_id"] == "r-pub"
        assert snap["chunk_id"] == "00aa"
        assert snap["counters"]["kafka_test_total"] == 4
        assert snap["gauges"]["kafka_test_depth"] == 7
        hist = snap["histograms"]["kafka_test_seconds"]
        assert hist["le"] == [0.1, 1.0] and hist["count"] == 1
        assert snap["health"]["unhealthy"] is False
        assert snap["status"]["queue_outdir"] == "/q"
        assert snap["crash_dumps"] == ["crash_x_1.json"]
        # stop() republished with the clean-shutdown marker.
        final = json.load(open(pub.path))
        assert final["final"] is True
        assert final["seq"] > snap["seq"]
        # Atomic writes: no torn tmp litter.
        assert not [n for n in os.listdir(d) if ".tmp" in n]

    def test_background_thread_republishes(self, tmp_path):
        with telemetry.use(MetricsRegistry(str(tmp_path))):
            pub = live.LivePublisher(
                str(tmp_path), interval_s=0.05
            ).start()
            deadline = time.time() + 10
            seq = 0
            while time.time() < deadline and seq < 3:
                try:
                    seq = json.load(open(pub.path))["seq"]
                except (OSError, ValueError):
                    pass
                time.sleep(0.02)
            pub.stop()
        assert seq >= 3

    def test_series_bounded(self, tmp_path, monkeypatch):
        monkeypatch.setattr(live, "MAX_SERIES", 2)
        with telemetry.use(MetricsRegistry(str(tmp_path))) as reg:
            for i in range(5):
                reg.counter("kafka_test_total").inc(1, k=str(i))
            snap = live.build_snapshot(reg)
        assert len(snap["counters"]) == 2
        assert snap["series_truncated"] == 3

    def test_start_publisher_requires_directory(self):
        with telemetry.use(MetricsRegistry()):
            assert live.start_publisher() is None

    def test_flight_recorder_dump_refreshes_snapshot(self, tmp_path):
        """Satellite: a crash dump must be referenced from the live
        snapshot immediately — the fleet view points at the forensics
        file without waiting out the publish interval."""
        from kafka_tpu.telemetry.flight_recorder import FlightRecorder

        d = str(tmp_path)
        with telemetry.use(MetricsRegistry(d)):
            pub = live.start_publisher(directory=d, interval_s=60.0)
            recorder = FlightRecorder(d)
            crash = recorder.dump("exception", exc=ValueError("boom"))
            snap = json.load(open(pub.path))
            live.stop_publisher()
        assert os.path.basename(crash) in snap["crash_dumps"]


# ---------------------------------------------------------------------------
# HTTP endpoint.
# ---------------------------------------------------------------------------

class TestHTTPd:
    def test_port_zero_means_disabled(self):
        assert maybe_start(0) is None
        assert maybe_start(None) is None

    def test_metrics_endpoint_serves_parseable_exposition(self):
        with telemetry.use(MetricsRegistry()) as reg:
            reg.counter("kafka_test_total").inc(2)
            h = TelemetryHTTPd(port=0).start()
            try:
                code, ctype, body = _get(h.url + "/metrics")
            finally:
                h.close()
        assert code == 200
        assert ctype.startswith("text/plain")
        fams = parse_prom_text(body)
        assert fams["kafka_test_total"]["samples"][0]["value"] == 2
        # The endpoint's own access counter is live too.
        assert "kafka_httpd_requests_total" in fams

    def test_healthz_reads_registry_verdict(self):
        with telemetry.use(MetricsRegistry()) as reg:
            h = TelemetryHTTPd(port=0).start()
            try:
                code, _, body = _get(h.url + "/healthz")
                assert code == 200
                assert json.loads(body)["verdict"] == "unprobed"
                reg.gauge("kafka_health_unhealthy").set(0.0)
                code, _, body = _get(h.url + "/healthz")
                assert code == 200
                assert json.loads(body)["verdict"] == "healthy"
                reg.gauge("kafka_health_unhealthy").set(1.0)
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _get(h.url + "/healthz")
                assert exc.value.code == 503
                assert json.loads(exc.value.read())["verdict"] == \
                    "unhealthy"
            finally:
                h.close()

    def test_statusz_carries_provider_and_crash_index(self, tmp_path):
        d = str(tmp_path)
        with telemetry.use(MetricsRegistry(d)) as reg:
            reg.counter("kafka_solver_nonfinite_total").inc(3)
            open(os.path.join(d, "crash_y_2.json"), "w").write("{}")
            h = TelemetryHTTPd(
                port=0, role="serve",
                status_provider=lambda: {"queue_depth": 5},
            ).start()
            try:
                with tracing.push(run_id="r-sz"):
                    code, ctype, body = _get(h.url + "/statusz")
            finally:
                h.close()
        assert code == 200 and ctype == "application/json"
        sz = json.loads(body)
        assert sz["pid"] == os.getpid()
        assert sz["status"]["queue_depth"] == 5
        assert sz["crash_dumps"] == ["crash_y_2.json"]
        assert sz["solver_health"]["kafka_solver_nonfinite_total"] == 3

    def test_unknown_path_404s(self):
        with telemetry.use(MetricsRegistry()):
            h = TelemetryHTTPd(port=0).start()
            try:
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _get(h.url + "/nope")
                assert exc.value.code == 404
            finally:
                h.close()


# ---------------------------------------------------------------------------
# Fleet aggregation.
# ---------------------------------------------------------------------------

def _snap(tmp_path, rel, host, pid, ts, *, final=False, interval=1.0,
          counters=None, gauges=None, histograms=None, status=None,
          run_id="r1", role="queue_worker", crash=()):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps({
        "schema": 1, "ts": ts, "host": host, "pid": pid, "role": role,
        "seq": 1, "interval_s": interval, "final": final,
        "run_id": run_id, "chunk_id": None,
        "health": {"unhealthy": False},
        "counters": counters or {}, "gauges": gauges or {},
        "histograms": histograms or {}, "series_truncated": 0,
        "crash_dumps": list(crash), "status": status or {},
    }))
    return str(path)


class TestAggregate:
    def test_counters_sum_gauges_per_host_dead_flagged(self, tmp_path):
        now = time.time()
        _snap(tmp_path, "w0/live_hostA_1.json", "hostA", 1, now - 0.2,
              counters={"kafka_shard_chunks_completed_total": 4},
              gauges={"kafka_shard_chunks_pending": 2})
        _snap(tmp_path, "w1/live_hostB_2.json", "hostB", 2, now - 60,
              counters={"kafka_shard_chunks_completed_total": 5},
              gauges={"kafka_shard_chunks_pending": 7},
              crash=["crash_z.json"])
        _snap(tmp_path, "w2/live_hostC_3.json", "hostC", 3, now - 60,
              final=True,
              counters={"kafka_shard_chunks_completed_total": 1})
        fleet = aggregate_fleet(
            load_live_snapshots(str(tmp_path)), now=now, ttl_s=5.0
        )
        assert fleet["n_workers"] == 3
        assert fleet["counters"][
            "kafka_shard_chunks_completed_total"] == 10
        by = fleet["counters_by_worker"][
            "kafka_shard_chunks_completed_total"]
        assert sum(by.values()) == 10 and len(by) == 3
        assert fleet["gauges"]["kafka_shard_chunks_pending"] == {
            "hostA:1": 2, "hostB:2": 7,
        }
        # Stale heartbeat without a final marker = dead; a clean exit
        # (final) is never dead however old.
        assert fleet["dead_hosts"] == ["hostB:2"]
        assert fleet["crash_dumps"] == [
            {"worker": "hostB:2", "file": "crash_z.json"}
        ]
        assert fleet["run_ids"] == ["r1"]

    def test_default_ttl_is_three_intervals(self, tmp_path):
        now = time.time()
        _snap(tmp_path, "live_h_9.json", "h", 9, now - 2.0,
              interval=1.0)
        fleet = aggregate_fleet(load_live_snapshots(str(tmp_path)),
                                now=now)
        assert fleet["dead_hosts"] == []  # 2s < 3x1s
        fleet = aggregate_fleet(load_live_snapshots(str(tmp_path)),
                                now=now + 2.0)
        assert fleet["dead_hosts"] == ["h:9"]

    def test_newest_snapshot_wins_per_worker(self, tmp_path):
        now = time.time()
        _snap(tmp_path, "a/live_h_1.json", "h", 1, now - 50,
              counters={"kafka_test_total": 1})
        _snap(tmp_path, "b/live_h_1.json", "h", 1, now - 1,
              counters={"kafka_test_total": 6})
        fleet = aggregate_fleet(load_live_snapshots(str(tmp_path)),
                                now=now, ttl_s=5.0)
        assert fleet["n_workers"] == 1
        assert fleet["counters"]["kafka_test_total"] == 6
        assert fleet["dead_hosts"] == []

    def test_histograms_merge_into_fleet_quantiles(self, tmp_path):
        now = time.time()
        le = [1.0, 2.0, 4.0]
        _snap(tmp_path, "w0/live_h_1.json", "h", 1, now,
              histograms={"kafka_serve_latency_seconds": {
                  "le": le, "buckets": [10, 10, 10], "sum": 5.0,
                  "count": 10}})
        _snap(tmp_path, "w1/live_h_2.json", "h", 2, now,
              histograms={"kafka_serve_latency_seconds": {
                  "le": le, "buckets": [0, 10, 10], "sum": 15.0,
                  "count": 10}})
        fleet = aggregate_fleet(load_live_snapshots(str(tmp_path)),
                                now=now, ttl_s=5.0)
        h = fleet["histograms"]["kafka_serve_latency_seconds"]
        assert h["count"] == 20 and h["sum"] == 20.0
        # Merged cumulative buckets: [10, 20, 20] — the median falls
        # exactly at the first bucket's boundary.
        assert h["p50"] == pytest.approx(1.0)
        assert h["p99"] == pytest.approx(1.98)

    def test_quantile_interpolation(self):
        assert quantile_from_buckets([1.0, 2.0], [5, 10], 10, 0.5) \
            == pytest.approx(1.0)
        assert quantile_from_buckets([1.0, 2.0], [0, 10], 10, 0.5) \
            == pytest.approx(1.5)
        # Beyond the last finite bucket: clamp to its bound.
        assert quantile_from_buckets([1.0, 2.0], [0, 0], 10, 0.5) == 2.0
        assert quantile_from_buckets([], [], 0, 0.5) is None

    def test_queue_outdir_discovery_and_liveness(self, tmp_path):
        now = time.time()
        _snap(tmp_path, "live_h_1.json", "h", 1, now - 0.1,
              status={"queue_outdir": "/data/q", "worker_id": "h:1"})
        snaps = load_live_snapshots(str(tmp_path))
        assert discover_queue_outdir(snaps) == "/data/q"
        lv = worker_liveness(snaps, now=now, ttl_s=5.0)
        assert lv["h:1"]["dead"] is False
        assert lv["h:1"]["age_s"] == pytest.approx(0.1, abs=0.05)


# ---------------------------------------------------------------------------
# Trace stitching (unit; the multi-process golden test lives in
# test_fleet_chaos.py).
# ---------------------------------------------------------------------------

class TestStitchTraces:
    def _fragment(self, tmp_path, rel, run_id, epoch, span="work"):
        from kafka_tpu.telemetry.tracing import TraceBuffer

        buf = TraceBuffer()
        buf.epoch = epoch
        t0 = time.perf_counter()
        with tracing.push(run_id=run_id):
            buf.add_span(span, t0, t0 + 0.01)
        d = tmp_path / rel
        d.mkdir(parents=True, exist_ok=True)
        buf.export(str(d / "trace.json"))

    def test_stitch_remaps_pids_and_aligns_epochs(self, tmp_path):
        self._fragment(tmp_path, "worker_0", "r-st", 100.0, span="w0")
        self._fragment(tmp_path, "worker_1", "r-st", 103.0, span="w1")
        self._fragment(tmp_path, "other", "r-unrelated", 101.0,
                       span="noise")
        doc = stitch_traces(str(tmp_path), run_id="r-st")
        assert doc["otherData"]["run_ids"] == ["r-st"]
        assert len(doc["otherData"]["sources"]) == 2
        spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        names = {e["name"] for e in spans}
        assert names == {"w0", "w1"}
        pids = {e["pid"] for e in spans}
        assert len(pids) == 2
        # Epoch alignment: worker_1's fragment started 3s later, so its
        # span timestamps sit ~3e6 us after worker_0's.
        ts = {e["name"]: e["ts"] for e in spans}
        assert ts["w1"] - ts["w0"] == pytest.approx(3e6, rel=0.1)
        # Every source gets a named process track.
        proc_names = {
            e["args"]["name"] for e in doc["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert proc_names == {"kafka_tpu worker_0", "kafka_tpu worker_1"}

    def test_no_filter_merges_everything(self, tmp_path):
        self._fragment(tmp_path, "a", "r1", 100.0)
        self._fragment(tmp_path, "b", "r2", 100.0)
        doc = stitch_traces(str(tmp_path))
        assert sorted(doc["otherData"]["run_ids"]) == ["r1", "r2"]
        assert len(doc["otherData"]["sources"]) == 2


# ---------------------------------------------------------------------------
# Operator CLIs: fleet_status and the queue_status liveness join.
# ---------------------------------------------------------------------------

class TestFleetStatusCLI:
    def test_json_view_and_render(self, tmp_path, capsys):
        from tools.fleet_status import main

        now = time.time()
        _snap(tmp_path, "w0/live_hostA_1.json", "hostA", 1, now - 0.1,
              counters={"kafka_shard_chunks_completed_total": 3})
        _snap(tmp_path, "w1/live_hostB_2.json", "hostB", 2, now - 500)
        assert main([str(tmp_path), "--json", "--ttl-s", "5"]) == 0
        fleet = json.loads(capsys.readouterr().out)
        assert fleet["dead_hosts"] == ["hostB:2"]
        assert fleet["counters"][
            "kafka_shard_chunks_completed_total"] == 3
        assert main([str(tmp_path), "--ttl-s", "5"]) == 0
        out = capsys.readouterr().out
        assert "DEAD" in out and "hostB:2" in out

    def test_missing_root_is_usage_error(self, tmp_path, capsys):
        from tools.fleet_status import main

        assert main([str(tmp_path / "nope")]) == 2

    def test_stitch_trace_flag_writes_merged_trace(self, tmp_path,
                                                   capsys):
        from tools.fleet_status import main

        TestStitchTraces()._fragment(tmp_path, "w0", "rf", 100.0)
        out = tmp_path / "merged.json"
        assert main([str(tmp_path), "--json",
                     "--stitch-trace", str(out), "--run-id", "rf"]) == 0
        doc = json.load(open(out))
        assert doc["otherData"]["run_ids"] == ["rf"]
        assert any(e["ph"] == "X" for e in doc["traceEvents"])


class TestQueueStatusLiveness:
    def test_liveness_joined_to_lease_ownership(self, tmp_path, capsys):
        from tools.queue_status import main
        from kafka_tpu.shard.queue import _try_claim, write_manifest
        from kafka_tpu.io.tiling import get_chunks

        outdir = tmp_path / "q"
        outdir.mkdir()
        chunks = list(get_chunks(64, 32, (32, 32)))
        write_manifest(str(outdir), chunks)
        _try_claim(str(outdir), "0001", "hostA:1", lease_ttl_s=60.0)
        tel = tmp_path / "tel"
        now = time.time()
        _snap(tel, "w/live_hostA_1.json", "hostA", 1, now - 90)
        rc = main([str(outdir), "--json",
                   "--telemetry-dir", str(tel), "--ttl-s", "5"])
        assert rc == 0
        status = json.loads(capsys.readouterr().out)
        assert status["liveness"]["hostA:1"]["dead"] is True
        rc = main([str(outdir), "--telemetry-dir", str(tel),
                   "--ttl-s", "5"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "DEAD" in out and "hostA:1" in out


# ---------------------------------------------------------------------------
# Serve-side fleet awareness: admission sheds on the fleet gauge.
# ---------------------------------------------------------------------------

class TestFleetAdmission:
    def test_sheds_when_fleet_degraded(self):
        from kafka_tpu.serve.admission import (
            AdmissionController, AdmissionPolicy,
        )

        with telemetry.use(MetricsRegistry()) as reg:
            ctl = AdmissionController(
                AdmissionPolicy(max_dead_hosts=0)
            )
            assert ctl.decide(queue_depth=0) is None  # gauge unset
            reg.gauge("kafka_fleet_dead_hosts").set(1)
            assert ctl.decide(queue_depth=0) == "fleet_degraded"
            reg.gauge("kafka_fleet_dead_hosts").set(0)
            assert ctl.decide(queue_depth=0) is None
            # Default policy ignores the gauge entirely.
            reg.gauge("kafka_fleet_dead_hosts").set(9)
            assert AdmissionController().decide(queue_depth=0) is None

    def test_daemon_refreshes_gauge_from_snapshots(self, tmp_path):
        from kafka_tpu.serve.daemon import ServeDaemon

        now = time.time()
        _snap(tmp_path / "fleet", "w/live_deadhost_7.json",
              "deadhost", 7, now - 900)
        with telemetry.use(MetricsRegistry()) as reg:
            daemon = ServeDaemon.__new__(ServeDaemon)
            daemon.fleet_dir = str(tmp_path / "fleet")
            daemon.fleet_refresh_s = 0.0
            daemon.fleet_ttl_s = 5.0
            daemon._fleet_next = 0.0
            daemon._refresh_fleet_gauge()
            assert reg.value("kafka_fleet_dead_hosts") == 1
            assert any(e["event"] == "fleet_dead_hosts_changed"
                       for e in reg.events)


# ---------------------------------------------------------------------------
# bench_compare: live_telemetry diffed informationally.
# ---------------------------------------------------------------------------

class TestBenchCompareLiveTelemetry:
    ART = {
        "device_xla_ms": 6.4, "unhealthy": False,
        "live_telemetry": {
            "samples": 3,
            "series": {"kafka_serve_queue_depth": [0, 4, 0]},
        },
    }

    def test_informational_lines_never_gate(self, tmp_path, capsys):
        from tools.bench_compare import live_telemetry_deltas, main

        new = json.loads(json.dumps(self.ART))
        new["live_telemetry"]["series"][
            "kafka_serve_queue_depth"] = [0, 9, 1]
        lines = live_telemetry_deltas(self.ART, new)
        assert any("queue_depth" in line and "peak 4 -> 9" in line
                   for line in lines)
        old_p = tmp_path / "old.json"
        new_p = tmp_path / "new.json"
        old_p.write_text(json.dumps(self.ART))
        new_p.write_text(json.dumps(new))
        assert main([str(old_p), str(new_p)]) == 0
        out = capsys.readouterr().out
        assert "live telemetry deltas" in out

    def test_identical_series_stay_silent(self):
        from tools.bench_compare import live_telemetry_deltas

        assert live_telemetry_deltas(self.ART, self.ART) == []
        assert live_telemetry_deltas({}, {}) == []
