"""Seeded contract violations for tools/programlint (the IR-level twin
of tests/lint_fixtures/): four fixture programs, each violating exactly
one checker's contract, registered in a private REGISTRY the CLI loads
via ``--spec-module tests.programlint_fixtures``.

``EXPECT`` mirrors the lint fixtures' ``# expect: <rule>`` convention at
program granularity: fixture name -> the one checker that must (and the
only checker that may) report it.
"""

from __future__ import annotations

from kafka_tpu.analysis.registry import BuiltProgram, register_program

#: fixture program -> the intended checker (and no other).
EXPECT = {
    "fixture_f64_upcast": "dtype",
    "fixture_smuggled_callback": "transfer",
    "fixture_rank3_relayout": "relayout",
    "fixture_unmanifested_collective": "collective",
}

REGISTRY = {}


def _sds(shape, dtype="float32"):
    import jax
    import numpy as np

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


@register_program(
    "fixture_f64_upcast",
    description="seeded violation: a mid-program astype(float64) upcast "
                "(traced under x64 so the upcast is visible, exactly the "
                "leak scenario the dtype checker guards)",
    x64=True,
    registry=REGISTRY,
)
def _build_f64():
    import jax.numpy as jnp

    def run(x):
        acc = x.astype(jnp.float64)       # the seeded upcast
        return (acc * acc).sum(axis=-1).astype(jnp.float32)

    return run, (_sds((64, 7)),)


@register_program(
    "fixture_smuggled_callback",
    description="seeded violation: a pure_callback smuggled into the "
                "traced body — a host round-trip per execution",
    registry=REGISTRY,
)
def _build_callback():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def host_mean(x):
        return np.mean(x, axis=-1, keepdims=True)

    def run(x):
        m = jax.pure_callback(
            host_mean, jax.ShapeDtypeStruct((64, 1), np.float32), x
        )
        return jnp.asarray(x) - m

    return run, (_sds((64, 7)),)


@register_program(
    "fixture_rank3_relayout",
    description="seeded violation: a rank-3 Jacobian-shaped transpose in "
                "a program registered relayout_clean",
    relayout_clean=True,
    registry=REGISTRY,
)
def _build_relayout():
    import jax.numpy as jnp

    def run(jac):
        # the (n_pix, B, p) -> (B, n_pix, p) relayout the in-kernel path
        # exists to delete.
        rows = jnp.transpose(jac, (1, 0, 2))
        return rows.sum(axis=-1)

    return run, (_sds((64, 2, 7)),)


@register_program(
    "fixture_unmanifested_collective",
    description="seeded violation: a cross-pixel mean under a pixel-"
                "sharded 1xN CPU mesh with an EMPTY collectives manifest "
                "— GSPMD must insert an unmanifested all-reduce",
    collectives=(),
    registry=REGISTRY,
)
def _build_collective():
    import jax

    from kafka_tpu.shard.mesh import make_pixel_mesh, pixel_sharding

    devices = jax.devices()
    mesh = make_pixel_mesh(devices)
    sh = pixel_sharding(mesh, 0, 1)

    def run(x):
        return x - x.mean()               # cross-shard reduction

    fn = jax.jit(run, in_shardings=(sh,), out_shardings=sh)
    n = 128 * max(len(devices), 1)
    return BuiltProgram(
        fn=fn, args=(_sds((n,)),), mesh_devices=len(devices)
    )
