"""Assimilation-as-a-service (ISSUE 8): admission control, deadlines,
crash-safe journal replay, warm-state incremental serving, and the
chaos acceptance tests.

Acceptance pins:

- warm-path parity: a request served incrementally from a warm
  checkpoint is IDENTICAL to a cold full-series rerun — bit-identical
  on the unfused CPU path, within the established fused budget when
  temporal scan fusion is on;
- (a) overload beyond the admission threshold sheds with counted
  rejections while every admitted request completes;
- (b) SIGKILL of the daemon mid-request, then restart: resumes from the
  warm checkpoint, journal replay re-serves the interrupted request,
  and its output matches the uninterrupted run;
- (c) SIGTERM: in-flight requests finish, new requests are rejected,
  exit 0.

All tier-1 / CPU.
"""

import datetime
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.engine import Checkpointer, KalmanFilter
from kafka_tpu.resilience import POISON, RetryPolicy, faults
from kafka_tpu.serve import (
    AdmissionController,
    AdmissionPolicy,
    AssimilationService,
    BadRequest,
    RequestJournal,
    ServeDaemon,
    TileSession,
    make_synthetic_tile,
    parse_request,
    read_response,
    submit_request,
    synthetic_dates,
)
from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
from kafka_tpu.telemetry import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the default synthetic tile's observation calendar.
DATES = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)

#: zero-wait deterministic retry for the service under test.
FAST2 = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)


def day(i):
    return datetime.datetime(2017, 7, 1) + datetime.timedelta(days=i)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(faults.ENV_VAR, None)
    return env


class StubSession:
    """Duck-typed tile session for service-mechanics tests: no JAX, the
    solve is a recorded, optionally-blocking constant."""

    def __init__(self, name="t", block=None, body=None):
        self.name = name
        self.block = block
        self.body = body or {"status": "ok", "x_sha256": "stub"}
        self.serves = 0
        self.started = threading.Event()

    def serve(self, date):
        self.serves += 1
        self.started.set()
        if self.block is not None:
            assert self.block.wait(timeout=30.0)
        out = dict(self.body)
        out["date"] = date.isoformat()
        return out


def stub_service(tmp_path, reg=None, block=None, max_queue=8, **kw):
    sess = StubSession(block=block)
    svc = AssimilationService(
        {"t": sess}, str(tmp_path),
        policy=AdmissionPolicy(max_queue_depth=max_queue),
        retry_policy=FAST2, **kw,
    )
    return svc, sess


# ---------------------------------------------------------------------------
# request parsing
# ---------------------------------------------------------------------------

class TestParseRequest:
    def test_roundtrip(self):
        req = parse_request({
            "request_id": "r-1", "tile": "t", "date": "2017-07-05",
            "deadline_s": 3.5,
        })
        assert req.tile == "t" and req.date == day(4)
        assert req.deadline is not None and req.deadline_s == 3.5
        assert req.payload()["date"] == "2017-07-05T00:00:00"

    def test_generated_id_and_default_deadline(self):
        req = parse_request({"tile": "t", "date": "2017-07-05"},
                            default_deadline_s=9.0)
        assert len(req.request_id) == 16 and req.deadline_s == 9.0

    @pytest.mark.parametrize("payload", [
        "not a dict",
        {"tile": "t"},                                    # no date
        {"tile": "t", "date": "yesterday-ish"},
        {"date": "2017-07-05"},                           # no tile
        {"tile": "t", "date": "2017-07-05", "request_id": "../../etc"},
        {"tile": "t", "date": "2017-07-05", "deadline_s": -1},
        {"tile": "t", "date": "2017-07-05", "deadline_s": "soon"},
    ])
    def test_bad_requests_raise(self, payload):
        with pytest.raises(BadRequest):
            parse_request(payload)

    def test_replayed_requests_have_no_live_deadline(self):
        req = parse_request(
            {"tile": "t", "date": "2017-07-05", "deadline_s": 0.001,
             "submitted_ts": 1.0},
            replayed=True,
        )
        assert req.deadline is None and req.submitted_ts == 1.0


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_queue_full_sheds(self):
        ctl = AdmissionController(AdmissionPolicy(max_queue_depth=4))
        assert ctl.decide(queue_depth=3) is None
        assert ctl.decide(queue_depth=4) == "queue_full"

    def test_writer_backlog_sheds(self):
        with telemetry.use(MetricsRegistry()) as reg:
            ctl = AdmissionController(
                AdmissionPolicy(max_writer_backlog=10)
            )
            assert ctl.decide(0) is None
            reg.gauge("kafka_io_writer_backlog", "").set(11)
            assert ctl.decide(0) == "writer_backlog"

    def test_prefetch_backlog_sheds(self):
        with telemetry.use(MetricsRegistry()) as reg:
            ctl = AdmissionController(
                AdmissionPolicy(max_prefetch_queue_depth=8)
            )
            reg.gauge("kafka_prefetch_queue_depth", "").set(9)
            assert ctl.decide(0) == "prefetch_backlog"

    def test_unhealthy_verdict_sheds(self):
        with telemetry.use(MetricsRegistry()) as reg:
            ctl = AdmissionController(AdmissionPolicy())
            reg.gauge("kafka_health_unhealthy", "").set(1.0)
            assert ctl.decide(0) == "unhealthy"
            ctl2 = AdmissionController(
                AdmissionPolicy(shed_when_unhealthy=False)
            )
            assert ctl2.decide(0) is None

    def test_signals_disabled_with_none(self):
        with telemetry.use(MetricsRegistry()) as reg:
            reg.gauge("kafka_io_writer_backlog", "").set(1e9)
            ctl = AdmissionController(AdmissionPolicy(
                max_writer_backlog=None,
                max_prefetch_queue_depth=None,
                shed_when_unhealthy=False,
            ))
            assert ctl.decide(0) is None


# ---------------------------------------------------------------------------
# journal + response store
# ---------------------------------------------------------------------------

class TestJournal:
    def test_replay_skips_answered_and_dedupes(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.record({"request_id": "a", "tile": "t", "date": "d"})
        j.record({"request_id": "b", "tile": "t", "date": "d"})
        j.record({"request_id": "a", "tile": "t", "date": "d"})  # dupe
        j.respond("a", {"status": "ok"})
        pending = j.replay()
        assert [p["request_id"] for p in pending] == ["b"]
        j.close()

    def test_torn_tail_is_skipped_with_event(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            j = RequestJournal(str(tmp_path))
            j.record({"request_id": "a", "tile": "t", "date": "d"})
            with open(j.journal_path, "a") as f:
                f.write('{"request_id": "tor')  # crash mid-append
            assert [p["request_id"] for p in j.replay()] == ["a"]
            assert any(e["event"] == "journal_torn_line"
                       for e in reg.events)
            j.close()

    def test_response_write_is_atomic(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.respond("r", {"status": "ok", "n": 1})
        names = os.listdir(j.responses_dir)
        assert names == ["r.json"]  # no tmp residue
        assert j.response("r")["n"] == 1
        assert j.response("missing") is None
        j.close()


# ---------------------------------------------------------------------------
# warm-path parity (ACCEPTANCE): incremental == cold full rerun
# ---------------------------------------------------------------------------

class TestWarmPathParity:
    def test_incremental_bit_identical_to_cold_rerun(self, tmp_path):
        """The acceptance pin: serve D1 (cold), then D2 incrementally
        from the warm checkpoint; a fresh cold full-series rerun through
        D2 must produce BIT-IDENTICAL analysis arrays on the unfused CPU
        path."""
        warm = TileSession(make_synthetic_tile(
            "t", str(tmp_path / "ck_warm")))
        r1 = warm.serve(DATES[2])
        assert r1["served_from"] == "cold"
        r2 = warm.serve(DATES[6])
        assert r2["served_from"] == "warm"
        # The warm serve only ran the windows after the checkpoint.
        assert 0 < r2["windows_run"] < len(
            warm.spec.grid_through(DATES[6])) - 1

        cold = TileSession(make_synthetic_tile(
            "t", str(tmp_path / "ck_cold")))
        rc = cold.serve(DATES[6])
        assert rc["served_from"] == "cold"
        assert r2["x_sha256"] == rc["x_sha256"]
        np.testing.assert_array_equal(
            warm.last_state[0], cold.last_state[0]
        )
        np.testing.assert_array_equal(
            warm.last_state[1], cold.last_state[1]
        )

    def test_fused_scan_parity_within_budget(self, tmp_path):
        """With temporal fusion on (scan_window>1) the warm and cold
        paths bucket their scan blocks differently; parity holds within
        the established 2e-3 fused budget."""
        warm = TileSession(make_synthetic_tile(
            "t", str(tmp_path / "ck_warm"), scan_window=4))
        warm.serve(DATES[2])
        warm.serve(DATES[-1])
        cold = TileSession(make_synthetic_tile(
            "t", str(tmp_path / "ck_cold"), scan_window=4))
        cold.serve(DATES[-1])
        np.testing.assert_allclose(
            warm.last_state[0], cold.last_state[0], atol=2e-3
        )

    def test_noop_cache_and_replay_paths(self, tmp_path):
        sess = TileSession(make_synthetic_tile("t", str(tmp_path / "ck")))
        r_new = sess.serve(DATES[6])
        # Same date again: the checkpoint already sits at the grid step.
        r_noop = sess.serve(DATES[6])
        assert r_noop["served_from"] == "warm_noop"
        assert r_noop["windows_run"] == 0
        assert r_noop["x_sha256"] == r_new["x_sha256"]
        # A date BEHIND the warm chain replays cold without touching it.
        before = sess.checkpointer.list_checkpoints()
        r_old = sess.serve(DATES[2])
        assert r_old["served_from"] == "cold_replay"
        assert sess.checkpointer.list_checkpoints() == before
        # ...and matches what a chain that stopped there would have.
        ref = TileSession(make_synthetic_tile("t", str(tmp_path / "ck2")))
        assert r_old["x_sha256"] == ref.serve(DATES[2])["x_sha256"]


# ---------------------------------------------------------------------------
# resume_time_grid boundary invariants (the serve path leans on these)
# ---------------------------------------------------------------------------

class TestResumeTimeGridBoundaries:
    def _checkpoint_at(self, folder, ts, n=8, p=2):
        ck = Checkpointer(str(folder))
        x = np.full((n, p), 0.25, np.float32)
        pinv = np.stack([np.eye(p, dtype=np.float32)] * n)
        ck.save(ts, x, pinv)
        return ck, x, pinv

    def test_resume_at_midpoint_reruns_only_subsequent_dates(
            self, tmp_path):
        ck, _, _ = self._checkpoint_at(tmp_path, day(4))
        grid, seed = ck.resume_time_grid([day(0), day(2), day(4), day(6)])
        assert grid == [day(4), day(6)] and seed is not None

    def test_resume_at_exactly_last_date_is_empty_remainder(
            self, tmp_path):
        ck, x, pinv = self._checkpoint_at(tmp_path, day(6))
        grid, seed = ck.resume_time_grid([day(0), day(2), day(4), day(6)])
        assert grid == [day(6)]
        np.testing.assert_array_equal(seed[0], x)

    def test_empty_remainder_run_is_a_clean_noop(self, tmp_path):
        """A single-element grid must run ZERO windows: state out equals
        state in, nothing dumped, nothing checkpointed — the invariant
        the serve warm_noop path leans on."""
        from kafka_tpu.obsops import IdentityOperator
        from kafka_tpu.testing import MemoryOutput, SyntheticObservations

        mask = np.ones((4, 8), bool)
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        obs = SyntheticObservations(
            dates=[day(i) for i in (1, 3, 5)], operator=op,
            truth_fn=lambda d: np.full((4, 8, 2), 0.4, np.float32),
        )
        out = MemoryOutput()
        kf = KalmanFilter(obs, out, mask, ("a", "b"), pad_multiple=32)
        x0 = np.full((32, 2), 0.5, np.float32)
        p_inv0 = np.stack([np.eye(2, dtype=np.float32)] * 32)
        ck = Checkpointer(str(tmp_path / "ck"))
        x, _, p_inv = kf.run([day(6)], x0, None, p_inv0,
                             checkpointer=ck, advance_first=True)
        np.testing.assert_array_equal(np.asarray(x), x0)
        np.testing.assert_array_equal(np.asarray(p_inv), p_inv0)
        assert out.output == {}
        assert ck.list_checkpoints() == []


# ---------------------------------------------------------------------------
# checkpoint-set integrity guard (multi-shard corruption falls back)
# ---------------------------------------------------------------------------

class TestShardedCheckpointIntegrity:
    def _save_two(self, folder, n_shards=3, n=12, p=2):
        ck = Checkpointer(str(folder), n_shards=n_shards)
        states = {}
        for i, ts in enumerate([day(1), day(2)]):
            x = np.full((n, p), 0.1 * (i + 1), np.float32)
            pinv = np.stack([np.eye(p, dtype=np.float32)] * n)
            ck.save(ts, x, pinv)
            states[ts] = x
        return ck, states

    def test_missing_shard_falls_back_with_event(self, tmp_path):
        ck, states = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1]
        os.remove(newest[1][1])  # shard 1 of the day-2 set vanishes
        with telemetry.use(MetricsRegistry()) as reg:
            ts, x, _ = ck.load_latest()
            assert reg.value("kafka_checkpoint_unreadable_total") == 1
            events = [e for e in reg.events
                      if e["event"] == "checkpoint_unreadable"]
            assert events and "incomplete" in events[0]["error"]
        assert ts == day(1)
        np.testing.assert_array_equal(x, states[day(1)])

    def test_short_shard_falls_back(self, tmp_path):
        ck, states = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1]
        with open(newest[1][2], "r+b") as f:
            f.truncate(30)  # torn shard write
        with telemetry.use(MetricsRegistry()) as reg:
            ts, x, _ = ck.load_latest()
            assert reg.value("kafka_checkpoint_unreadable_total") == 1
        assert ts == day(1)
        np.testing.assert_array_equal(x, states[day(1)])

    def test_inconsistent_shard_width_falls_back(self, tmp_path):
        ck, states = self._save_two(tmp_path)
        newest = ck.list_checkpoints()[-1]
        # Overwrite shard 0 with a different state width — a foreign
        # file that must read as corrupt, not silently concatenate.
        np.savez_compressed(
            newest[1][0].removesuffix(".npz"),
            x_analysis=np.zeros((4, 5), np.float32),
            p_inv_tril=np.zeros((4, 15), np.float32), p=np.int64(5),
        )
        with telemetry.use(MetricsRegistry()):
            ts, x, _ = ck.load_latest()
        assert ts == day(1)
        np.testing.assert_array_equal(x, states[day(1)])

    def test_resume_time_grid_skips_incomplete_newest(self, tmp_path):
        ck, _ = self._save_two(tmp_path)
        os.remove(ck.list_checkpoints()[-1][1][0])
        with telemetry.use(MetricsRegistry()):
            grid, seed = ck.resume_time_grid(
                [day(0), day(1), day(2), day(3)]
            )
        assert grid == [day(1), day(2), day(3)] and seed is not None


# ---------------------------------------------------------------------------
# service mechanics (stub sessions: no JAX on these paths)
# ---------------------------------------------------------------------------

class TestServiceMechanics:
    def test_ok_flow_and_result_cache(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            svc, sess = stub_service(tmp_path)
            svc.start()
            try:
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r1"})
                r1 = svc.result("r1", timeout_s=30)
                assert r1["status"] == "ok" and "latency_ms" in r1
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r2"})
                r2 = svc.result("r2", timeout_s=30)
                assert r2["served_from"] == "cache"
                assert sess.serves == 1
                assert reg.value("kafka_serve_cache_hits_total") == 1
            finally:
                svc.close()

    def test_rejections_are_answered_and_counted(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            svc, _ = stub_service(tmp_path)
            svc.start()
            try:
                ack = svc.submit({"tile": "nope", "date": "2017-07-05",
                                  "request_id": "ru"})
                assert ack == {"request_id": "ru", "status": "rejected",
                               "reason": "unknown_tile"}
                # The rejection is a RESPONSE, visible cross-process.
                assert svc.journal.response("ru")["status"] == "rejected"
                bad = svc.submit({"tile": "t", "request_id": "rb"})
                assert bad["reason"] == "bad_request"
                assert reg.value("kafka_serve_rejected_total",
                                 reason="unknown_tile") == 1
                assert reg.value("kafka_serve_rejected_total",
                                 reason="bad_request") == 1
            finally:
                svc.close()

    def test_poison_solve_answers_error_and_daemon_survives(
            self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            svc, sess = stub_service(tmp_path)
            svc.start()
            try:
                faults.script("serve.solve", "1", POISON)
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r1"})
                r1 = svc.result("r1", timeout_s=30)
                assert r1["status"] == "error"
                assert "InjectedFault" in r1["error"]
                assert reg.value("kafka_serve_errors_total") == 1
                # The worker survives poison; the next request is fine.
                svc.submit({"tile": "t", "date": "2017-07-07",
                            "request_id": "r2"})
                assert svc.result("r2", timeout_s=30)["status"] == "ok"
            finally:
                svc.close()

    def test_transient_solve_fault_retried_in_place(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            svc, sess = stub_service(tmp_path)
            svc.start()
            try:
                faults.script("serve.solve", "1")  # transient
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r1"})
                assert svc.result("r1", timeout_s=30)["status"] == "ok"
                assert reg.value("kafka_resilience_retries_total",
                                 site="serve.solve") == 1
            finally:
                svc.close()

    def test_admit_fault_sheds_not_crashes(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            svc, _ = stub_service(tmp_path)
            svc.start()
            try:
                faults.script("serve.admit", "1")
                ack = svc.submit({"tile": "t", "date": "2017-07-05",
                                  "request_id": "r1"})
                assert ack["status"] == "rejected"
                assert ack["reason"] == "admit_error"
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r2"})
                assert svc.result("r2", timeout_s=30)["status"] == "ok"
            finally:
                svc.close()

    def test_transient_respond_fault_retried(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            svc, _ = stub_service(tmp_path)
            svc.start()
            try:
                faults.script("serve.respond", "1")  # transient
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r1"})
                assert svc.result("r1", timeout_s=30)["status"] == "ok"
            finally:
                svc.close()

    def test_lost_response_recovered_by_replay(self, tmp_path):
        """serve.respond poison: the answer is lost but counted; because
        no response file exists, a restart's journal replay re-serves
        the request — the crash-between-solve-and-respond path."""
        with telemetry.use(MetricsRegistry()) as reg:
            svc, sess = stub_service(tmp_path)
            svc.start()
            try:
                faults.script("serve.respond", "1", POISON)
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "r1"})
                deadline = time.monotonic() + 30
                while reg.value("kafka_serve_respond_errors_total") \
                        is None and time.monotonic() < deadline:
                    time.sleep(0.01)
                assert reg.value("kafka_serve_respond_errors_total") == 1
                assert svc.journal.response("r1") is None
            finally:
                svc.close()
            faults.reset()
            # "Restart": a fresh service over the same root replays r1.
            svc2, sess2 = stub_service(tmp_path)
            svc2.start()
            try:
                r1 = svc2.result("r1", timeout_s=30)
                assert r1 is not None and r1["status"] == "ok"
                assert sess2.serves == 1
                assert reg.value("kafka_serve_replayed_total") == 1
            finally:
                svc2.close()

    def test_expired_deadline_cancelled_and_counted(self, tmp_path):
        """A request whose wall-clock budget ran out before its turn is
        CANCELLED — counted and answered, never silently dropped."""
        with telemetry.use(MetricsRegistry()) as reg:
            gate = threading.Event()
            svc, sess = stub_service(tmp_path, block=gate)
            svc.start()
            try:
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "slow"})
                assert sess.started.wait(10.0)
                svc.submit({"tile": "t", "date": "2017-07-07",
                            "request_id": "doomed", "deadline_s": 0.01})
                time.sleep(0.05)  # let the deadline lapse in the queue
                gate.set()
                doomed = svc.result("doomed", timeout_s=30)
                assert doomed["status"] == "cancelled"
                assert doomed["reason"] == "deadline"
                assert reg.value("kafka_serve_cancelled_total") == 1
                assert svc.result("slow", timeout_s=30)["status"] == "ok"
            finally:
                gate.set()
                svc.close()

    def test_drain_rejects_new_finishes_admitted(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            gate = threading.Event()
            svc, sess = stub_service(tmp_path, block=gate)
            svc.start()
            try:
                svc.submit({"tile": "t", "date": "2017-07-05",
                            "request_id": "inflight"})
                assert sess.started.wait(10.0)
                svc.submit({"tile": "t", "date": "2017-07-07",
                            "request_id": "queued"})
                svc.stop_admitting()
                late = svc.submit({"tile": "t", "date": "2017-07-09",
                                   "request_id": "late"})
                assert late["reason"] == "draining"
                gate.set()
                assert svc.drain(timeout_s=30)
                assert svc.journal.response("inflight")["status"] == "ok"
                assert svc.journal.response("queued")["status"] == "ok"
                assert svc.journal.response("late")["status"] == \
                    "rejected"
            finally:
                gate.set()
                svc.close()


# ---------------------------------------------------------------------------
# chaos (a): overload sheds with counted rejections, admitted complete
# ---------------------------------------------------------------------------

class TestOverloadShedding:
    def test_overload_sheds_admitted_all_complete(self, tmp_path):
        """Deterministic overload: the worker is held on a gate, the
        queue bound is 2, and a burst of 8 arrives — exactly 1 in-flight
        + 2 queued are admitted, 5 shed with counted ``queue_full``
        rejections, and every admitted request completes once the gate
        opens."""
        with telemetry.use(MetricsRegistry()) as reg:
            gate = threading.Event()
            svc, sess = stub_service(tmp_path, block=gate, max_queue=2)
            svc.start()
            try:
                acks = {}
                for i in range(8):
                    rid = f"r{i}"
                    acks[rid] = svc.submit({
                        "tile": "t", "date": "2017-07-05",
                        "request_id": rid,
                    })
                    if i == 0:
                        assert sess.started.wait(10.0)
                queued = [r for r, a in acks.items()
                          if a["status"] == "queued"]
                shed = [r for r, a in acks.items()
                        if a["status"] == "rejected"]
                assert len(queued) == 3 and len(shed) == 5
                assert all(acks[r]["reason"] == "queue_full"
                           for r in shed)
                assert reg.value("kafka_serve_rejected_total",
                                 reason="queue_full") == 5
                # Shed requests were ANSWERED (fast rejection), not
                # silently dropped.
                for rid in shed:
                    assert svc.journal.response(rid)["status"] == \
                        "rejected"
                gate.set()
                for rid in queued:
                    got = svc.result(rid, timeout_s=30)
                    assert got is not None and got["status"] == "ok"
                assert reg.value("kafka_serve_admitted_total") == 3
            finally:
                gate.set()
                svc.close()


# ---------------------------------------------------------------------------
# telemetry growth bounds for a long-lived process
# ---------------------------------------------------------------------------

class TestTelemetryGrowthBounds:
    def test_events_jsonl_rotates_size_capped_keep_n(self, tmp_path):
        reg = MetricsRegistry(str(tmp_path), events_rotate_bytes=600,
                              events_keep=2)
        for i in range(100):
            reg.emit("filler", i=i, pad="x" * 40)
        reg.close()
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith("events.jsonl"))
        assert "events.jsonl" in names
        assert "events.jsonl.1" in names and "events.jsonl.2" in names
        assert "events.jsonl.3" not in names  # keep-N enforced
        # Segments stay line-whole (rotation never tears a record).
        for n in names:
            with open(tmp_path / n) as f:
                for line in f:
                    assert json.loads(line)["event"] == "filler"
        # Total on-disk telemetry is bounded near cap * (keep + 1).
        total = sum(os.path.getsize(tmp_path / n) for n in names)
        assert total < 600 * 4

    def test_no_rotation_below_cap(self, tmp_path):
        reg = MetricsRegistry(str(tmp_path))
        for i in range(50):
            reg.emit("filler", i=i)
        reg.close()
        assert sorted(
            n for n in os.listdir(tmp_path) if "events" in n
        ) == ["events.jsonl"]

    def test_crash_dumps_are_capped(self, tmp_path, monkeypatch):
        from kafka_tpu.telemetry.flight_recorder import FlightRecorder

        monkeypatch.setattr(FlightRecorder, "MAX_CRASH_DUMPS", 2)
        for i in range(4):
            (tmp_path / f"crash_2020010{i}T000000_1.json").write_text(
                "{}"
            )
        with telemetry.use(MetricsRegistry()):
            rec = FlightRecorder(str(tmp_path))
            path = rec.dump("unhealthy_probe")
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith("crash_"))
        assert len(names) == 2
        assert os.path.basename(path) in names  # newest survive
        assert "crash_20200100T000000_1.json" not in names


# ---------------------------------------------------------------------------
# loadgen (in-process mode) — the serving rows
# ---------------------------------------------------------------------------

class TestLoadgen:
    def test_bench_serve_rows(self, tmp_path):
        from tools.loadgen import bench_serve

        with telemetry.use(MetricsRegistry()):
            rows = bench_serve(str(tmp_path), requests=6, concurrency=2)
        assert rows["serve_ok_total"] == 6
        assert rows["serve_error_total"] == 0
        assert rows["serve_p50_ms"] > 0
        assert rows["serve_p99_ms"] >= rows["serve_p50_ms"]
        assert rows["serve_cold_ms"] > 0
        assert rows["serve_rejected_total"] == 0
        # ISSUE 10 satellite: the bench scrapes its own ephemeral
        # /metrics endpoint mid-run and embeds the serve series.
        lt = rows["live_telemetry"]
        assert lt["samples"] >= 1 and lt["scrape_errors"] == 0
        assert lt["scrape_url"].endswith("/metrics")
        assert any(k.startswith("kafka_serve_") for k in lt["series"])

    def test_rejections_counted_not_waited(self, tmp_path):
        from tools.loadgen import _Target, run_load

        with telemetry.use(MetricsRegistry()):
            gate = threading.Event()
            svc, _ = stub_service(tmp_path, block=gate, max_queue=1)
            svc.start()
            try:
                plan = [{"tile": "t", "date": "2017-07-05"}
                        for _ in range(6)]
                done = {}

                def release():
                    gate.set()

                t = threading.Timer(0.5, release)
                t.start()
                rows = run_load(_Target(service=svc), plan,
                                concurrency=6, timeout_s=60)
                t.cancel()
                assert rows["serve_requests_total"] == 6
                assert rows["serve_rejected_total"] >= 1
                assert rows["serve_ok_total"] + \
                    rows["serve_rejected_total"] == 6
            finally:
                gate.set()
                svc.close()


# ---------------------------------------------------------------------------
# the daemon: filesystem transport + idle exit + crash recovery
# ---------------------------------------------------------------------------

class TestDaemonInProcess:
    def test_inbox_roundtrip_and_idle_exit(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            root = str(tmp_path)
            svc, sess = stub_service(tmp_path)
            rid = submit_request(root, {"tile": "t",
                                        "date": "2017-07-05"})
            # Unparseable inbox files are dropped with an event, never a
            # crashed daemon.
            with open(os.path.join(root, "inbox", "garbage.json"),
                      "w") as f:
                f.write("{not json")
            daemon = ServeDaemon(svc, root, poll_interval_s=0.01,
                                 exit_when_idle=True, idle_grace_s=0.1)
            summary = daemon.run()
            assert summary["admitted"] == 1
            got = read_response(root, rid)
            assert got is not None and got["status"] == "ok"
            assert os.listdir(os.path.join(root, "inbox")) == []


def _daemon_cmd(root, extra=()):
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_serve",
        "--root", str(root), "--tiles", "2", "--operator", "identity",
        "--ny", "16", "--nx", "20", "--days", "40", "--step", "2",
        "--obs-every", "2", "--poll-interval-s", "0.02", *extra,
    ]


def _daemon_dates():
    return synthetic_dates(DEFAULT_BASE_DATE, 40, 2)


def _reference_checksum(tmp_path, date, tile_seed=0):
    """The uninterrupted run's answer for ``date`` (same spec as the
    daemon's tile0), computed in-process."""
    sess = TileSession(make_synthetic_tile(
        "tile0", str(tmp_path / "ck_ref"), operator="identity",
        ny=16, nx=20, days=40, step_days=2, obs_every=2, seed=tile_seed,
    ))
    return sess.serve(date)["x_sha256"]


class TestDaemonChaos:
    def test_chaos_b_sigkill_midrequest_restart_replays_identically(
            self, tmp_path):
        """(b) SIGKILL mid-request, restart: the journal replays the
        interrupted request, the tile resumes from the warm checkpoint
        (not a cold rerun), and the replayed output matches the
        uninterrupted run bit-for-bit."""
        root = tmp_path / "serve"
        root.mkdir()
        date = _daemon_dates()[-1]
        victim = subprocess.Popen(
            _daemon_cmd(root), env=_subprocess_env(), cwd=REPO_ROOT,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        try:
            rid = submit_request(str(root), {
                "tile": "tile0", "date": date.isoformat(),
                "request_id": "victimreq",
            })
            ck_dir = root / "ckpt_tile0"
            deadline = time.time() + 180
            while time.time() < deadline:
                if victim.poll() is not None:
                    pytest.fail(f"daemon exited rc={victim.returncode} "
                                "before it could be killed")
                # Kill as soon as warm state exists but the response
                # does not: mid-request, checkpoints on disk.
                if read_response(str(root), rid) is not None:
                    pytest.fail("daemon answered before the kill — "
                                "widen the request")
                if ck_dir.is_dir() and any(
                        n.endswith(".npz") for n in os.listdir(ck_dir)):
                    break
                time.sleep(0.002)
            else:
                pytest.fail("daemon never checkpointed")
            victim.kill()
            victim.wait(timeout=30)
        finally:
            if victim.poll() is None:
                victim.kill()
        assert read_response(str(root), rid) is None

        # Restart: replay the journal, serve, exit when idle.
        restarted = subprocess.run(
            _daemon_cmd(root, extra=["--exit-when-idle",
                                     "--idle-grace-s", "0.3"]),
            env=_subprocess_env(), cwd=REPO_ROOT, capture_output=True,
            text=True, timeout=600,
        )
        assert restarted.returncode == 0, restarted.stderr[-2000:]
        summary = json.loads(
            restarted.stdout.strip().splitlines()[-1])
        assert summary["replayed"] == 1 and summary["errors"] == 0
        got = read_response(str(root), rid)
        assert got is not None and got["status"] == "ok"
        # Resumed warm, not recomputed from scratch...
        assert got["served_from"] in ("warm", "warm_noop")
        # ...and the answer equals the uninterrupted run's, exactly.
        assert got["x_sha256"] == _reference_checksum(tmp_path, date)

    def test_chaos_c_sigterm_drains_finishes_inflight_rejects_new(
            self, tmp_path):
        """(c) SIGTERM: admitted requests (in-flight AND queued) finish,
        a latecomer is answered ``rejected: draining``, exit 0."""
        root = tmp_path / "serve"
        root.mkdir()
        dates = _daemon_dates()
        daemon = subprocess.Popen(
            _daemon_cmd(root), env=_subprocess_env(), cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        try:
            r1 = submit_request(str(root), {
                "tile": "tile0", "date": dates[-1].isoformat()})
            r2 = submit_request(str(root), {
                "tile": "tile1", "date": dates[-1].isoformat()})
            journal = root / "requests.jsonl"
            deadline = time.time() + 180
            while time.time() < deadline:
                if daemon.poll() is not None:
                    pytest.fail(f"daemon exited rc={daemon.returncode} "
                                "before SIGTERM")
                text = journal.read_text() if journal.exists() else ""
                if r1 in text and r2 in text and \
                        read_response(str(root), r2) is None:
                    break
                time.sleep(0.002)
            else:
                pytest.fail("daemon never admitted both requests")
            daemon.send_signal(signal.SIGTERM)
            # New work during the drain window gets an explicit
            # rejection, not silence.
            r3 = submit_request(str(root), {
                "tile": "tile0", "date": dates[0].isoformat()})
            out, _ = daemon.communicate(timeout=600)
        finally:
            if daemon.poll() is None:
                daemon.kill()
        assert daemon.returncode == 0
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["drained"] is True
        for rid in (r1, r2):
            got = read_response(str(root), rid)
            assert got is not None and got["status"] == "ok", rid
        got3 = read_response(str(root), r3)
        assert got3 is not None and got3["status"] == "rejected"
        assert got3["reason"] == "draining"


# ---------------------------------------------------------------------------
# solve-health in responses (ISSUE 9 satellite): result quality, not
# just latency
# ---------------------------------------------------------------------------

class TestServeSolverHealth:
    def test_response_carries_solver_health_counts(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            spec = make_synthetic_tile(
                "t", ckpt_dir=str(tmp_path / "ckpt"), seed=0
            )
            sess = TileSession(spec)
            body = sess.serve(DATES[2])
        health = body["solver_health"]
        assert set(health) == {
            "quarantined", "cap_bailouts", "damped_recovered",
            "nonfinite",
        }
        assert all(isinstance(v, int) for v in health.values())
        # a clean synthetic tile converges everywhere
        assert health["quarantined"] == 0
        assert health["nonfinite"] == 0

    def test_warm_noop_serve_reports_zero_health(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            spec = make_synthetic_tile(
                "t", ckpt_dir=str(tmp_path / "ckpt"), seed=0
            )
            sess = TileSession(spec)
            sess.serve(DATES[2])
            body = sess.serve(DATES[2])  # zero windows re-run
        assert body["served_from"] == "warm_noop"
        assert body["solver_health"]["quarantined"] == 0

    def test_quarantined_pixels_reach_response_and_loadgen(self,
                                                          tmp_path):
        """solver.pixel chaos through the whole serving stack: the
        armed pixels' quarantine count lands in the response body, the
        journal's persisted response, and the loadgen quality rows."""
        from tools.loadgen import _Target, run_load

        faults.script("solver.pixel", "0-2")
        with telemetry.use(MetricsRegistry()):
            spec = make_synthetic_tile(
                "t", ckpt_dir=str(tmp_path / "ckpt"), seed=0
            )
            svc = AssimilationService(
                {"t": TileSession(spec)}, str(tmp_path)
            ).start()
            try:
                rows = run_load(
                    _Target(service=svc),
                    [{"tile": "t", "date": DATES[2].isoformat(),
                      "request_id": "rq0"}],
                    concurrency=1, timeout_s=120,
                )
                got = read_response(str(tmp_path), "rq0")
            finally:
                svc.close()
        assert rows["serve_ok_total"] == 1
        assert got["solver_health"]["quarantined"] > 0
        assert rows["serve_quarantined_pixels"] == \
            got["solver_health"]["quarantined"]
