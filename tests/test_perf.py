"""Performance observability (ISSUE 12): always-on attribution gauges,
live roofline utilization, on-demand profiler capture, and the
bench-history trend ledger.

Acceptance: a CPU ``run_synthetic`` run publishes live
``kafka_perf_px_steps_per_s``, device-fraction and roofline-utilization
gauges visible via ``/metrics`` and ``fleet_status``, with
``kafka_engine_device_reads_total == dispatches`` still asserted;
``tools/bench_history.py`` over the checked-in BENCH_r01-r05 renders a
per-row trend table that flags the e2e rows unjudgeable by spread.
"""

import datetime
import json
import os
import sys
import urllib.request

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from kafka_tpu import telemetry  # noqa: E402
from kafka_tpu.telemetry import MetricsRegistry, perf  # noqa: E402

from tools import bench_history  # noqa: E402


def day(i):
    return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)


def run_identity_engine(telemetry_dir=None, scan_window=1,
                        prefetch_depth=2):
    """Small identity-operator run: 8 observation dates, 5 grid windows.
    Returns ``(kf, out, reg)`` — the shared engine harness shape of
    tests/test_quality.py."""
    import jax.numpy as jnp

    from kafka_tpu.core.propagators import (
        PixelPrior, propagate_information_filter_approx,
    )
    from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
    from kafka_tpu.obsops.identity import IdentityOperator
    from kafka_tpu.testing.fixtures import make_pivot_mask
    from kafka_tpu.testing.synthetic import (
        MemoryOutput, SyntheticObservations,
    )

    mask = make_pivot_mask(20, 20, seed=0)
    p = 2
    op = IdentityOperator(n_params=p, obs_indices=(0, 1))
    cov = np.diag(np.full(p, 0.4 ** 2)).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.full((p,), 0.5, jnp.float32),
            cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        ("a", "b"),
    )
    truth = np.broadcast_to(
        np.array([0.3, 0.7], np.float32), mask.shape + (2,)
    ).astype(np.float32)
    with telemetry.use(MetricsRegistry(telemetry_dir)) as reg:
        obs = SyntheticObservations(
            dates=[day(i) for i in range(1, 16, 2)], operator=op,
            truth_fn=lambda d: truth, sigma=0.02, mask_prob=0.1, seed=0,
        )
        out = MemoryOutput()
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter_approx,
            prior=None, solver_options={"relaxation": 0.5},
            scan_window=scan_window, prefetch_depth=prefetch_depth,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.full(p, 1e-3, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        kf.run([day(i) for i in range(0, 20, 4)], x0, None, p_inv0)
    return kf, out, reg


# ---------------------------------------------------------------------------
# Analytic traffic bounds: one derivation, shared by the runtime gauge
# and tools/roofline.py.
# ---------------------------------------------------------------------------

class TestTrafficBounds:
    def test_bounds_positive_and_linear_in_pixels(self):
        for fn in (perf.min_traffic_linearize, perf.min_traffic_update,
                   perf.min_traffic_gn_full,
                   perf.min_traffic_gn_inkernel):
            a = fn(1000, 7, 2)
            b = fn(2000, 7, 2)
            assert a > 0 and b == 2 * a

    def test_roofline_tool_imports_the_same_bounds(self):
        """tools/roofline.py must derive its table from THESE formulas —
        a drifted copy would make the live gauge and the tool disagree
        about the same kernel."""
        from tools import roofline

        assert roofline.min_traffic_gn_full is perf.min_traffic_gn_full
        assert roofline.min_traffic_gn_inkernel is \
            perf.min_traffic_gn_inkernel
        assert roofline.HBM_GBPS == perf.HBM_GBPS

    def test_component_mapping_follows_solver_options(self):
        assert perf.component_for(None) == "gn_full"
        assert perf.component_for({}) == "gn_full"
        assert perf.component_for({"use_pallas": True}) == \
            "gn_full_pallas"
        assert perf.component_for(
            {"use_pallas": True, "inkernel_linearize": True}
        ) == "gn_inkernel"

    def test_utilization_is_bound_over_traffic_time(self):
        u = perf.roofline_utilization("gn_full", 1 << 19, 7, 2, 0.0038)
        expected = perf.min_traffic_gn_full(1 << 19, 7, 2) / (
            0.0038 * perf.HBM_GBPS * 1e9
        )
        assert u == pytest.approx(expected)
        assert perf.roofline_utilization("gn_full", 10, 7, 2, 0.0) is None


# ---------------------------------------------------------------------------
# Always-on attribution through the real engine.
# ---------------------------------------------------------------------------

class TestAttribution:
    @pytest.mark.parametrize("scan_window", [1, 4])
    def test_engine_publishes_perf_gauges(self, scan_window):
        kf, _, reg = run_identity_engine(scan_window=scan_window)
        assert kf.diagnostics_log, "no windows assimilated"
        rate = reg.value("kafka_perf_px_steps_per_s")
        frac = reg.value("kafka_perf_device_fraction")
        assert rate is not None and rate > 0
        # The acceptance band: device fraction in (0, 1], computed from
        # the same wall_s sums bench.py's e2e row uses.
        assert frac is not None and 0 < frac <= 1.0
        util = reg.value(
            "kafka_perf_roofline_utilization", component="gn_full"
        )
        assert util is not None and util > 0
        solve_frac = reg.value(
            "kafka_perf_phase_fraction", phase="solve"
        )
        assert solve_frac is not None and 0 < solve_frac <= 1.0
        for phase in ("fetch", "advance", "dump", "write"):
            assert reg.value(
                "kafka_perf_phase_fraction", phase=phase
            ) is not None

    def test_device_reads_invariant_with_attribution_active(self):
        """THE invariant, re-asserted with perf sampling on: attribution
        derives from the record the one packed read built — reads ==
        dispatches, fused and unfused."""
        for scan_window in (1, 4):
            kf, _, reg = run_identity_engine(scan_window=scan_window)
            expected = sum(
                1.0 / rec.get("fused", 1) for rec in kf.diagnostics_log
            )
            assert expected == int(expected)
            assert reg.value("kafka_engine_device_reads_total") == \
                int(expected)
            # ... and the gauges were indeed published on this run.
            assert reg.value("kafka_perf_px_steps_per_s") > 0

    def test_device_fraction_consistent_with_bench_e2e_arithmetic(self):
        """The live gauge is the same quantity bench_end_to_end derives:
        sum of the diagnostics log's wall_s over elapsed wall — the
        cumulative gauge must not exceed that sum's share by more than
        rolling-window effects allow (it is a fraction of REAL time, so
        never above 1)."""
        kf, _, reg = run_identity_engine()
        device_s = sum(r["wall_s"] for r in kf.diagnostics_log)
        assert device_s > 0
        assert 0 < reg.value("kafka_perf_device_fraction") <= 1.0

    def test_summary_shape(self):
        _, _, reg = run_identity_engine()
        s = perf.summary(reg)
        assert set(s) == {
            "px_steps_per_s", "device_fraction",
            "roofline_utilization", "phases",
        }
        assert "gn_full" in s["roofline_utilization"]
        assert "solve" in s["phases"]
        empty = perf.summary(MetricsRegistry())
        assert empty["px_steps_per_s"] is None
        assert empty["roofline_utilization"] == {}


# ---------------------------------------------------------------------------
# Profiler capture: programmatic, one at a time, off-TPU safe.
# ---------------------------------------------------------------------------

@pytest.fixture
def stub_profiler(monkeypatch, tmp_path):
    """Replace the jax.profiler seam with a marker-file stub: capture
    MECHANICS (locking, windowed ticks, endpoint plumbing) test
    deterministically — a real stop_trace grows slow late in a long
    jax session and real captures are covered once, directly."""
    def fake_start(directory):
        os.makedirs(directory, exist_ok=True)
        open(os.path.join(directory, "capture.marker"), "w").close()

    monkeypatch.setattr(perf, "_start_trace", fake_start)
    monkeypatch.setattr(perf, "_stop_trace", lambda: None)
    return tmp_path


class TestProfilerCapture:
    def test_real_capture_writes_or_degrades_cleanly(self, tmp_path):
        """The ONE real-profiler test: the programmatic capture either
        materialises a dump directory or raises the clean
        CaptureUnavailable — never a crash (the off-TPU acceptance)."""
        reg = MetricsRegistry()
        d = str(tmp_path / "profile")
        try:
            result = perf.capture(0.1, d, registry=reg)
        except perf.CaptureUnavailable:
            assert not perf._capture_lock.locked()
            return  # profiler genuinely absent here — the clean path
        assert result["directory"] == d
        assert os.path.isdir(d)
        assert reg.value("kafka_perf_profile_captures_total") == 1
        # The lock was released: nothing holds the one-capture slot.
        assert not perf._capture_lock.locked()

    def test_one_capture_at_a_time(self, stub_profiler):
        tmp_path = stub_profiler
        reg = MetricsRegistry()
        perf.start_windowed_capture(5, str(tmp_path / "w"), registry=reg)
        try:
            with pytest.raises(perf.CaptureBusy):
                perf.capture(0.05, str(tmp_path / "p"), registry=reg)
        finally:
            assert perf.stop_windowed_capture(registry=reg) is not None
        # Idempotent stop; lock released.
        assert perf.stop_windowed_capture(registry=reg) is None
        perf.capture(0.05, str(tmp_path / "p2"), registry=reg)
        assert not perf._capture_lock.locked()

    def test_windowed_capture_stops_after_n_windows(self, stub_profiler):
        tmp_path = stub_profiler
        reg = MetricsRegistry()
        rec = {"wall_s": 0.001, "chi2_per_band": [1.0]}
        perf.start_windowed_capture(2, str(tmp_path / "w"), registry=reg)
        try:
            for _ in range(2):
                perf.record_window(
                    rec, n_valid=10, n_pad=16, n_params=2, n_bands=1,
                    registry=reg,
                )
            # The second window ticked the capture closed.
            assert perf._windowed["directory"] is None
            assert reg.value(
                "kafka_perf_profile_captures_total"
            ) == 1
        finally:
            perf.stop_windowed_capture(registry=reg)

    def test_unavailable_profiler_releases_the_slot(self, monkeypatch,
                                                    tmp_path):
        def refuse(directory):
            raise perf.CaptureUnavailable("no profiler here")

        monkeypatch.setattr(perf, "_start_trace", refuse)
        with pytest.raises(perf.CaptureUnavailable):
            perf.capture(0.05, str(tmp_path / "p"))
        with pytest.raises(perf.CaptureUnavailable):
            perf.start_windowed_capture(2, str(tmp_path / "w"))
        assert not perf._capture_lock.locked()


class TestProfilezEndpoint:
    def _get(self, url):
        try:
            with urllib.request.urlopen(url, timeout=30) as resp:
                return resp.status, resp.read().decode()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read().decode()

    def test_profilez_smoke_capture_file_appears(self, stub_profiler):
        """ISSUE 12 acceptance, 200 branch: the endpoint runs a capture
        into <telemetry dir>/profile/ and the capture file appears."""
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        tmp_path = stub_profiler
        reg = MetricsRegistry(str(tmp_path))
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(
                httpd.url + "/profilez?seconds=0.1"
            )
            payload = json.loads(body)
            assert code == 200, body
            assert payload["ok"] is True
            assert payload["directory"].startswith(
                os.path.join(str(tmp_path), "profile")
            )
            assert os.path.exists(
                os.path.join(payload["directory"], "capture.marker")
            )
            assert reg.value(
                "kafka_perf_profile_captures_total"
            ) == 1
        finally:
            httpd.close()
            reg.close()

    def test_profilez_unavailable_profiler_is_clean_503(
            self, monkeypatch, tmp_path):
        """ISSUE 12 acceptance, 503 branch: where the profiler cannot
        run (off-TPU stripped builds), the endpoint answers a clean 503
        — the run being observed never crashes."""
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        def refuse(directory):
            raise perf.CaptureUnavailable("no profiler here")

        monkeypatch.setattr(perf, "_start_trace", refuse)
        reg = MetricsRegistry(str(tmp_path))
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/profilez?seconds=0.1")
            assert code == 503
            assert "profiler" in json.loads(body)["error"]
        finally:
            httpd.close()
            reg.close()

    def test_profilez_busy_is_409(self, stub_profiler):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        tmp_path = stub_profiler
        reg = MetricsRegistry(str(tmp_path))
        perf.start_windowed_capture(5, str(tmp_path / "w"), registry=reg)
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/profilez?seconds=0.1")
            assert code == 409
            assert "already running" in json.loads(body)["error"]
        finally:
            httpd.close()
            perf.stop_windowed_capture(registry=reg)
            reg.close()

    def test_profilez_without_telemetry_dir_is_503(self):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        reg = MetricsRegistry()  # no directory
        httpd = TelemetryHTTPd(port=0, registry=reg).start()
        try:
            code, body = self._get(httpd.url + "/profilez")
            assert code == 503
            assert "telemetry" in json.loads(body)["error"]
        finally:
            httpd.close()

    def test_statusz_and_index_carry_perf(self, tmp_path):
        from kafka_tpu.telemetry.httpd import TelemetryHTTPd

        with telemetry.use(MetricsRegistry()) as reg:
            rec = {"wall_s": 0.002, "chi2_per_band": [1.0]}
            perf.record_window(
                rec, n_valid=10, n_pad=16, n_params=2, n_bands=1,
                registry=reg,
            )
            httpd = TelemetryHTTPd(port=0, registry=reg).start()
            try:
                code, body = self._get(httpd.url + "/statusz")
                assert code == 200
                status = json.loads(body)
                assert status["perf"]["px_steps_per_s"] > 0
                code, body = self._get(httpd.url + "/")
                assert "/profilez" in json.loads(body)["endpoints"]
            finally:
                httpd.close()


# ---------------------------------------------------------------------------
# Acceptance: the CPU driver run publishes live perf gauges end to end.
# ---------------------------------------------------------------------------

class TestRunSyntheticLive:
    def test_driver_publishes_perf_plane(self, tmp_path):
        from kafka_tpu.telemetry import get_registry, set_registry
        from kafka_tpu.cli.run_synthetic import main
        from tools.fleet_status import build_view

        tel = str(tmp_path / "tel")
        prev = get_registry()
        try:
            summary = main([
                "--operator", "identity", "--ny", "40", "--nx", "40",
                "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
            ])
            reg = get_registry()
            rate = reg.value("kafka_perf_px_steps_per_s")
            frac = reg.value("kafka_perf_device_fraction")
            assert rate is not None and rate > 0
            assert frac is not None and 0 < frac <= 1.0
            assert reg.value(
                "kafka_perf_roofline_utilization", component="gn_full"
            ) > 0
            # /metrics surface: the exposition the endpoint serves and
            # metrics.prom archives carries the gauges.
            prom = open(os.path.join(tel, "metrics.prom")).read()
            assert "kafka_perf_px_steps_per_s" in prom
            assert "kafka_perf_device_fraction" in prom
            assert 'kafka_perf_roofline_utilization{' \
                'component="gn_full"}' in prom
            # The packed-read funnel was the diagnostic path (the exact
            # reads == dispatches equality is pinned in-engine by
            # TestAttribution; fusion makes dispatches < n_dates here).
            reads = reg.value("kafka_engine_device_reads_total")
            assert reads is not None and 0 < reads <= summary["n_dates"]
        finally:
            set_registry(prev)
        # Fleet surface: the live snapshot carried the perf summary and
        # fleet_status renders it per worker.
        snaps = [
            f for f in os.listdir(tel)
            if f.startswith("live_") and f.endswith(".json")
        ]
        assert snaps
        snap = json.load(open(os.path.join(tel, snaps[0])))
        assert snap["perf"]["px_steps_per_s"] > 0
        assert 0 < snap["perf"]["device_fraction"] <= 1.0
        view = build_view(tel)
        workers = [w for w in view["workers"] if w.get("perf")]
        assert workers
        assert workers[0]["perf"]["px_steps_per_s"] > 0
        from tools.fleet_status import render

        assert "perf=" in render(view)

    def test_profile_windows_flag(self, stub_profiler):
        """--profile-windows N: the driver starts a windowed capture
        into <telemetry-dir>/profile and the attribution path closes it
        after N windows (profiler seam stubbed — the flag's plumbing is
        under test, the real capture path has its own test)."""
        from kafka_tpu.telemetry import get_registry, set_registry
        from kafka_tpu.cli.run_synthetic import main

        tmp_path = stub_profiler
        tel = str(tmp_path / "tel")
        prev = get_registry()
        try:
            main([
                "--operator", "identity", "--ny", "24", "--nx", "24",
                "--days", "8", "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
                "--profile-windows", "2",
            ])
            reg = get_registry()
            assert reg.value(
                "kafka_perf_profile_captures_total"
            ) == 1
        finally:
            set_registry(prev)
            perf.stop_windowed_capture()
        assert os.path.exists(
            os.path.join(tel, "profile", "capture.marker")
        )


# ---------------------------------------------------------------------------
# bench_history: the multi-artifact trend ledger.
# ---------------------------------------------------------------------------

def _write(tmp_path, name, doc):
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


class TestBenchHistory:
    def test_unwrap_artifact(self):
        bare = {"metric": "x", "device_xla_ms": 6.4}
        assert bench_history.unwrap_artifact(bare) is bare
        wrapped = {"n": 3, "cmd": "python bench.py", "rc": 0,
                   "tail": "...", "parsed": bare}
        assert bench_history.unwrap_artifact(wrapped) == bare
        assert bench_history.unwrap_artifact(
            {"n": 1, "cmd": "x", "rc": 0, "tail": "", "parsed": None}
        ) == {}
        assert bench_history.unwrap_artifact([1, 2]) == {}

    def test_noisy_row_is_unjudgeable_and_trends_survive(self, tmp_path):
        paths = [
            _write(tmp_path, f"r{i}.json", doc) for i, doc in enumerate([
                {"e2e_pixel_steps_per_s": 74000.0,
                 "device_xla_ms": 7.1, "device_pallas_px_s": 1.0e8},
                {"e2e_pixel_steps_per_s": 36000.0,
                 "device_xla_ms": 6.6, "device_pallas_px_s": 9.0e7},
                {"e2e_pixel_steps_per_s": 73000.0,
                 "device_xla_ms": 6.5, "device_pallas_px_s": 7.0e7},
                {"e2e_pixel_steps_per_s": 44000.0,
                 "device_xla_ms": 5.2, "device_pallas_px_s": 6.0e7},
            ])
        ]
        hist = bench_history.build_history(paths)
        rows = hist["rows"]
        e2e = rows["e2e_pixel_steps_per_s"]
        assert e2e["verdict"] == "unjudgeable"
        assert "both directions" in e2e["reason"]
        # A monotone ms drop is improving (direction-aware) ...
        assert rows["device_xla_ms"]["verdict"] == "improving"
        # ... and a monotone px/s drop is regressing.
        assert rows["device_pallas_px_s"]["verdict"] == "regressing"

    def test_recorded_spread_flags_unjudgeable(self, tmp_path):
        paths = [
            _write(tmp_path, f"r{i}.json", {
                "oracle_ms_median": v, "oracle_ms_median_spread": s,
            })
            for i, (v, s) in enumerate([(700.0, 900.0), (660.0, 1900.0)])
        ]
        rows = bench_history.build_history(paths)["rows"]
        assert rows["oracle_ms_median"]["verdict"] == "unjudgeable"
        assert "spread" in rows["oracle_ms_median"]["reason"]

    def test_single_point_and_flat(self, tmp_path):
        paths = [
            _write(tmp_path, "a.json", {"serve_p99_ms": 20.0}),
        ]
        rows = bench_history.build_history(paths)["rows"]
        assert rows["serve_p99_ms"]["verdict"] == "single"
        paths.append(_write(tmp_path, "b.json", {"serve_p99_ms": 20.5}))
        rows = bench_history.build_history(paths)["rows"]
        assert rows["serve_p99_ms"]["verdict"] == "flat"

    def test_wrapped_and_bare_mix(self, tmp_path):
        paths = [
            _write(tmp_path, "w.json", {
                "n": 1, "cmd": "c", "rc": 0, "tail": "",
                "parsed": {"device_xla_ms": 6.4},
            }),
            _write(tmp_path, "b.json", {"device_xla_ms": 6.5}),
        ]
        hist = bench_history.build_history(paths)
        assert hist["n_artifacts"] == 2
        assert hist["rows"]["device_xla_ms"]["n"] == 2

    def test_cli_json_and_exit_codes(self, tmp_path, capsys):
        p = _write(tmp_path, "one.json", {"device_xla_ms": 6.4})
        assert bench_history.main([p, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["n_artifacts"] == 1
        assert payload["rows"]["device_xla_ms"]["verdict"] == "single"
        missing = str(tmp_path / "gone.json")
        assert bench_history.main([missing]) == 2

    def test_nulls_are_absent_rounds_not_zeros(self, tmp_path):
        paths = [
            _write(tmp_path, "a.json",
                   {"device_pallas_ms": None, "device_xla_ms": 6.4}),
            _write(tmp_path, "b.json",
                   {"device_pallas_ms": 3.8, "device_xla_ms": 6.5}),
        ]
        rows = bench_history.build_history(paths)["rows"]
        assert rows["device_pallas_ms"]["verdict"] == "single"
        assert rows["device_pallas_ms"]["rounds"] == [1]


class TestBenchHistoryCheckedInArtifacts:
    """CI satellite: the repo's own bench trajectory is a regression-
    tested artifact — bench_history must parse all five archived rounds
    (wrapper format) and render a trend, flagging the e2e row
    unjudgeable by spread."""

    PATHS = [
        os.path.join(REPO_ROOT, f"BENCH_r0{i}.json") for i in range(1, 6)
    ]

    def test_all_five_rounds_parse_and_render(self, capsys):
        assert bench_history.main(self.PATHS) == 0
        out = capsys.readouterr().out
        assert "5 artifact(s)" in out
        for i in range(1, 6):
            assert f"BENCH_r0{i}.json" in out

    def test_e2e_row_flagged_unjudgeable(self):
        hist = bench_history.build_history(self.PATHS)
        assert hist["n_artifacts"] == 5
        # Every archived round is wrapper format and yields real rows
        # (r01 predates most rows but carries the headline value).
        assert all(m["rows"] >= 1 for m in hist["artifacts"])
        e2e = hist["rows"]["e2e_pixel_steps_per_s"]
        assert e2e["verdict"] == "unjudgeable"
        assert e2e["n"] == 4  # r02-r05 carry the row
        # The headline throughput row is NOT drowned by its r01->r02
        # improvement staircase: one-directional moves stay judgeable.
        assert hist["rows"]["value"]["verdict"] in (
            "flat", "improving"
        )

    def test_bench_compare_reads_wrapped_artifacts(self, capsys):
        """Satellite: bench_compare unwraps the archive format — two
        checked-in rounds compare on their real content instead of
        finding the wrapper row-less."""
        from tools import bench_compare

        rc = bench_compare.main([self.PATHS[3], self.PATHS[4]])
        out = capsys.readouterr().out
        assert rc == 0
        # The unwrapped artifacts' rows were seen (both rounds predate
        # the gated device_*_ms rows, so the report says so explicitly
        # rather than comparing wrapper keys).
        assert "BENCH_r04.json -> " in out or "r04" in out
