"""Fleet observability acceptance (ISSUE 10): multi-process trace
stitching and the fleet chaos scenario.

(1) Golden stitch: 2 subprocess queue workers + 1 serve daemon share
    one ``KAFKA_TPU_RUN_ID``; their per-process ``trace.json``
    fragments stitch into a single well-formed Chrome trace with >= 3
    distinct process tracks.

(2) Fleet chaos: a queue run with 2 subprocess workers plus a serve
    daemon; one worker is SIGKILLed mid-chunk.  ``fleet_status --json``
    flags the dead host within one heartbeat TTL while counters still
    sum correctly (and the queue view shows 9/9 done), trace stitching
    produces one well-formed Chrome trace for the run id, and the
    daemon's live ``/metrics`` output parses as valid Prometheus text
    exposition.

All tier-1 / CPU.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from kafka_tpu.io.tiling import chunk_mask, get_chunks
from kafka_tpu.resilience import faults
from kafka_tpu.serve import read_response, submit_request
from kafka_tpu.telemetry.aggregate import parse_prom_text, stitch_traces
from kafka_tpu.testing.fixtures import make_pivot_mask

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRACE_FIELDS = ("ph", "ts", "pid", "tid", "name")

#: a date on the default synthetic tile's observation calendar
#: (base 2017-07-01 + day offsets 1, 3, 5, ... -> Jul 2, Jul 4, ...).
SERVE_DATE = "2017-07-02T00:00:00"


def _env(run_id):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KAFKA_TPU_RUN_ID"] = run_id
    env["KAFKA_TPU_LIVE_INTERVAL_S"] = "0.2"
    env.pop(faults.ENV_VAR, None)
    return env


def _fleet_args(outdir, tel_dir, workers, extra=()):
    args = [
        "--operator", "identity", "--outdir", str(outdir),
        "--ny", "48", "--nx", "48", "--days", "8", "--step", "4",
        "--obs-every", "2", "--chunk-size", "16",
        "--retry-delay-s", "0.01", "--queue",
        "--num-workers", str(workers),
        "--telemetry-dir", str(tel_dir),
    ]
    return args + list(extra)


def _serve_cmd(root, tel_dir, extra=()):
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_serve",
        "--root", str(root), "--tiles", "1", "--operator", "identity",
        "--ny", "12", "--nx", "12", "--days", "16", "--step", "4",
        "--obs-every", "2", "--telemetry-dir", str(tel_dir),
        *extra,
    ]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _assert_wellformed(doc):
    assert doc["traceEvents"], "stitched trace is empty"
    for e in doc["traceEvents"]:
        for field in TRACE_FIELDS:
            assert field in e, f"{field} missing from {e}"


def _span_pids(doc):
    return {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}


class TestStitchedTraceGolden:
    def test_two_workers_plus_daemon_stitch_to_three_tracks(
            self, tmp_path):
        """Satellite acceptance: 2 subprocess workers + 1 daemon on CPU
        -> one merged trace.json with >= 3 distinct process tracks."""
        run_id = "golden-stitch"
        env = _env(run_id)
        tel = tmp_path / "tel"

        # Daemon first, one-shot: the request is pre-dropped into the
        # inbox, --exit-when-idle serves it and exits 0, dumping its
        # trace.json fragment under tel/serve.
        root = tmp_path / "serve"
        rid = submit_request(str(root), {"tile": "tile0",
                                         "date": SERVE_DATE})
        daemon = subprocess.run(
            _serve_cmd(root, tel / "serve",
                       extra=["--exit-when-idle",
                              "--idle-grace-s", "0.5"]),
            env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=600,
        )
        assert daemon.returncode == 0, daemon.stderr[-2000:]
        got = read_response(str(root), rid)
        assert got and got["status"] == "ok"

        # Then the 2-worker queue fleet over one shared outdir; each
        # worker dumps its own fragment under tel/fleet/worker_i.
        fleet = subprocess.run(
            [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
             *_fleet_args(tmp_path / "out", tel / "fleet", workers=2)],
            env=env, cwd=REPO_ROOT, capture_output=True, text=True,
            timeout=600,
        )
        assert fleet.returncode == 0, fleet.stderr[-2000:]
        summary = json.loads(fleet.stdout.strip().splitlines()[-1])
        assert summary["done"] == 9 and summary["failed"] == 0

        doc = stitch_traces(str(tel), run_id=run_id)
        _assert_wellformed(doc)
        assert doc["otherData"]["run_ids"] == [run_id]
        assert len(doc["otherData"]["sources"]) >= 3
        assert len(_span_pids(doc)) >= 3
        labels = {
            e["args"]["name"] for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        }
        assert any("serve" in lb for lb in labels)
        assert any("worker_0" in lb for lb in labels)
        assert any("worker_1" in lb for lb in labels)
        # The stitched timeline is itself loadable JSON on disk.
        out = tmp_path / "stitched.json"
        json.dump(doc, open(out, "w"))
        assert json.load(open(out))["otherData"]["stitched"] is True


class TestFleetChaos:
    def test_sigkill_worker_flagged_dead_with_correct_sums(
            self, tmp_path):
        """ISSUE 10 acceptance: 2 workers + daemon, one worker
        SIGKILLed mid-chunk -> fleet_status flags the dead host within
        one heartbeat TTL, counters still sum correctly, the stitched
        trace is well-formed, and /metrics parses as valid Prometheus
        exposition."""
        run_id = "fleet-chaos"
        env = _env(run_id)
        tel = tmp_path / "tel"
        outdir = tmp_path / "out"
        hostname = socket.gethostname()

        # -- serve daemon with the live HTTP endpoint ----------------
        port = _free_port()
        root = tmp_path / "serve"
        daemon = subprocess.Popen(
            _serve_cmd(root, tel / "serve",
                       extra=["--http-port", str(port)]),
            env=env, cwd=REPO_ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True,
        )
        victim = None
        try:
            base = f"http://127.0.0.1:{port}"
            deadline = time.time() + 180
            while time.time() < deadline:
                if daemon.poll() is not None:
                    pytest.fail(
                        f"daemon exited rc={daemon.returncode} before "
                        "serving"
                    )
                try:
                    urllib.request.urlopen(base + "/", timeout=1.0)
                    break
                except OSError:
                    time.sleep(0.1)
            else:
                pytest.fail("daemon endpoint never came up")

            rid = submit_request(str(root), {"tile": "tile0",
                                             "date": SERVE_DATE})
            deadline = time.time() + 180
            while time.time() < deadline:
                got = read_response(str(root), rid)
                if got is not None:
                    break
                time.sleep(0.1)
            else:
                pytest.fail("daemon never answered the request")
            assert got["status"] == "ok"

            # Acceptance: live /metrics parses as valid exposition and
            # carries the serve counters mid-run.
            body = urllib.request.urlopen(
                base + "/metrics", timeout=5.0
            ).read().decode("utf-8")
            fams = parse_prom_text(body)
            admitted = fams["kafka_serve_admitted_total"]["samples"]
            assert admitted and admitted[0]["value"] >= 1
            sz = json.loads(urllib.request.urlopen(
                base + "/statusz", timeout=5.0
            ).read())
            assert sz["status"]["sessions"]["tile0"]["serves"] >= 1

            # -- victim worker, SIGKILLed mid-(non-empty)-chunk ------
            mask = make_pivot_mask(48, 48)
            slow_leases = {
                f".chunk_{c.chunk_no:04x}.lease"
                for c in get_chunks(48, 48, (16, 16))
                if chunk_mask(mask, c).any()
            }
            victim = subprocess.Popen(
                [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
                 *_fleet_args(outdir, tel / "w0", workers=1,
                              extra=["--lease-ttl-s", "1.0"])],
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            deadline = time.time() + 180
            while time.time() < deadline:
                if victim.poll() is not None:
                    pytest.fail(
                        f"victim exited rc={victim.returncode} before "
                        "it could be killed"
                    )
                names = set(
                    os.listdir(outdir) if os.path.isdir(outdir) else ()
                )
                if names & slow_leases:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("victim never claimed a non-empty chunk")
            victim.kill()
            victim.wait(timeout=30)
            # The victim heartbeated at least once before dying.
            victim_key = f"{hostname}:{victim.pid}"
            victim_snaps = [
                n for n in os.listdir(tel / "w0")
                if n == f"live_{hostname}_{victim.pid}.json"
            ] if os.path.isdir(tel / "w0") else []
            assert victim_snaps, "victim published no live snapshot"

            # -- survivor finishes the queue -------------------------
            survivor = subprocess.run(
                [sys.executable, "-m", "kafka_tpu.cli.run_synthetic",
                 *_fleet_args(outdir, tel / "w1", workers=1,
                              extra=["--lease-ttl-s", "1.0"])],
                env=env, cwd=REPO_ROOT, capture_output=True, text=True,
                timeout=600,
            )
            assert survivor.returncode == 0, survivor.stderr[-2000:]
            s_summary = json.loads(
                survivor.stdout.strip().splitlines()[-1]
            )
            assert s_summary["failed"] == 0 and \
                s_summary["pending"] == 0
            assert s_summary["reclaimed"] >= 1

            # -- drain the daemon cleanly ----------------------------
            daemon.send_signal(signal.SIGTERM)
            out, _ = daemon.communicate(timeout=120)
            assert daemon.returncode == 0
            d_summary = json.loads(out.strip().splitlines()[-1])
            assert d_summary["errors"] == 0
        finally:
            for proc in (victim, daemon):
                if proc is not None and proc.poll() is None:
                    proc.kill()

        # -- the fleet view ------------------------------------------
        from tools.fleet_status import build_view

        fleet = build_view(str(tel), ttl_s=1.0)
        workers = {w["key"]: w for w in fleet["workers"]}
        # Dead host flagged within one heartbeat TTL: the victim's
        # heartbeat is stale and carries no clean-shutdown marker...
        assert workers[victim_key]["dead"] is True
        assert victim_key in fleet["dead_hosts"]
        # ...while the survivor and the daemon exited cleanly (final
        # snapshots) and are NOT flagged however long ago they stopped.
        clean = [w for k, w in workers.items() if k != victim_key]
        assert clean and all(w["final"] and not w["dead"]
                             for w in clean)
        roles = {w["role"] for w in fleet["workers"]}
        assert {"queue_worker", "serve"} <= roles

        # Counters still sum correctly: the fleet total equals the
        # per-worker breakdown's sum, and covers at least the
        # survivor's own completions.
        done_tag = "kafka_shard_chunks_completed_total"
        by_worker = fleet["counters_by_worker"][done_tag]
        assert fleet["counters"][done_tag] == sum(by_worker.values())
        # The survivor's final snapshot carries its exact completion
        # count (the victim's last heartbeat may lag its true count —
        # that is the nature of a SIGKILL).
        assert s_summary["chunks_run"] in by_worker.values()
        assert fleet["counters"][done_tag] >= s_summary["chunks_run"]
        # The queue view (auto-discovered from worker status) agrees:
        # every chunk reached .done despite the kill.
        assert fleet["queue"] is not None
        assert fleet["queue"]["counts"]["done"] == 9
        assert fleet["queue"]["counts"]["lease_expired"] == 0

        # Trace stitching produces a single well-formed Chrome trace
        # for the run id (survivor + daemon fragments; the SIGKILLed
        # victim never got to dump one).
        doc = stitch_traces(str(tel), run_id=run_id)
        _assert_wellformed(doc)
        assert doc["otherData"]["run_ids"] == [run_id]
        assert len(doc["otherData"]["sources"]) >= 2
        assert len(_span_pids(doc)) >= 2
