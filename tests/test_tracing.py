"""The trace timeline (ISSUE 3 tentpole): context propagation, Chrome
trace-event export, and the end-to-end acceptance — a CPU-only synthetic
run with ``--telemetry-dir`` produces a well-formed ``trace.json`` with
at least the engine, prefetch and writer thread tracks."""

import json
import os
import threading
import time

import pytest

from kafka_tpu import telemetry
from kafka_tpu.telemetry import MetricsRegistry, tracing


REQUIRED_FIELDS = ("ph", "ts", "pid", "tid", "name")


def thread_names(events):
    return {
        e["args"]["name"] for e in events if e["name"] == "thread_name"
    }


class TestTraceContext:
    def test_push_creates_and_nests(self):
        assert tracing.current_context() is None
        with tracing.push(run_id="r1") as ctx:
            assert ctx.run_id == "r1"
            with tracing.push(chunk_id="00ff", window_id=2) as inner:
                assert inner.run_id == "r1"
                assert inner.chunk_id == "00ff"
                assert inner.window_id == 2
            assert tracing.current_context().chunk_id is None
        assert tracing.current_context() is None

    def test_new_run_id_prefers_env(self, monkeypatch):
        monkeypatch.setenv("KAFKA_TPU_RUN_ID", "parent-run")
        assert tracing.new_run_id() == "parent-run"
        monkeypatch.delenv("KAFKA_TPU_RUN_ID")
        assert tracing.new_run_id() != "parent-run"

    def test_context_does_not_cross_threads_without_set(self):
        """Threads start context-free; set_context() is the explicit
        propagation the prefetcher/writer perform."""
        seen = {}

        def probe(ctx):
            seen["bare"] = tracing.current_context()
            tracing.set_context(ctx)
            seen["installed"] = tracing.current_context()

        with tracing.push(run_id="r2") as ctx:
            t = threading.Thread(target=probe, args=(ctx,))
            t.start()
            t.join()
        assert seen["bare"] is None
        assert seen["installed"].run_id == "r2"


class TestTraceBuffer:
    def test_spans_carry_context_and_lanes(self):
        buf = tracing.TraceBuffer()
        t0 = time.perf_counter()
        with tracing.push(run_id="rid", window_id=7):
            buf.add_span("advance", t0, t0 + 0.01, cat="phase")
        buf.add_span("read", t0, t0 + 0.02, lane="prefetch")
        buf.add_counter("queue_depth", 3)
        doc = buf.to_chrome()
        events = doc["traceEvents"]
        for e in events:
            for field in REQUIRED_FIELDS:
                assert field in e, f"{field} missing from {e}"
        spans = {e["name"]: e for e in events if e["ph"] == "X"}
        assert spans["advance"]["args"]["run_id"] == "rid"
        assert spans["advance"]["args"]["window_id"] == 7
        assert spans["advance"]["dur"] > 0
        assert {"engine", "prefetch"} <= thread_names(events)
        counters = [e for e in events if e["ph"] == "C"]
        assert counters[0]["name"] == "queue_depth"
        assert counters[0]["args"]["value"] == 3.0
        assert doc["otherData"]["run_ids"] == ["rid"]

    def test_trace_span_nests_parents(self):
        with telemetry.use(MetricsRegistry()) as reg:
            with tracing.push(run_id="rp"):
                with tracing.trace_span("outer"):
                    with tracing.trace_span("inner"):
                        pass
            spans = {
                e["name"]: e["args"]
                for e in reg.trace.to_chrome()["traceEvents"]
                if e["ph"] == "X"
            }
        assert spans["inner"]["parent_span"] == spans["outer"]["span_id"]

    def test_export_is_loadable_json(self, tmp_path):
        buf = tracing.TraceBuffer()
        t0 = time.perf_counter()
        buf.add_span("x", t0, t0 + 0.001)
        path = buf.export(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        assert doc["displayTimeUnit"] == "ms"
        assert any(e["ph"] == "X" for e in doc["traceEvents"])

    def test_bounded(self):
        buf = tracing.TraceBuffer(max_events=8)
        t0 = time.perf_counter()
        for i in range(40):
            buf.add_span(f"s{i}", t0, t0 + 0.001)
            buf.add_counter("c", i)
        assert len(buf) == 16  # 8 spans + 8 counters, oldest dropped


class TestEngineTimeline:
    def test_engine_run_produces_three_lanes(self):
        """The in-process engine harness alone covers engine + prefetch
        tracks; the writer track needs the async GeoTIFF writer (covered
        by the driver test below)."""
        from kafka_tpu.testing.synthetic import run_tip_engine

        with telemetry.use(MetricsRegistry()) as reg:
            run_tip_engine(scan_window=4)
            events = reg.trace.to_chrome()["traceEvents"]
        assert {"engine", "prefetch"} <= thread_names(events)
        span_names = {e["name"] for e in events if e["ph"] == "X"}
        assert "fused_scan" in span_names
        assert "prefetch_read" in span_names
        assert any(
            e["ph"] == "C" and e["name"] == "prefetch_queue_depth"
            for e in events
        )
        # Engine phases carry the window correlation id.
        windows = {
            e["args"].get("window_id") for e in events
            if e["ph"] == "X" and e["cat"] == "phase"
        }
        assert len(windows) > 1

    def test_run_synthetic_writes_wellformed_trace_json(self, tmp_path):
        """ISSUE 3 acceptance: CPU-only run_synthetic --telemetry-dir
        produces a well-formed Chrome trace-event ``trace.json`` with >= 3
        distinct thread tracks (engine, prefetch, writer)."""
        from kafka_tpu.cli.run_synthetic import main

        tel = str(tmp_path / "tel")
        prev = telemetry.get_registry()
        try:
            main([
                "--operator", "identity",
                "--outdir", str(tmp_path / "out"),
                "--telemetry-dir", tel,
                "--days", "8", "--step", "2",
                "--ny", "24", "--nx", "24",
            ])
        finally:
            telemetry.set_registry(prev)
            telemetry.flight_recorder.uninstall()
        doc = json.load(open(os.path.join(tel, "trace.json")))
        events = doc["traceEvents"]
        assert events
        for e in events:
            for field in REQUIRED_FIELDS:
                assert field in e, f"{field} missing from {e}"
            assert isinstance(e["ts"], (int, float))
        lanes = thread_names(events)
        assert {"engine", "prefetch", "writer"} <= lanes
        assert len({e["tid"] for e in events if e["ph"] == "X"}) >= 3
        span_names = {e["name"] for e in events if e["ph"] == "X"}
        assert {"dump", "prefetch_read", "write"} <= span_names
        counter_names = {e["name"] for e in events if e["ph"] == "C"}
        assert {"prefetch_queue_depth", "writer_backlog"} <= counter_names
        # One run id threads the whole timeline together.
        assert len(doc["otherData"]["run_ids"]) == 1


class TestCompileObservability:
    def test_backend_compile_lands_in_registry_and_trace(self):
        """A jitted compile must produce the compile-wall histogram, a
        ``compile`` event and an ``xla_compile`` span (listener path —
        degrades silently only when jax.monitoring is absent)."""
        import jax
        import jax.numpy as jnp

        from kafka_tpu.telemetry import install_compile_listeners

        if not install_compile_listeners():
            pytest.skip("jax.monitoring unavailable")
        with telemetry.use(MetricsRegistry()) as reg:
            # A fresh closure defeats jit's in-memory cache, forcing one
            # real backend compile while listeners are active.
            salt = time.time_ns()
            jax.jit(lambda v: v * 2 + (salt % 7))(jnp.zeros(4))
            st = reg.value("kafka_compile_program_seconds")
            assert st is not None and st["count"] >= 1
            assert any(e["event"] == "compile" for e in reg.events)
            names = {
                e["name"] for e in reg.trace.to_chrome()["traceEvents"]
                if e["ph"] == "X"
            }
            assert "xla_compile" in names


class TestMemoryWatermark:
    def test_noop_on_cpu_or_records_gauges(self):
        """On CPU memory_stats() is None -> clean no-op; on a real device
        the per-device gauges and trace counters appear.  Either way:
        zero device->host transfers (the reads counter is untouched)."""
        import jax

        from kafka_tpu.telemetry import record_memory_watermark

        with telemetry.use(MetricsRegistry()) as reg:
            record_memory_watermark()
            reads = reg.value("kafka_engine_device_reads_total")
            assert reads is None  # the funnel was never touched
            has_stats = any(
                d.memory_stats() for d in jax.local_devices()
            )
            gauge = reg.value(
                "kafka_device_memory_bytes_in_use",
                device=jax.local_devices()[0].id,
            )
            if has_stats:
                assert gauge is not None and gauge > 0
            else:
                assert gauge is None
