"""Tier-1 wiring of tools/programlint (ISSUE 19): every registered
device program is abstractly traced on CPU and verified against its
contracts — dtype hygiene, transfer-freedom, relayout-freedom, the
collective manifest and the checked-in fingerprint manifests — on every
test run, so an f64 upcast, a smuggled callback, a Jacobian relayout or
a surprise all-gather breaks the suite, not a TPU bench run later.

Also pins the analyzer itself: each seeded violation in
tests/programlint_fixtures.py must be reported by exactly its intended
checker (the ``EXPECT`` map — the IR-level twin of the lint fixtures'
``# expect:`` convention), manifests must round-trip with drift/waiver
semantics, and the CLI exit codes must stay stable.
"""

import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from kafka_tpu import analysis  # noqa: E402
from kafka_tpu.analysis import checkers, trace  # noqa: E402
from kafka_tpu.analysis import programs  # noqa: E402,F401  (registration)
from tests import programlint_fixtures  # noqa: E402  (fixture registry)
from tools import programlint  # noqa: E402


@pytest.fixture(scope="module")
def production_result():
    """One full analysis pass over every production program, shared by
    the tier-1 assertions below (tracing is deterministic)."""
    return analysis.analyze(
        analysis.get_specs(), contracts_dir=analysis.contracts_dir()
    )


# ---------------------------------------------------------------------------
# Tier-1: the production programs must analyze clean.
# ---------------------------------------------------------------------------

def test_all_production_programs_clean(production_result):
    assert production_result.findings == [], "\n".join(
        f.format() for f in production_result.findings
    )


def test_production_manifests_checked_in_and_waiver_free(production_result):
    names = set(analysis.REGISTRY)
    on_disk = {
        fn[:-len(".json")]
        for fn in os.listdir(analysis.contracts_dir())
        if fn.endswith(".json")
    }
    assert on_disk == names
    for name in names:
        stored = checkers.load_manifest(analysis.contracts_dir(), name)
        assert stored["waivers"] == []  # the goal state, like the baseline
        assert stored["fingerprint"] == \
            production_result.reports[name]["fingerprint"]


def test_registry_covers_the_flagship_programs():
    names = set(analysis.REGISTRY)
    assert {
        "date_twostream_xla", "date_twostream_inkernel",
        "date_twostream_jac_to_rows", "windows_scan_twostream",
        "windows_scan_twostream_inkernel", "smoother_rts_sweep",
        "sharded_step_tip", "sharded_forward_tip",
    } <= names
    assert sum(1 for n in names if n.startswith("linearize_")) >= 6


def test_mesh_program_collectives_are_inventoried(production_result):
    step = production_result.reports["sharded_step_tip"]
    assert step["mesh_devices"] >= 2
    assert set(step["collectives"]) <= {"all-reduce"}
    assert step["collectives"]  # the convergence norm must be there
    fwd = production_result.reports["sharded_forward_tip"]
    assert fwd["collectives"] == {}


def test_fingerprints_are_deterministic():
    spec = analysis.REGISTRY["linearize_twostream"]
    fp = [
        analysis.fingerprint(
            trace.trace_program(spec, compile_collectives=False)
        )
        for _ in range(2)
    ]
    assert fp[0] == fp[1] and len(fp[0]) == 16


# ---------------------------------------------------------------------------
# Seeded fixtures: each violation caught by exactly its intended checker.
# ---------------------------------------------------------------------------

def _fixture_findings(name):
    spec = programlint_fixtures.REGISTRY[name]
    tp = trace.trace_program(spec)
    return checkers.run_checkers(tp)


@pytest.mark.parametrize(
    "name,expected_checker", sorted(programlint_fixtures.EXPECT.items())
)
def test_seeded_fixture_caught_by_exactly_its_checker(name,
                                                      expected_checker):
    findings = _fixture_findings(name)
    assert {f.checker for f in findings} == {expected_checker}, \
        "\n".join(f.format() for f in findings)


def test_fixture_expect_map_spans_all_four_checkers():
    assert set(programlint_fixtures.EXPECT.values()) == {
        "dtype", "transfer", "relayout", "collective",
    }
    assert set(programlint_fixtures.EXPECT) == \
        set(programlint_fixtures.REGISTRY)


# ---------------------------------------------------------------------------
# Manifest mechanics: missing -> update -> clean -> drift; waivers.
# ---------------------------------------------------------------------------

def _toy_registry():
    registry = {}

    @analysis.register_program(
        "toy_scale", description="manifest round-trip probe",
        registry=registry,
    )
    def _build():
        import jax
        import numpy as np

        return (
            lambda x: x * 2.0,
            (jax.ShapeDtypeStruct((8,), np.float32),),
        )

    return registry


def test_manifest_roundtrip_and_drift(tmp_path):
    registry = _toy_registry()
    specs = analysis.get_specs(registry=registry)
    cdir = str(tmp_path)

    missing = analysis.analyze(specs, contracts_dir=cdir)
    assert [f.checker for f in missing.findings] == ["manifest"]
    assert "--update" in missing.findings[0].message

    updated = analysis.analyze(specs, contracts_dir=cdir, update=True)
    assert updated.findings == []
    assert [os.path.basename(p) for p in updated.updated] == \
        ["toy_scale.json"]

    clean = analysis.analyze(specs, contracts_dir=cdir)
    assert clean.findings == []

    stored = checkers.load_manifest(cdir, "toy_scale")
    stored["fingerprint"] = "0" * 16
    checkers.write_manifest(cdir, stored)
    drifted = analysis.analyze(specs, contracts_dir=cdir)
    assert [f.checker for f in drifted.findings] == ["drift"]
    assert "0000000000000000 ->" in drifted.findings[0].message


def test_waiver_silences_and_goes_stale(tmp_path):
    spec = programlint_fixtures.REGISTRY["fixture_smuggled_callback"]
    cdir = str(tmp_path)
    analysis.analyze([spec], contracts_dir=cdir, update=True)

    stored = checkers.load_manifest(cdir, spec.name)
    stored["waivers"] = [{
        "checker": "transfer", "contains": "pure_callback",
        "reason": "seeded fixture, waiver mechanics probe",
    }]
    checkers.write_manifest(cdir, stored)
    waived = analysis.analyze([spec], contracts_dir=cdir)
    assert waived.findings == []

    stored["waivers"] = [{
        "checker": "dtype", "contains": "no such finding",
        "reason": "stale on purpose",
    }]
    checkers.write_manifest(cdir, stored)
    stale = analysis.analyze([spec], contracts_dir=cdir)
    by_checker = {f.checker for f in stale.findings}
    assert by_checker == {"transfer", "stale-waiver"}


# ---------------------------------------------------------------------------
# CLI: exit codes, --json schema, --spec-module, --list.
# ---------------------------------------------------------------------------

def test_cli_clean_subset_exits_zero(capsys):
    rc = programlint.main(["--programs", "linearize_twostream"])
    assert rc == 0
    assert "clean (1 programs" in capsys.readouterr().out


def test_cli_fixture_violation_exits_one_naming_checker(capsys):
    rc = programlint.main([
        "--spec-module", "tests.programlint_fixtures", "--no-manifest",
        "--programs", "fixture_f64_upcast",
    ])
    assert rc == 1
    err = capsys.readouterr().err
    assert "[dtype]" in err and "fixture_f64_upcast" in err


def test_cli_json_schema(capsys):
    rc = programlint.main([
        "--spec-module", "tests.programlint_fixtures", "--no-manifest",
        "--programs", "fixture_rank3_relayout", "--json",
    ])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == 1
    assert set(payload["programs"]) == {"fixture_rank3_relayout"}
    report = payload["programs"]["fixture_rank3_relayout"]
    assert {"fingerprint", "eqns", "primitives", "dtypes",
            "relayout_clean", "collectives_manifest"} <= set(report)
    assert payload["findings"] and all(
        set(f) == {"program", "checker", "message"}
        for f in payload["findings"]
    )
    assert payload["findings"][0]["checker"] == "relayout"


def test_cli_unknown_program_and_bad_module_exit_two(capsys):
    assert programlint.main(["--programs", "no_such_program"]) == 2
    assert "no_such_program" in capsys.readouterr().err
    assert programlint.main(["--spec-module", "json"]) == 2
    assert "REGISTRY" in capsys.readouterr().err


def test_cli_list_names_every_program(capsys):
    assert programlint.main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in analysis.REGISTRY:
        assert name in out
    assert "relayout-clean" in out


def test_cli_subprocess_entry_point():
    """`python -m tools.programlint` works cold (fresh interpreter, no
    conftest): the CLI owns its CPU/device-count environment."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.programlint", "--programs",
         "linearize_wcm"],
        cwd=REPO_ROOT, capture_output=True, text=True,
        env={k: v for k, v in os.environ.items()
             if k not in ("JAX_PLATFORMS", "XLA_FLAGS")},
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    assert "clean" in proc.stdout
