"""End-to-end reference-artifact path: gp_emulator pickles -> converted
banks -> the S2 driver assimilating through them (operator "gp_bank").

This is the drop-in story for reference users: their per-geometry
emulator pickles drive the TPU engine with no PROSAIL physics operator
involved.
"""

import datetime
import os
import pickle
import sys
import types

import numpy as np
import pytest

from kafka_tpu.engine.config import RunConfig
from kafka_tpu.engine.priors import PROSAIL_PARAMETER_LIST

BAND_NUMBERS = (2, 3, 4, 5, 6, 7, 8, 9, 12, 13)


def _fake_module():
    if not hasattr(_fake_module, "_mod"):
        mod = types.ModuleType("gp_emulator")

        class GaussianProcess:
            pass

        GaussianProcess.__module__ = "gp_emulator"
        GaussianProcess.__qualname__ = "GaussianProcess"
        mod.GaussianProcess = GaussianProcess
        _fake_module._mod = mod
    return _fake_module._mod


def _make_emulator_pickle(path, aux, n_train=200, seed=0):
    """Fit one GP per band to the PROSAIL forward at this geometry and
    pickle them in the reference's artifact format."""
    import jax

    from kafka_tpu.engine.priors import sail_prior
    from kafka_tpu.obsops.prosail import ProsailOperator

    op = ProsailOperator()
    rng = np.random.default_rng(seed)
    prior = sail_prior()
    mean = np.asarray(prior.prior.mean)
    lo, hi = op.state_bounds
    x_train = np.clip(
        mean + rng.normal(0, 0.08, (n_train, 10)), lo + 1e-3, hi - 1e-3
    ).astype(np.float32)
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        y = np.asarray(op.forward(aux, jax.device_put(x_train, cpu)))

    mod = _fake_module()
    bank = {}
    for b, num in enumerate(BAND_NUMBERS):
        # gp_emulator hyperparameters: theta = [log w_d..., log amp,
        # log noise], w = inverse squared lengthscales.
        ell = x_train.std(0).astype(np.float64) * 2.0 + 0.05
        theta = np.concatenate([
            np.log(1.0 / ell**2), [np.log(0.05)], [np.log(1e-6)],
        ])
        w = np.exp(theta[:10])
        z = x_train.astype(np.float64) * np.sqrt(w)
        d2 = (
            (z * z).sum(1)[:, None] + (z * z).sum(1)[None, :]
            - 2.0 * z @ z.T
        )
        k = np.exp(theta[10]) * np.exp(-0.5 * np.maximum(d2, 0.0))
        k[np.diag_indices_from(k)] += np.exp(theta[11])
        gp = mod.GaussianProcess()
        gp.inputs = x_train.astype(np.float64)
        gp.targets = y[b].astype(np.float64)
        gp.theta = theta
        gp.invQt = np.linalg.solve(k, y[b].astype(np.float64))
        bank[b"S2A_MSI_%02d" % num] = gp
    sys.modules["gp_emulator"] = mod
    try:
        with open(path, "wb") as f:
            pickle.dump(bank, f, protocol=2)
    finally:
        del sys.modules["gp_emulator"]


@pytest.mark.slow
def test_s2_run_through_converted_reference_emulators(tmp_path):
    from kafka_tpu.cli.drivers import resolve_aux_builder, run_one_chunk
    from kafka_tpu.cli.import_emulators import main as import_main
    from kafka_tpu.io.geotiff import read_geotiff
    from kafka_tpu.io.tiling import Chunk
    from kafka_tpu.obsops.prosail import ProsailAux
    from kafka_tpu.testing.fixtures import (
        DEFAULT_GEO, make_pivot_mask, make_s2_granule_tree,
    )
    import jax.numpy as jnp

    ny = nx = 24
    dates = [datetime.datetime(2017, 7, 3),
             datetime.datetime(2017, 7, 5)]
    make_s2_granule_tree(str(tmp_path / "s2"), dates, ny=ny, nx=nx)

    # Emulator pickles at the scene geometry (sza 30.5, vza 5, raa -50
    # -> filename-encoded grid point).
    aux = ProsailAux(
        sza=jnp.asarray(30.5), vza=jnp.asarray(5.0),
        raa=jnp.asarray(-50.0),
    )
    os.makedirs(tmp_path / "pickles")
    _make_emulator_pickle(
        str(tmp_path / "pickles" / "prosail_5_30_310.pkl"), aux
    )
    # CLI conversion to .npz banks
    import_main([str(tmp_path / "pickles"), str(tmp_path / "banks"),
                 "--verbose"])
    assert list((tmp_path / "banks").glob("*.npz"))

    # The S2 driver path with operator gp_bank over the converted banks.
    from kafka_tpu.io.geotiff import GeoInfo, write_geotiff

    mask = make_pivot_mask(ny, nx, n_pivots=2, seed=1)
    write_geotiff(str(tmp_path / "mask.tif"),
                  mask.astype(np.uint8), DEFAULT_GEO)
    cfg = RunConfig(
        parameter_list=PROSAIL_PARAMETER_LIST,
        start=dates[0] - datetime.timedelta(days=1),
        end=dates[-1] + datetime.timedelta(days=1),
        step_days=2,
        operator="gp_bank",
        propagator="none",
        prior="sail",
        observations="sentinel2",
        data_folder=str(tmp_path / "s2"),
        state_mask=str(tmp_path / "mask.tif"),
        output_folder=str(tmp_path / "out"),
        chunk_size=(64, 64),
        solver_options={"relaxation": 0.6},
        device_mesh="none",
    )
    cfg.extra["emulator_folder"] = str(tmp_path / "banks")
    from kafka_tpu.io.geotiff import read_info

    _, info = read_geotiff(str(tmp_path / "mask.tif"))
    chunk = Chunk(0, 0, nx, ny, 0)
    summary = run_one_chunk(
        cfg, chunk, "0000", mask, info.geo,
        aux_builder=resolve_aux_builder(cfg),
    )
    assert summary is not None and summary["n_dates_assimilated"] == 2
    outs = sorted((tmp_path / "out").glob("lai_*.tif"))
    assert outs
    lai, _ = read_geotiff(str(outs[-1]))
    assert np.isfinite(lai).all()
    vals = lai[mask.astype(bool)]
    # The synthetic truth has TLAI ~ exp(-lai/2) around the SAIL prior;
    # emulated retrievals must land in (0, 1) and actually move pixels.
    assert ((vals > 0.0) & (vals < 1.0)).all()
