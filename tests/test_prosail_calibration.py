"""PROSAIL operator calibration tests (VERDICT round-1 item 6).

Three layers of quantitative checks, replacing the round-1 suite's purely
qualitative physics assertions:

1. **Flux-solution parity**: the closed-form SAIL two-stream solution
   (``sail_fluxes``) against an independent finite-difference boundary-
   value oracle of the same ODE system (float64, 20k layers) — validates
   the eigenmode/particular/BC algebra to ~1e-3 across leaf optics,
   LIDF moments, soils and LAI.
2. **Plate-model parity**: the jitted leaf model against a float64 oracle
   using SciPy's exact exponential integral (validates the branch-free
   E1 approximation and float32 stability).
3. **Canonical signatures**: reflectance windows per S2 band for the
   standard PROSAIL validation state (N=1.5, Cab=40, Car=8, Cw=0.0176,
   Cm=0.009, LAI=3, spherical LIDF) — the published behaviour of healthy
   dense vegetation — plus directional sensitivity checks (chlorophyll ->
   red, water -> SWIR, LAI -> NIR plateau monotone).
"""

import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sp
import scipy.sparse.linalg as spl
from scipy.special import exp1

from kafka_tpu.obsops.prosail import (
    BAND_K,
    N_REFRACT,
    ProsailAux,
    ProsailOperator,
    _TAV40,
    _TAV90,
    bf_from_ala,
    leaf_optics,
    sail_fluxes,
)


# ---------------------------------------------------------------------------
# 1. SAIL flux solution vs finite-difference BVP oracle
# ---------------------------------------------------------------------------


def bvp_oracle(rho, tau, soil, lai, ks, ko, bf, n=20000):
    """Float64 finite-difference solve of the SAIL diffuse-flux system:

        dD/dx = -att D + sigb U + sf e^{-ks x}
        dU/dx =  att U - sigb D - sb e^{-ks x}
        D(0) = 0,  U(L) = soil (D(L) + e^{-ks L})

    Returns the same quantities as ``sail_fluxes``.
    """
    ddb, ddf = 0.5 * (1 + bf), 0.5 * (1 - bf)
    sdb, sdf = 0.5 * (ks + bf), 0.5 * (ks - bf)
    dob, dof = 0.5 * (ko + bf), 0.5 * (ko - bf)
    sigb = ddb * rho + ddf * tau
    sigf = ddf * rho + ddb * tau
    att = 1 - sigf
    sb = sdb * rho + sdf * tau
    sf = sdf * rho + sdb * tau
    vb = dob * rho + dof * tau
    vf = dof * rho + dob * tau

    x = np.linspace(0.0, lai, n + 1)
    h = x[1] - x[0]
    es = np.exp(-ks * x)
    A = sp.lil_matrix((2 * (n + 1), 2 * (n + 1)))
    b = np.zeros(2 * (n + 1))
    for i in range(1, n):
        A[2 * i, 2 * (i + 1)] += 1 / (2 * h)
        A[2 * i, 2 * (i - 1)] -= 1 / (2 * h)
        A[2 * i, 2 * i] += att
        A[2 * i, 2 * i + 1] -= sigb
        b[2 * i] = sf * es[i]
        A[2 * i + 1, 2 * (i + 1) + 1] += 1 / (2 * h)
        A[2 * i + 1, 2 * (i - 1) + 1] -= 1 / (2 * h)
        A[2 * i + 1, 2 * i + 1] -= att
        A[2 * i + 1, 2 * i] += sigb
        b[2 * i + 1] = -sb * es[i]
    A[0, 0] = 1.0                       # D(0) = 0
    A[1, 3] += 1 / h                    # forward difference for U at top
    A[1, 1] += -1 / h - att
    A[1, 0] += sigb
    b[1] = -sb * es[0]
    A[2 * n + 1, 2 * n + 1] = 1.0       # soil boundary
    A[2 * n + 1, 2 * n] = -soil
    b[2 * n + 1] = soil * np.exp(-ks * lai)
    A[2 * n, 2 * n] += 1 / h + att      # backward difference for D at L
    A[2 * n, 2 * (n - 1)] += -1 / h
    A[2 * n, 2 * n + 1] -= sigb
    b[2 * n] = sf * es[n]
    sol = spl.spsolve(A.tocsr(), b)
    d, u = sol[0::2], sol[1::2]
    return {
        "rad_leaf": np.trapezoid((vb * u + vf * d) * np.exp(-ko * x), x),
        "u_bottom": u[-1],
        "d_bottom": d[-1],
        "rdd_top": u[0],
    }


FLUX_CASES = [
    # rho, tau, soil, lai, ks, ko, bf          — regime
    (0.47, 0.48, 0.20, 3.0, 0.577, 0.500, 1 / 3),   # NIR, dense
    (0.05, 0.04, 0.15, 3.0, 0.577, 0.500, 1 / 3),   # red, dense
    (0.47, 0.48, 0.25, 0.5, 0.577, 0.500, 1 / 3),   # NIR, sparse
    (0.30, 0.30, 0.10, 5.0, 0.800, 0.600, 0.60),    # planophile, oblique
    (0.15, 0.10, 0.30, 1.5, 0.450, 1.000, 0.15),    # erectophile
    (0.09, 0.06, 0.35, 2.0, 0.577, 0.577, 1 / 3),   # SWIR over bright soil
    # exact ks = m resonance (red leaf at sza ~ 57 deg): the removable
    # singularity handled by the consistent ks nudge
    (0.09, 0.06, 0.15, 3.0, 0.9265527507918803, 0.5, 1 / 3),
]


class TestFluxParity:
    @pytest.mark.parametrize("rho,tau,soil,lai,ks,ko,bf", FLUX_CASES)
    def test_matches_bvp_oracle(self, rho, tau, soil, lai, ks, ko, bf):
        fx = sail_fluxes(*map(jnp.asarray, (rho, tau, soil, lai, ks, ko,
                                            bf)))
        want = bvp_oracle(rho, tau, soil, lai, ks, ko, bf)
        for key, expect in want.items():
            got = float(fx[key])
            assert got == pytest.approx(expect, abs=2e-3), (
                f"{key}: analytic {got} vs oracle {expect}"
            )

    def test_energy_balance_near_conservative_leaf(self):
        """With a nearly non-absorbing leaf (rho + tau = 0.996) over a
        black soil, reflected + transmitted + beam energy must equal
        incident minus the small leaf absorption.  (The exactly
        conservative limit is a degenerate eigenproblem the closed form
        clamps away from — physical leaves always absorb.)"""
        rho, tau = 0.499, 0.497
        lai, ks, bf = 2.0, 0.577, 1 / 3
        fx = sail_fluxes(*map(jnp.asarray, (rho, tau, 0.0, lai, ks, 0.5,
                                            bf)))
        want = bvp_oracle(rho, tau, 0.0, lai, ks, 0.5, bf)
        total = float(fx["rdd_top"]) + float(fx["d_bottom"]) + float(
            fx["tss"]
        )
        total_oracle = want["rdd_top"] + want["d_bottom"] + np.exp(
            -ks * lai
        )
        assert total == pytest.approx(total_oracle, abs=5e-3)
        assert 0.97 <= total <= 1.0  # tiny absorption only


# ---------------------------------------------------------------------------
# 2. Plate model vs float64 SciPy oracle
# ---------------------------------------------------------------------------


def plate_oracle(n_layers, cab, car, cbrown, cw, cm):
    """Float64 generalized plate model with SciPy's exact E1."""
    k = (BAND_K * np.array([cab, car, cbrown, cw, cm])[:, None]).sum(0)
    k = np.maximum(k / max(n_layers, 1.0), 1e-6)
    trans = (1 - k) * np.exp(-k) + k**2 * exp1(k)
    trans = np.clip(trans, 1e-6, 1 - 1e-6)
    t21 = _TAV90 / N_REFRACT**2
    r21 = 1 - t21
    r12 = 1 - _TAV90
    talf, ralf = _TAV40, 1 - _TAV40
    denom = 1 - r21**2 * trans**2
    ta = talf * trans * t21 / denom
    ra = ralf + r21 * trans * ta
    t = _TAV90 * trans * t21 / denom
    r = r12 + r21 * trans * t
    t = np.clip(t, 1e-6, 1 - 1e-6)
    r = np.clip(r, 1e-6, 1 - 1e-6)
    d = np.sqrt(np.maximum(
        (1 + r + t) * (1 + r - t) * (1 - r + t) * (1 - r - t), 1e-12
    ))
    a = (1 + r**2 - t**2 + d) / (2 * r)
    b = (1 - r**2 + t**2 + d) / (2 * t)
    m = max(n_layers - 1.0, 1e-6)
    bnm1 = np.power(np.maximum(b, 1 + 1e-6), m)
    denom2 = a**2 * bnm1**2 - 1
    rsub = a * (bnm1**2 - 1) / denom2
    tsub = bnm1 * (a**2 - 1) / denom2
    denom3 = 1 - rsub * r
    return ra + ta * rsub * t / denom3, ta * tsub / denom3


LEAF_CASES = [
    (1.5, 40.0, 8.0, 0.0, 0.0176, 0.009),
    (1.2, 20.0, 5.0, 0.1, 0.0100, 0.005),
    (2.5, 70.0, 15.0, 0.0, 0.0300, 0.012),
    (1.8, 5.0, 2.0, 0.5, 0.0050, 0.002),
]


class TestPlateParity:
    @pytest.mark.parametrize("n,cab,car,cbrown,cw,cm", LEAF_CASES)
    def test_matches_scipy_oracle(self, n, cab, car, cbrown, cw, cm):
        rho, tau = leaf_optics(*map(jnp.asarray, (n, cab, car, cbrown, cw,
                                                  cm)))
        rho_o, tau_o = plate_oracle(n, cab, car, cbrown, cw, cm)
        np.testing.assert_allclose(np.asarray(rho), rho_o, atol=2e-3)
        np.testing.assert_allclose(np.asarray(tau), tau_o, atol=2e-3)


# ---------------------------------------------------------------------------
# 3. Canonical signatures + sensitivities
# ---------------------------------------------------------------------------


def standard_state(cab=40.0, cw=0.0176, cm=0.009, lai=3.0):
    return jnp.asarray([
        1.5, np.exp(-cab / 100), np.exp(-8.0 / 100), 0.0,
        np.exp(-50 * cw), np.exp(-100 * cm), np.exp(-lai / 2),
        57.3 / 90, 1.0, 0.5,
    ], jnp.float32)


AUX = ProsailAux(
    sza=jnp.asarray(30.0), vza=jnp.asarray(0.0), raa=jnp.asarray(0.0)
)

#: Plausibility windows for healthy dense vegetation (LAI 3, Cab 40) per
#: S2 band — the published shape of the canopy reflectance spectrum.
BAND_WINDOWS = [
    # band   lo     hi
    ("B02", 0.005, 0.06),
    ("B03", 0.02, 0.10),
    ("B04", 0.005, 0.07),
    ("B05", 0.03, 0.15),
    ("B06", 0.12, 0.35),
    ("B07", 0.30, 0.55),
    ("B08", 0.30, 0.55),
    ("B8A", 0.30, 0.55),
    ("B09", 0.25, 0.50),
    ("B12", 0.02, 0.20),
]


class TestCanonicalSignatures:
    def setup_method(self):
        self.op = ProsailOperator()

    def brf(self, x):
        return np.asarray(self.op.forward(AUX, x[None, :]))[:, 0]

    def test_dense_canopy_band_windows(self):
        brf = self.brf(standard_state())
        for (name, lo, hi), val in zip(BAND_WINDOWS, brf):
            assert lo <= val <= hi, f"{name}: {val:.3f} not in [{lo}, {hi}]"

    def test_ndvi_dense_canopy(self):
        brf = self.brf(standard_state())
        ndvi = (brf[6] - brf[2]) / (brf[6] + brf[2])
        assert 0.75 <= ndvi <= 0.97

    def test_nir_plateau_monotone_in_lai(self):
        nir = [self.brf(standard_state(lai=lai))[6]
               for lai in (0.5, 1.0, 2.0, 3.0, 5.0)]
        assert all(b > a for a, b in zip(nir, nir[1:]))
        assert 0.30 <= nir[-2] <= 0.55  # LAI 3 plateau

    def test_red_increases_when_chlorophyll_drops(self):
        hi = self.brf(standard_state(cab=40.0))[2]
        lo = self.brf(standard_state(cab=10.0))[2]
        assert lo > 2.0 * hi

    def test_swir_increases_when_water_drops(self):
        moist = self.brf(standard_state(cw=0.0176))[9]
        dry = self.brf(standard_state(cw=0.004))[9]
        assert dry > 1.5 * moist

    def test_red_edge_monotone(self):
        brf = self.brf(standard_state())
        # B04 < B05 < B06 < B07 — the red edge climbs
        assert brf[2] < brf[3] < brf[4] < brf[5]

    def test_bare_soil_low_ndvi(self):
        x = standard_state().at[6].set(0.999).at[8].set(1.0).at[9].set(1.0)
        brf = self.brf(x)
        ndvi = (brf[6] - brf[2]) / (brf[6] + brf[2])
        assert ndvi < 0.35
        # soil spectrum monotone brightening into the SWIR
        assert brf[9] > brf[2]

    def test_hotspot_brightens_backscatter(self):
        """Reflectance in the exact backscatter direction must exceed the
        same geometry away from the hotspot (the Kuusk correlation)."""
        op = ProsailOperator()
        x = standard_state()
        hot = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(30.0),
                         raa=jnp.asarray(0.0))
        cold = ProsailAux(sza=jnp.asarray(30.0), vza=jnp.asarray(30.0),
                          raa=jnp.asarray(120.0))
        b_hot = np.asarray(op.forward(hot, x[None, :]))[:, 0]
        b_cold = np.asarray(op.forward(cold, x[None, :]))[:, 0]
        assert b_hot[6] > b_cold[6]


class TestLIDFMoment:
    def test_spherical_second_moment(self):
        """Spherical LIDF (ALA ~ 57.3 deg) has <cos^2> = 1/3."""
        assert float(bf_from_ala(57.3)) == pytest.approx(1 / 3, abs=0.05)

    def test_monotone_decreasing_in_ala(self):
        vals = [float(bf_from_ala(a)) for a in (20.0, 35.0, 50.0, 65.0,
                                                80.0)]
        assert all(b > a for a, b in zip(vals[1:], vals))

    def test_limits(self):
        assert float(bf_from_ala(16.0)) > 0.75   # planophile: cos^2 -> 1
        assert float(bf_from_ala(79.0)) < 0.12   # erectophile: cos^2 -> 0


class TestQuantitativePerBandTargets:
    """Quantitative (not window) per-band agreement with canonical
    published green-leaf / canopy reflectance anchors (VERDICT r2 #3).

    Leaf targets are the textbook fresh-green-leaf directional-
    hemispherical reflectance values (LOPEX-class means): ~0.05 blue,
    ~0.12 green, ~0.05 red, red edge through ~0.10 (705 nm) and
    ~0.30 (740 nm) to the 0.45-0.50 NIR plateau, ~0.45 at the 945 nm
    water shoulder, ~0.10 at 2200 nm for a fresh leaf rising to ~0.20
    when water drops."""

    CANONICAL = dict(n=1.5, cab=40.0, car=8.0, cbrown=0.0, cw=0.0176,
                     cm=0.009)

    def _leaf(self, **over):
        from kafka_tpu.obsops.prosail import leaf_optics

        p = {**self.CANONICAL, **over}
        rho, tau = leaf_optics(
            jnp.asarray(p["n"]), jnp.asarray(p["cab"]),
            jnp.asarray(p["car"]), jnp.asarray(p["cbrown"]),
            jnp.asarray(p["cw"]), jnp.asarray(p["cm"]),
        )
        return np.asarray(rho), np.asarray(tau)

    #            B02   B03   B04   B05   B06   B07   B08   B8A   B09   B12
    LEAF_RHO = [0.05, 0.12, 0.05, 0.10, 0.30, 0.47, 0.47, 0.47, 0.45, 0.10]
    LEAF_TOL = [0.02, 0.03, 0.02, 0.03, 0.05, 0.04, 0.04, 0.04, 0.04, 0.04]

    def test_leaf_reflectance_per_band(self):
        rho, _ = self._leaf()
        for name, val, target, tol in zip(
            [b for b, *_ in BAND_WINDOWS], rho, self.LEAF_RHO,
            self.LEAF_TOL,
        ):
            assert abs(float(val) - target) <= tol, (
                f"{name}: leaf rho {float(val):.3f} vs target "
                f"{target} +- {tol}"
            )

    def test_leaf_transmittance_tracks_reflectance_in_nir(self):
        # NIR plateau: scattering-dominated, rho ~ tau ~ 0.45-0.50,
        # absorptance < 0.12 (published fresh-leaf NIR property).
        rho, tau = self._leaf()
        for b in (5, 6, 7):
            assert abs(float(rho[b]) - float(tau[b])) < 0.06
            assert 1.0 - float(rho[b]) - float(tau[b]) < 0.12

    def test_dry_leaf_swir_brightens_to_dry_matter_floor(self):
        rho_fresh, _ = self._leaf()
        rho_dry, _ = self._leaf(cw=0.002)
        assert abs(float(rho_dry[9]) - 0.20) <= 0.06
        assert float(rho_dry[9]) > float(rho_fresh[9]) + 0.08

    def test_chlorotic_leaf_red_green(self):
        # Cab=15 (chlorotic): red rises towards ~0.08, green to the
        # published chlorotic range ~0.18-0.28.
        rho, _ = self._leaf(cab=15.0)
        assert abs(float(rho[2]) - 0.08) <= 0.04
        assert abs(float(rho[1]) - 0.22) <= 0.06

    def test_dense_canopy_per_band(self):
        op = ProsailOperator()
        brf = np.asarray(op.forward(AUX, standard_state()[None, :]))[:, 0]
        #          B02    B03    B04    B05    B06    B07    B08
        targets = [0.02, 0.055, 0.02, 0.045, 0.18, 0.43, 0.43,
                   0.43, 0.40, 0.055]
        tols = [0.015, 0.025, 0.015, 0.025, 0.06, 0.06, 0.06,
                0.06, 0.06, 0.03]
        for (name, *_), val, target, tol in zip(
            BAND_WINDOWS, brf, targets, tols
        ):
            assert abs(float(val) - target) <= tol, (
                f"{name}: canopy BRF {float(val):.3f} vs "
                f"{target} +- {tol}"
            )


class TestGeneratedConstantsLocked:
    """Regression lock on the generated spectral constants: the
    prospect_data generator is deterministic — any drift (SRF change,
    anchor edit) must be a deliberate, test-visible act."""

    def test_band_k_snapshot(self):
        from kafka_tpu.obsops.prospect_data import BAND_K

        snapshot = np.array([
            [0.0392, 0.0133, 0.0730, 0.0186, 0.0035, 0.0000, 0.0000,
             0.0000, 0.0000, 0.0000],
            [0.0387, 0.0000, 0.0000, 0.0000, 0.0000, 0.0000, 0.0000,
             0.0000, 0.0000, 0.0000],
            [0.4905, 0.3110, 0.1545, 0.1185, 0.0932, 0.0702, 0.0514,
             0.0406, 0.0000, 0.0000],
            [0.0013, 0.0017, 0.0046, 0.0066, 0.0116, 0.0176, 0.0366,
             0.0511, 0.3189, 28.2898],
            [2.3070, 1.8016, 1.3384, 1.2396, 1.1496, 1.0431, 1.1822,
             1.3239, 1.7254, 22.7958],
        ])
        np.testing.assert_allclose(BAND_K, snapshot, atol=2e-3)

    def test_refractive_index_monotone_decline(self):
        from kafka_tpu.obsops.prospect_data import N_REFRACT

        assert N_REFRACT[0] > 1.50 and N_REFRACT[-1] < 1.40
        assert all(b <= a + 1e-6 for a, b in zip(N_REFRACT, N_REFRACT[1:]))

    def test_water_band_structure(self):
        """The published liquid-water magnitudes must survive band
        averaging: B09 (945 nm) sits on the weak ~0.3 cm^-1 shoulder,
        B12 (2202 nm) on the ~27 cm^-1 SWIR plateau."""
        from kafka_tpu.obsops.prospect_data import BAND_K

        cw = BAND_K[3]
        assert 0.2 <= cw[8] <= 0.5      # B09
        assert 20.0 <= cw[9] <= 40.0    # B12
        assert np.all(cw[:8] < 0.06)    # VNIR transparent


class TestRetrievalRecovery:
    def test_engine_recovers_lai_and_cab(self):
        """The capstone identifiability check: synthetic 10-band
        reflectances from a known state, assimilated through the REAL
        engine, must pull LAI and Cab from the SAIL prior to the truth —
        quantitatively (LAI 4->3 +-0.3, Cab 60->55 +-3), not just
        directionally."""
        import datetime

        from kafka_tpu.engine import KalmanFilter
        from kafka_tpu.engine.priors import (
            PROSAIL_PARAMETER_LIST, sail_prior,
        )
        from kafka_tpu.obsops.prosail import ProsailAux
        from kafka_tpu.testing import MemoryOutput, SyntheticObservations

        def day(i):
            return datetime.datetime(2017, 7, 1) + \
                datetime.timedelta(days=i)

        mask = np.ones((8, 10), bool)
        op = ProsailOperator()
        prior = sail_prior()
        mean = np.asarray(prior.prior.mean)
        truth = np.broadcast_to(mean, mask.shape + (10,)).copy()
        truth[..., 6] = np.exp(-3.0 / 2)       # LAI 3   (prior: 4)
        truth[..., 1] = np.exp(-55.0 / 100)    # Cab 55  (prior: 60)
        aux = ProsailAux(
            sza=jnp.asarray(30.0), vza=jnp.asarray(5.0),
            raa=jnp.asarray(80.0),
        )
        obs = SyntheticObservations(
            dates=[day(i) for i in (1, 3, 5)], operator=op,
            truth_fn=lambda d: truth, sigma=0.004, mask_prob=0.05,
            aux_fn=lambda d, g: aux,
        )
        kf = KalmanFilter(
            obs, MemoryOutput(), mask, PROSAIL_PARAMETER_LIST,
            state_propagation=None, prior=prior, pad_multiple=128,
            solver_options={"relaxation": 0.7, "max_iterations": 40},
        )
        kf.set_trajectory_uncertainty(np.zeros(10))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        x_a, _, _ = kf.run(
            [day(0), day(2), day(4), day(6)], x0, None, p_inv0
        )
        x = np.asarray(x_a)[: kf.gather.n_valid]
        # Invert with the OPERATOR's own transform so the check can
        # never drift from the production convention.
        from kafka_tpu.obsops.prosail import inverse_transforms

        physical = np.stack([
            np.asarray(jnp.stack(inverse_transforms(jnp.asarray(row))))
            for row in x
        ])
        lai, cab = physical[:, 6], physical[:, 1]
        assert abs(float(np.median(lai)) - 3.0) < 0.3
        assert abs(float(np.median(cab)) - 55.0) < 3.0
