"""Reanalysis subsystem (ISSUE 17): the RTS smoother over the
checkpoint chain and the ``smoothed=true`` request kind.

Acceptance pins:

- chain-walk regression: ``list_checkpoints``/``_scan_sets`` are
  chronological regardless of save order or shard count, and the
  newest->oldest walk skips corrupt/incomplete sets with the same
  counted fallback ``load_latest`` uses — including a corrupt NEWEST
  set (the smoother anchors one set earlier, exactly like resume);
- smoother parity: the newest date is BIT-IDENTICAL to the filter
  analysis, mid-series smoothed sigma is pixelwise <= the filter's,
  and the jitted sweep matches the dense float64 NumPy RTS oracle in
  the identity-operator linear regime;
- pre-sidecar compatibility: checkpoint sets saved without the
  forecast sidecar still resume the filter AND feed the smoother via
  the propagator fallback (``rederived`` populated, never a failure);
- serving: a ``smoothed=true`` response from the warm chain equals the
  offline ``kafka-smooth`` output bit-for-bit (the shared
  ``state_sha256`` digest), smoothed answers are never cached, and the
  quality ledger/report score the reanalysis pass separately.

All tier-1 / CPU.
"""

import datetime
import os
import time

import numpy as np
import pytest

from kafka_tpu import telemetry
from kafka_tpu.core import propagate_information_filter
from kafka_tpu.engine import Checkpointer
from kafka_tpu.engine.checkpoint import SIDECAR_SCHEMA, pack_tril
from kafka_tpu.serve import (
    AssimilationService,
    BadRequest,
    TileSession,
    make_synthetic_tile,
    parse_request,
    read_response,
    synthetic_dates,
)
from kafka_tpu.serve.session import UnknownDateError
from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
from kafka_tpu.smoother import (
    QA_REDERIVED,
    QA_SMOOTHED,
    QA_TERMINAL,
    ChainNode,
    SmootherError,
    load_chain,
    smooth_chain,
    smooth_checkpoints,
    state_sha256,
)
from kafka_tpu.telemetry import MetricsRegistry, quality
from kafka_tpu.testing.oracle import rts_smoother_np

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the default synthetic tile's observation calendar.
DATES = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)


def day(i):
    return datetime.datetime(2017, 7, 1) + datetime.timedelta(days=i)


def _spd(rng, n_pix, p):
    """Batch of well-conditioned SPD information matrices."""
    a = rng.normal(size=(n_pix, p, p))
    return (np.einsum("nij,nkj->nik", a, a)
            + 3.0 * np.eye(p)).astype(np.float64)


def _save_states(ck, timesteps, n_pix=6, p=2, seed=0, sidecar=False):
    """Save one deterministic analysis state per timestep; returns the
    per-timestep ``(x, p_inv)`` pairs keyed by timestep."""
    rng = np.random.default_rng(seed)
    saved = {}
    for ts in timesteps:
        x = rng.normal(size=(n_pix, p)).astype(np.float32)
        p_inv = _spd(rng, n_pix, p).astype(np.float32)
        extra = {}
        if sidecar:
            extra = dict(
                x_forecast=rng.normal(size=(n_pix, p)).astype(np.float32),
                p_forecast_inverse=_spd(rng, n_pix, p).astype(np.float32),
            )
        ck.save(ts, x, p_inv, **extra)
        saved[ts] = (x, p_inv, extra or None)
        # mtime separation so the most-recently-written-set-wins rule
        # in _scan_sets is deterministic on coarse-mtime filesystems
        time.sleep(0.01)
    return saved


# ---------------------------------------------------------------------------
# Satellite: chain-walk ordering regression (the smoother's foundation)
# ---------------------------------------------------------------------------

class TestChainWalkOrdering:
    def test_list_checkpoints_chronological_regardless_of_save_order(
            self, tmp_path):
        ck = Checkpointer(str(tmp_path))
        _save_states(ck, [day(9), day(1), day(5)])
        assert [ts for ts, _ in ck.list_checkpoints()] == \
            [day(1), day(5), day(9)]
        assert [ts for ts, _, _ in ck._scan_sets()] == \
            [day(1), day(5), day(9)]

    def test_multi_shard_sets_are_chronological_and_complete(
            self, tmp_path):
        ck = Checkpointer(str(tmp_path), n_shards=3)
        _save_states(ck, [day(5), day(1), day(9)], n_pix=9)
        listed = ck.list_checkpoints()
        assert [ts for ts, _ in listed] == [day(1), day(5), day(9)]
        for _, paths in listed:
            assert len(paths) == 3
            # shard files in shard order, never lexicographic accident
            assert [f"shard{k}of3" in os.path.basename(q)
                    for k, q in enumerate(paths)] == [True] * 3

    def test_reverse_scan_is_the_newest_first_walk(self, tmp_path):
        """``load_latest`` and ``load_chain`` both walk
        ``reversed(_scan_sets())`` — pin that this IS newest-first."""
        ck = Checkpointer(str(tmp_path))
        _save_states(ck, [day(1), day(5), day(9)])
        walked = [ts for ts, _, _ in reversed(ck._scan_sets())]
        assert walked == [day(9), day(5), day(1)]

    def test_load_chain_skips_corrupt_newest_and_anchors_earlier(
            self, tmp_path):
        """The smoother's corrupt-NEWEST fallback, in reverse of the
        resume test: truncate one shard of the newest set; the chain
        anchors at the previous intact set, the skipped timestep is
        reported, and the unreadable counter fires once."""
        ck = Checkpointer(str(tmp_path), n_shards=2)
        _save_states(ck, [day(1), day(5), day(9)], n_pix=8)
        newest_paths = ck.list_checkpoints()[-1][1]
        with open(newest_paths[0], "r+b") as f:
            f.truncate(40)
        with telemetry.use(MetricsRegistry()) as reg:
            nodes, skipped = load_chain(ck)
            assert reg.value("kafka_checkpoint_unreadable_total") == 1
        assert [n.timestep for n in nodes] == [day(1), day(5)]
        assert skipped == [day(9)]
        # load_latest agrees: the same set anchors a resume.
        latest = ck.load_latest()
        assert latest is not None and latest[0] == day(5)
        np.testing.assert_array_equal(latest[1], nodes[-1].x_analysis)

    def test_load_chain_skips_incomplete_middle_set(self, tmp_path):
        """A missing shard (crash between shard writes) in the MIDDLE of
        the chain: the walk bridges it, surviving neighbours intact."""
        ck = Checkpointer(str(tmp_path), n_shards=2)
        saved = _save_states(ck, [day(1), day(5), day(9)], n_pix=8)
        middle_paths = ck.list_checkpoints()[1][1]
        os.remove(middle_paths[1])
        with telemetry.use(MetricsRegistry()) as reg:
            nodes, skipped = load_chain(ck)
            assert reg.value("kafka_checkpoint_unreadable_total") == 1
        assert [n.timestep for n in nodes] == [day(1), day(9)]
        assert skipped == [day(5)]
        np.testing.assert_array_equal(
            nodes[1].x_analysis, saved[day(9)][0]
        )


# ---------------------------------------------------------------------------
# Sidecar schema: roundtrip, pre-sidecar compatibility, unknown schema
# ---------------------------------------------------------------------------

class TestForecastSidecar:
    def test_sidecar_roundtrips_through_sharded_sets(self, tmp_path):
        ck = Checkpointer(str(tmp_path), n_shards=2)
        saved = _save_states(ck, [day(1), day(5)], n_pix=8,
                             sidecar=True)
        nodes, skipped = load_chain(ck)
        assert skipped == []
        for node in nodes:
            assert node.sidecar is not None
            xf, pf_inv = node.sidecar
            want = saved[node.timestep][2]
            np.testing.assert_array_equal(xf, want["x_forecast"])
            np.testing.assert_array_equal(
                pf_inv, want["p_forecast_inverse"]
            )

    def test_pre_sidecar_sets_resume_and_smooth_via_fallback(
            self, tmp_path):
        """The back-compat acceptance pin: sets saved WITHOUT the
        sidecar (the pre-ISSUE-17 writer) still resume the filter and
        still smooth — every pair re-derived through the propagator,
        never a load failure."""
        ck = Checkpointer(str(tmp_path))
        _save_states(ck, [day(1), day(5), day(9)])
        assert ck.load_latest() is not None  # the filter resumes
        nodes, _ = load_chain(ck)
        assert all(n.sidecar is None for n in nodes)
        # No fallback configuration -> a diagnosable error, not garbage.
        with pytest.raises(SmootherError, match="no forecast sidecar"):
            smooth_chain(nodes)
        with telemetry.use(MetricsRegistry()) as reg:
            result = smooth_checkpoints(ck, q_diag=np.float32(1e-3))
            assert reg.value("kafka_smoother_rederived_total") == 2
        assert result.rederived == [day(5), day(9)]
        assert bool(np.all(result.qa[1] & QA_REDERIVED))
        # The newest-date passthrough holds on the fallback path too.
        assert bool(np.all(result.qa[-1] & QA_TERMINAL))

    def test_unknown_sidecar_schema_degrades_to_no_sidecar(
            self, tmp_path):
        """A FUTURE schema number must read as "no sidecar" (propagator
        fallback), never as a load failure."""
        ck = Checkpointer(str(tmp_path))
        rng = np.random.default_rng(3)
        x = rng.normal(size=(4, 2)).astype(np.float32)
        p_inv = _spd(rng, 4, 2).astype(np.float32)
        path = os.path.join(str(tmp_path), "state_20170701T000000.npz")
        np.savez_compressed(
            path, x_analysis=x, p_inv_tril=pack_tril(p_inv),
            p=np.int64(2), x_forecast=x,
            f_inv_tril=pack_tril(p_inv), f_p=np.int64(2),
            sidecar=np.int64(SIDECAR_SCHEMA + 41),
        )
        nodes, skipped = load_chain(ck)
        assert skipped == []
        assert len(nodes) == 1 and nodes[0].sidecar is None
        np.testing.assert_array_equal(nodes[0].x_analysis, x)


# ---------------------------------------------------------------------------
# Satellite: smoother parity pins
# ---------------------------------------------------------------------------

def _simulate_linear_filter(t_total=5, n_pix=6, p=3, seed=7):
    """A consistent identity-operator linear Kalman filter in float64:
    the regime where the RTS recursion's invariants hold exactly, so
    the jitted sweep can be pinned against the dense oracle."""
    rng = np.random.default_rng(seed)
    q = np.array([1e-2, 5e-3, 2e-2])[:p]
    r_inv = 4.0
    x_a = rng.normal(size=(n_pix, p))
    p_a_inv = _spd(rng, n_pix, p)
    xs_a, ps_a_inv = [x_a], [p_a_inv]
    xs_f = [np.zeros((n_pix, p))]
    ps_f_inv = [np.stack([np.eye(p)] * n_pix)]
    for _ in range(t_total - 1):
        p_f = np.linalg.inv(p_a_inv) + np.diag(q)
        p_f_inv = np.linalg.inv(p_f)
        x_f = x_a.copy()  # M = I
        y = x_f + rng.normal(size=(n_pix, p)) * 0.3
        p_a_inv = p_f_inv + r_inv * np.eye(p)
        rhs = np.einsum("nij,nj->ni", p_f_inv, x_f) + r_inv * y
        x_a = np.linalg.solve(p_a_inv, rhs[..., None])[..., 0]
        xs_a.append(x_a)
        ps_a_inv.append(p_a_inv)
        xs_f.append(x_f)
        ps_f_inv.append(p_f_inv)
    return (np.stack(xs_a), np.stack(ps_a_inv),
            np.stack(xs_f), np.stack(ps_f_inv))


class TestSmootherParity:
    def test_sweep_matches_dense_numpy_oracle(self):
        """Identity-operator linear regime: the jitted float32 sweep
        against ``rts_smoother_np`` (dense float64) on the SAME
        float32-rounded inputs."""
        x_a, p_a_inv, x_f, p_f_inv = _simulate_linear_filter()
        x_a32 = x_a.astype(np.float32)
        pa32 = p_a_inv.astype(np.float32)
        xf32 = x_f.astype(np.float32)
        pf32 = p_f_inv.astype(np.float32)
        nodes = [
            ChainNode(day(1 + 4 * t), x_a32[t], pa32[t],
                      sidecar=(xf32[t], pf32[t]) if t else None)
            for t in range(len(x_a32))
        ]
        result = smooth_chain(nodes)
        assert result.rederived == []
        x_oracle, p_oracle = rts_smoother_np(
            x_a32.astype(np.float64), pa32.astype(np.float64),
            xf32.astype(np.float64), pf32.astype(np.float64),
            np.eye(x_a32.shape[-1]),
        )
        np.testing.assert_allclose(
            result.x_smoothed, x_oracle, rtol=1e-3, atol=1e-4
        )
        diag_oracle = np.diagonal(
            np.linalg.inv(p_oracle), axis1=-2, axis2=-1
        )
        np.testing.assert_allclose(
            result.p_inv_diag, diag_oracle, rtol=2e-3
        )

    def test_final_date_bit_identical_and_sigma_never_larger(self):
        x_a, p_a_inv, x_f, p_f_inv = _simulate_linear_filter(seed=11)
        x_a32 = x_a.astype(np.float32)
        pa32 = p_a_inv.astype(np.float32)
        nodes = [
            ChainNode(day(1 + 4 * t), x_a32[t], pa32[t],
                      sidecar=(x_f[t].astype(np.float32),
                               p_f_inv[t].astype(np.float32))
                      if t else None)
            for t in range(len(x_a32))
        ]
        result = smooth_chain(nodes)
        # Newest date: EXACT passthrough of the filter analysis.
        np.testing.assert_array_equal(result.x_smoothed[-1], x_a32[-1])
        assert bool(np.all(result.qa[-1] & QA_TERMINAL))
        assert bool(np.all(result.qa & QA_SMOOTHED))
        # Smoothing adds information: pixelwise, every date, every param.
        assert bool(np.all(
            result.p_inv_diag >= result.p_inv_diag_filter
        ))
        # ...which the ledger signal and verdict reflect mid-series.
        for t in range(len(nodes) - 1):
            shrink = result.sigma_shrink(t)
            assert all(v <= 1.0 + 1e-3 for v in shrink if np.isfinite(v))
            assert quality.smoothed_verdict_for(shrink) == \
                quality.CONSISTENT

    def test_rederived_forecast_matches_sidecar_from_same_propagator(
            self):
        """When the sidecar was produced by the same propagator the
        fallback re-runs, stripping the sidecars changes NOTHING: the
        bridge is exact, down to the bit."""
        import jax.numpy as jnp

        x_a, p_a_inv, _, _ = _simulate_linear_filter(seed=13)
        x_a32 = x_a.astype(np.float32)
        pa32 = p_a_inv.astype(np.float32)
        p = x_a32.shape[-1]
        q = np.full(p, 1e-3, np.float32)
        nodes = [ChainNode(day(1), x_a32[0], pa32[0])]
        for t in range(1, len(x_a32)):
            xf, _, pf_inv = propagate_information_filter(
                jnp.asarray(x_a32[t - 1]), None,
                jnp.asarray(pa32[t - 1]),
                jnp.eye(p, dtype=jnp.float32), jnp.asarray(q),
            )
            nodes.append(ChainNode(
                day(1 + 4 * t), x_a32[t], pa32[t],
                sidecar=(np.asarray(xf), np.asarray(pf_inv)),
            ))
        with_sidecar = smooth_chain(nodes)
        stripped = [ChainNode(n.timestep, n.x_analysis,
                              n.p_analysis_inverse) for n in nodes]
        rederived = smooth_chain(stripped, q_diag=q)
        assert with_sidecar.rederived == []
        assert rederived.rederived == [n.timestep for n in nodes[1:]]
        np.testing.assert_array_equal(
            with_sidecar.x_smoothed, rederived.x_smoothed
        )
        np.testing.assert_array_equal(
            with_sidecar.p_inv_diag, rederived.p_inv_diag
        )

    def test_single_node_chain_is_the_analysis(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 2)).astype(np.float32)
        p_inv = _spd(rng, 4, 2).astype(np.float32)
        result = smooth_chain([ChainNode(day(1), x, p_inv)])
        np.testing.assert_array_equal(result.x_smoothed[0], x)
        assert bool(np.all(result.qa[0] & QA_TERMINAL))

    def test_real_chain_newest_equals_filter_analysis(self, tmp_path):
        """Over a REAL forward run's chain (sidecars written by the
        engine): the smoothed newest date is bit-identical to the
        checkpointed filter analysis, and no pair needs the fallback."""
        with telemetry.use(MetricsRegistry()):
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ck")))
            sess.serve(DATES[6])
            result = smooth_checkpoints(sess.checkpointer)
        assert result.rederived == [] and result.skipped == []
        ts, x_latest, p_inv_latest = sess.checkpointer.load_latest()
        assert result.timesteps[-1] == ts
        np.testing.assert_array_equal(
            result.x_smoothed[-1], np.asarray(x_latest, np.float32)
        )
        np.testing.assert_array_equal(
            result.p_inv_diag[-1],
            np.diagonal(p_inv_latest, axis1=-2, axis2=-1).astype(
                np.float32),
        )
        assert bool(np.all(
            result.p_inv_diag >= result.p_inv_diag_filter
        ))


# ---------------------------------------------------------------------------
# The smoothed=true request kind (serve path + offline CLI parity)
# ---------------------------------------------------------------------------

def _await_response(root, rid, timeout=120.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        got = read_response(root, rid)
        if got is not None:
            return got
        time.sleep(0.05)
    raise AssertionError(f"no response for {rid} within {timeout}s")


class TestSmoothedServe:
    def test_smoothed_flag_parses_and_rejects_non_bool(self):
        req = parse_request({
            "tile": "t", "date": "2017-07-05", "smoothed": True,
        })
        assert req.smoothed is True
        assert req.payload()["smoothed"] is True
        base = parse_request({"tile": "t", "date": "2017-07-05"})
        assert base.smoothed is False
        assert "smoothed" not in base.payload()
        with pytest.raises(BadRequest, match="smoothed"):
            parse_request({
                "tile": "t", "date": "2017-07-05", "smoothed": "yes",
            })

    def test_serve_matches_offline_cli_bit_identical(self, tmp_path):
        """THE acceptance pin: the warm-chain smoothed response and the
        offline ``kafka-smooth`` run report the same ``state_sha256``
        for the same date — the same jitted program over the same
        checkpoint bytes."""
        from kafka_tpu.cli import kafka_smooth

        with telemetry.use(MetricsRegistry()):
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ck")))
            sess.serve(DATES[6])
            body = sess.serve(DATES[4], smoothed=True)
        assert body["served_from"] == "smoothed_chain"
        assert body["smoothed"] is True
        assert body["windows_run"] == 0  # read work, no forward windows
        assert body["quality"]["verdict"] == quality.CONSISTENT

        with telemetry.use(MetricsRegistry()):
            summary = kafka_smooth.main([
                "--ckpt-dir", str(tmp_path / "ck"),
                "--ny", "20", "--nx", "20",
                "--propagator", "approx", "--q", "1e-3",
                "--outdir", str(tmp_path / "out"),
            ])
        assert "failed" not in summary
        assert summary["windows"] == body["windows_smoothed"]
        assert summary["dates"][body["timestep"]]["x_sha256"] == \
            body["x_sha256"]
        # The product set landed: per-date smoothed mean + sigma planes
        # and the QA twin.
        names = os.listdir(str(tmp_path / "out"))
        assert summary["outputs_written"] > 0
        assert any(n.endswith("_smoothed.tif") for n in names)
        assert any(n.endswith("_smoothed_unc.tif") for n in names)
        assert any(n.startswith("solver_qa_") for n in names)

    def test_smoothed_requests_route_but_are_never_cached(
            self, tmp_path):
        """Through the full service: a smoothed request is routable
        read work, its response carries the reanalysis identity, and a
        repeat is re-solved (never answered from the response cache) —
        while the forward answer for the same tile IS cached."""
        with telemetry.use(MetricsRegistry()):
            spec = make_synthetic_tile("t", str(tmp_path / "ck"))
            svc = AssimilationService(
                {"t": TileSession(spec)}, str(tmp_path)
            ).start()
            try:
                svc.submit({"request_id": "fwd0", "tile": "t",
                            "date": DATES[6].isoformat()})
                assert _await_response(
                    str(tmp_path), "fwd0")["status"] == "ok"
                for rid in ("rs1", "rs2"):
                    svc.submit({"request_id": rid, "tile": "t",
                                "date": DATES[4].isoformat(),
                                "smoothed": True})
                r1 = _await_response(str(tmp_path), "rs1")
                r2 = _await_response(str(tmp_path), "rs2")
                svc.submit({"request_id": "fwd1", "tile": "t",
                            "date": DATES[6].isoformat()})
                fwd_again = _await_response(str(tmp_path), "fwd1")
            finally:
                svc.close()
        assert r1["status"] == "ok" and r1["smoothed"] is True
        assert r1["served_from"] == "smoothed_chain"
        # The repeat re-solved from the chain — not "cache".
        assert r2["served_from"] == "smoothed_chain"
        assert r1["x_sha256"] == r2["x_sha256"]
        # Forward caching is untouched by the new kind.
        assert fwd_again["served_from"] == "cache"

    def test_smoothed_without_chain_or_beyond_it_is_unknown_date(
            self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            sess = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ck")))
            with pytest.raises(UnknownDateError,
                               match="no smoothable checkpoint chain"):
                sess.serve(DATES[4], smoothed=True)
            sess.serve(DATES[2])
            with pytest.raises(UnknownDateError,
                               match="serve the date forward first"):
                sess.serve(DATES[6], smoothed=True)

    def test_state_sha256_is_layout_stable(self):
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        assert state_sha256(x) == state_sha256(x[::1].copy())
        assert state_sha256(x) == state_sha256(
            np.asarray(x, np.float64))  # cast-stable: hashes f32 bytes
        assert state_sha256(x) != state_sha256(x + 1)


# ---------------------------------------------------------------------------
# Quality: the reanalysis pass is scored on its own timeline
# ---------------------------------------------------------------------------

class TestSmoothedQuality:
    def test_ledger_and_report_score_passes_separately(self, tmp_path):
        import tools.quality_report as qr

        with telemetry.use(MetricsRegistry()):
            ledger = quality.QualityLedger(directory=str(tmp_path))
            ledger.record_window(
                "2017-07-05", [1.0, 1.1], n_valid=9, prefix="tile:t",
            )
            ledger.record_smoothed(
                "2017-07-05", [0.8, 0.9], n_valid=9, prefix="tile:t",
            )
            ledger.record_smoothed(
                "2017-07-09", [1.4, 0.9], n_valid=9, prefix="tile:t",
            )
        report = qr.build_report([os.path.join(str(tmp_path),
                                               "quality.jsonl")])
        tiles = report["tiles"]
        assert set(tiles) == {"tile:t", "tile:t [smoothed]"}
        smoothed = tiles["tile:t [smoothed]"]["dates"]
        assert [d["verdict"] for d in smoothed] == \
            [quality.CONSISTENT, quality.OVERCONFIDENT]
        # Recomputed from the ledger alone (self-containment pin): the
        # sigma-shrink scoring reproduces the recorded verdicts.
        assert all(d["recomputed"] == d["verdict"] for d in smoothed)
        forward = tiles["tile:t"]["dates"]
        assert [d["verdict"] for d in forward] == [quality.CONSISTENT]
