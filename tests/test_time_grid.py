"""Time-grid windowing — the fixed version of the reference's
``tests/test_utils.py`` (broken import of ``kafka.utils``; SURVEY.md §4),
with its exact scenario preserved."""

import datetime

from kafka_tpu.core import iterate_time_grid


def test_iterate_time_grid_reference_scenario():
    base = datetime.datetime(2007, 7, 1)
    time_grid = [base + i * datetime.timedelta(days=1) for i in range(0, 60, 16)]
    base = datetime.datetime(2007, 1, 1)
    the_dates = [
        base + i * datetime.timedelta(days=1) for i in range(1, 365 + 8, 8)
    ]
    expected_steps = [
        datetime.datetime(2007, 7, 17),
        datetime.datetime(2007, 8, 2),
        datetime.datetime(2007, 8, 18),
    ]
    expected_obs = [
        [datetime.datetime(2007, 7, 5), datetime.datetime(2007, 7, 13)],
        [datetime.datetime(2007, 7, 21), datetime.datetime(2007, 7, 29)],
        [datetime.datetime(2007, 8, 6), datetime.datetime(2007, 8, 14)],
    ]
    out = list(iterate_time_grid(time_grid, the_dates))
    assert [o[0] for o in out] == expected_steps
    assert [o[1] for o in out] == expected_obs
    assert [o[2] for o in out] == [True, False, False]


def test_first_flag_and_empty_windows():
    grid = [0, 10, 20, 30]
    dates = [12, 15]
    out = list(iterate_time_grid(grid, dates))
    assert out == [(10, [], True), (20, [12, 15], False), (30, [], False)]
