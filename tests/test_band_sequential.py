"""The reference's legacy band-sequential assimilation path
(``linear_kf.py:325-425``): per-band Gauss-Newton with posterior->prior
chaining between bands.  For LINEAR operators sequential conditioning is
mathematically identical to the joint update (Gaussian information
adds); for nonlinear operators it is order-dependent — exactly the
reference's semantics.
"""

import datetime

import jax.numpy as jnp
import numpy as np

from kafka_tpu.core.propagators import PixelPrior, tip_prior
from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
from kafka_tpu.engine.priors import TIP_PARAMETER_LIST
from kafka_tpu.obsops import IdentityOperator, TwoStreamOperator
from kafka_tpu.testing import MemoryOutput, SyntheticObservations

RNG = np.random.default_rng(9)


def day(i):
    return datetime.datetime(2020, 6, 1) + datetime.timedelta(days=i)


def circle_mask(ny=10, nx=12, r=4):
    yy, xx = np.mgrid[:ny, :nx]
    return (yy - ny / 2) ** 2 + (xx - nx / 2) ** 2 < r**2


def _run(op, truth, prior, params, band_sequential, mask,
         solver_options=None, hessian_correction=False):
    obs = SyntheticObservations(
        dates=[day(1), day(2)], operator=op,
        truth_fn=lambda date: truth, sigma=0.01, mask_prob=0.1,
    )
    out = MemoryOutput()
    kf = KalmanFilter(
        obs, out, mask, params,
        state_propagation=None, prior=prior, pad_multiple=128,
        band_sequential=band_sequential, scan_window=8,
        solver_options=solver_options,
        hessian_correction=hessian_correction,
    )
    kf.set_trajectory_uncertainty(np.zeros(len(params)))
    x0, p_inv0 = prior.process_prior(None, kf.gather)
    x_a, _, p_inv_a = kf.run([day(0), day(3)], x0, None, p_inv0)
    return kf, out, np.asarray(x_a), np.asarray(p_inv_a)


class TestBandSequential:
    def test_linear_operator_sequential_equals_joint(self):
        """Gaussian information is additive: for a LINEAR operator the
        band-by-band chain must equal the joint update to float
        precision."""
        mask = circle_mask()
        p = 3
        op = IdentityOperator(n_params=p, obs_indices=(0, 1, 2))
        truth = RNG.uniform(0.3, 0.7, mask.shape + (p,)).astype(
            np.float32
        )
        cov = np.diag(np.full(p, 0.25)).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.full((p,), 0.5), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            ("a", "b", "c"),
        )
        kf_s, out_s, x_s, pinv_s = _run(
            op, truth, prior, ("a", "b", "c"), True, mask
        )
        kf_j, out_j, x_j, pinv_j = _run(
            op, truth, prior, ("a", "b", "c"), False, mask
        )
        np.testing.assert_allclose(x_s, x_j, atol=5e-5)
        np.testing.assert_allclose(pinv_s, pinv_j, rtol=1e-4, atol=1e-3)
        for ts in out_j.output:
            for key in out_j.output[ts]:
                np.testing.assert_allclose(
                    out_s.output[ts][key], out_j.output[ts][key],
                    atol=1e-4, err_msg=f"{ts} {key}",
                )

    def test_fusion_disabled_under_band_sequential(self):
        mask = circle_mask()
        op = IdentityOperator(n_params=2, obs_indices=(0, 1))
        truth = np.full(mask.shape + (2,), 0.5, np.float32)
        cov = np.diag([0.1, 0.1]).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.full((2,), 0.5), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            ("a", "b"),
        )
        kf, out, _, _ = _run(op, truth, prior, ("a", "b"), True, mask)
        assert not any(r.get("fused") for r in kf.diagnostics_log)

    def test_nonlinear_two_stream_converges_finite(self):
        """The TIP problem through the sequential path: per-band GN
        loops run, outputs finite, TLAI pulled towards truth."""
        mask = circle_mask()
        op = TwoStreamOperator()
        base = np.asarray(tip_prior().mean)
        truth = np.broadcast_to(base, mask.shape + (7,)).copy()
        truth[..., 6] = 0.45
        basep = tip_prior()
        mean = np.asarray(basep.mean)
        sigma = np.full(7, 0.01, np.float32)
        sigma[6] = 0.5
        cov = np.diag(sigma**2).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.asarray(mean), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            TIP_PARAMETER_LIST,
        )
        kf, out, x_a, pinv_a = _run(
            op, truth, prior, TIP_PARAMETER_LIST, True, mask,
            solver_options={"relaxation": 0.7, "max_iterations": 40},
        )
        assert np.isfinite(x_a).all() and np.isfinite(pinv_a).all()
        tlai = out.output[day(3)]["TeLAI"][mask]
        # The legacy path conditions on ONE band at a time: each band's
        # own Gauss-Newton walk is far less constrained than the joint
        # update, so per-pixel scatter is wide (the reason the reference
        # moved its drivers to assimilate_multiple_bands).  Assert the
        # ensemble is pulled from the prior (0.368) towards the truth
        # (0.45) and stays in the physical domain — the exact-equality
        # correctness anchor is the linear test above.
        assert 0.39 < float(tlai.mean()) < 0.55
        assert ((tlai > 0.0) & (tlai < 1.0)).all()
        # iterations aggregate across both bands' loops
        assert all(
            r["n_iterations"] >= 4 for r in kf.diagnostics_log
        )

    def test_hessian_correction_runs_per_band(self):
        """Per-band Hessian correction on the LOOSE TIP prior — the
        regime where the reference's unguarded subtraction drives A off
        the PD cone and NaNs every later date (reproduced on the joint
        path too before the solver's eigenvalue floor landed).  Both
        paths must now stay finite."""
        mask = circle_mask(8, 8, 3)
        op = TwoStreamOperator()
        base = np.asarray(tip_prior().mean)
        truth = np.broadcast_to(base, mask.shape + (7,)).copy()
        prior = FixedGaussianPrior(tip_prior(), TIP_PARAMETER_LIST)
        for band_seq in (True, False):
            kf, out, x_a, pinv_a = _run(
                op, truth, prior, TIP_PARAMETER_LIST, band_seq, mask,
                solver_options={"relaxation": 0.7},
                hessian_correction=True,
            )
            assert np.isfinite(x_a).all(), band_seq
            assert np.isfinite(pinv_a).all(), band_seq


def test_linearize_only_operator_rejected_clearly():
    """A linearize-only operator must fail with a clear TypeError at the
    engine boundary, not an opaque NotImplementedError mid-trace."""
    import pytest

    from kafka_tpu.core.types import Linearization
    from kafka_tpu.obsops.protocol import ObservationModel

    class LinearizeOnly(ObservationModel):
        n_bands, n_params = 2, 2

        def linearize(self, aux, x):
            n = x.shape[0]
            return Linearization(
                h0=jnp.zeros((2, n)), jac=jnp.zeros((2, n, 2))
            )

    mask = circle_mask(6, 6, 2)
    op = LinearizeOnly()
    obs = SyntheticObservations(
        dates=[day(1)], operator=IdentityOperator(2, (0, 1)),
        truth_fn=lambda d: np.full(mask.shape + (2,), 0.5, np.float32),
        sigma=0.02,
    )
    kf = KalmanFilter(
        obs, MemoryOutput(), mask, ("a", "b"), band_sequential=True,
    )
    with pytest.raises(TypeError, match="forward_pixel"):
        kf._band_view(op, 0)
