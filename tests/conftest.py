"""Test configuration: force an 8-device CPU mesh for sharding tests.

Must run before the first ``import jax`` in any test module (pytest imports
conftest first).  The axon TPU plugin registers itself via sitecustomize and
pins the default backend, so tests always resolve devices explicitly through
``cpu_devices()`` below rather than relying on ``jax.devices()``.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

# Force the host platform even though the TPU plugin's sitecustomize pins
# itself as default: tests must neither compile on the real chip nor hang
# when the TPU tunnel is unhealthy.  This must run before any backend
# initialisation (first jax.devices()/computation), hence here in conftest.
try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

# Persistent compilation cache (same helper the drivers and bench.py call,
# scoped to the cpu-pinned configuration): the tier-1 suite is dominated
# by re-compiling the same solver/scan/jacfwd programs every run on this
# one-core host, and a warm cache turns those into disk hits.
from kafka_tpu.utils.compilation_cache import (  # noqa: E402
    enable_compilation_cache,
)

enable_compilation_cache()


def cpu_devices():
    return jax.devices("cpu")


@pytest.fixture(scope="session")
def eight_cpu_devices():
    devs = cpu_devices()
    if len(devs) < 8:
        pytest.skip("need 8 host-platform devices")
    return devs[:8]


@pytest.fixture(autouse=True)
def _default_to_cpu():
    # Keep every test on the host platform even when a TPU plugin is present.
    with jax.default_device(cpu_devices()[0]):
        yield
