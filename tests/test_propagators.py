"""Oracle tests of the five propagators, prior blending and the advance
dispatcher — the fixed versions of the reference's broken-at-import tests
(``tests/test_kf.py`` imported a nonexistent symbol; SURVEY.md §4)."""

import numpy as np
import jax.numpy as jnp

from kafka_tpu.core import (
    advance,
    batched_diagonal,
    blend_prior,
    broadcast_prior,
    make_no_propagation,
    propagate_information_filter,
    propagate_information_filter_approx,
    propagate_information_filter_lai,
    propagate_standard_kalman,
    tip_prior,
)
from kafka_tpu.testing import oracle

RNG = np.random.default_rng(7)


def random_spd(n_pix, p):
    w = RNG.normal(size=(n_pix, p, p)).astype(np.float32)
    return np.einsum("npq,nrq->npr", w, w) + 2.0 * np.eye(p, dtype=np.float32)


def test_standard_kalman_matches_reference_intent():
    """The hand-computed expectation of the reference's
    ``test_propagate_standard_kalman`` (tests/test_kf.py:19-27), batched."""
    x = jnp.ones((5, 3))
    p_mat = jnp.broadcast_to(jnp.eye(3), (5, 3, 3))
    m = 2.0 * jnp.eye(3)
    q = jnp.full((3,), 0.5)
    x_f, p_f, p_f_inv = propagate_standard_kalman(x, p_mat, None, m, q)
    np.testing.assert_allclose(np.asarray(x_f), 2.0 * np.ones((5, 3)))
    np.testing.assert_allclose(
        np.asarray(p_f), np.broadcast_to(1.5 * np.eye(3), (5, 3, 3))
    )
    assert p_f_inv is None


def test_information_filter_matches_reference_intent():
    """The reference's (broken-at-import) ``test_propagate_information_filter``
    (tests/test_kf.py:30-54) asserted the *diagonal-approximation* values and
    documented the exact matrix in a comment ("In reality, the matrix ought to
    be ...").  Both variants are pinned here: the approx propagator must give
    the asserted diagonal, the exact propagator the commented matrix."""
    prior = tip_prior()
    p_inv = jnp.asarray(prior.inv_cov)[None]
    x = jnp.asarray(prior.mean)[None]
    m = jnp.eye(7)
    q = jnp.full((7,), 0.1)
    _, _, p_f_inv = propagate_information_filter_approx(x, None, p_inv, m, q)
    np.testing.assert_allclose(
        np.asarray(batched_diagonal(p_f_inv))[0],
        np.array([8.74, 1.69, 9.81, 8.16, 0.43, 9.21, 2.86]),
        atol=0.01,
    )
    _, _, p_exact = propagate_information_filter(x, None, p_inv, m, q)
    np.testing.assert_allclose(
        np.asarray(batched_diagonal(p_exact))[0],
        np.array([8.74, 1.69, 9.33, 8.16, 0.43, 7.28, 2.86]),
        atol=0.01,
    )
    np.testing.assert_allclose(np.asarray(p_exact)[0, 2, 5], -1.13, atol=0.01)


def test_information_filter_matches_sparse_oracle():
    n_pix, p = 13, 7
    p_inv = random_spd(n_pix, p)
    q = RNG.uniform(0.01, 0.5, size=(p,)).astype(np.float32)
    _, _, out = propagate_information_filter(
        jnp.zeros((n_pix, p)), None, jnp.asarray(p_inv), jnp.eye(p),
        jnp.asarray(q),
    )
    ref = oracle.propagate_information_filter_np(p_inv, q)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_information_filter_approx_diagonal_formula():
    """Diagonal deflation D = 1/(1 + diag(P_inv) q), off-diagonals dropped
    (kf_tools.py:280-288)."""
    n_pix, p = 9, 5
    p_inv = random_spd(n_pix, p)
    q = np.full((p,), 0.2, np.float32)
    _, _, out = propagate_information_filter_approx(
        jnp.zeros((n_pix, p)), None, jnp.asarray(p_inv), jnp.eye(p),
        jnp.asarray(q),
    )
    d = np.einsum("npp->np", p_inv)
    expected = d * (1.0 / (1.0 + d * 0.2))
    np.testing.assert_allclose(
        np.asarray(batched_diagonal(out)), expected, rtol=1e-5
    )
    # off-diagonals zero
    off = np.asarray(out) - np.asarray(
        np.einsum("np,pq->npq", np.asarray(batched_diagonal(out)), np.eye(p))
    )
    np.testing.assert_allclose(off, 0.0, atol=1e-7)


def test_lai_propagator_resets_to_prior_and_inflates_lai():
    """kf_tools.py:292-314: non-LAI params reset to TIP prior; LAI mean kept;
    LAI information deflated by 1/((1/p) + q)."""
    prior = tip_prior()
    n_pix = 6
    x_a = RNG.normal(0.5, 0.1, size=(n_pix, 7)).astype(np.float32)
    p_inv = random_spd(n_pix, 7)
    q = np.zeros((7,), np.float32)
    q[6] = 0.04
    x_f, _, p_f_inv = propagate_information_filter_lai(
        jnp.asarray(x_a), None, jnp.asarray(p_inv), jnp.eye(7),
        jnp.asarray(q),
    )
    x_f = np.asarray(x_f)
    np.testing.assert_allclose(x_f[:, 6], x_a[:, 6], rtol=1e-6)
    for k in range(6):
        np.testing.assert_allclose(
            x_f[:, k], float(prior.mean[k]), rtol=1e-6
        )
    lai_info = np.einsum("npp->np", p_inv)[:, 6]
    expected = 1.0 / ((1.0 / lai_info) + 0.04)
    np.testing.assert_allclose(
        np.asarray(p_f_inv)[:, 6, 6], expected, rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(p_f_inv)[:, 0, 0], float(prior.inv_cov[0, 0]), rtol=1e-5
    )


def test_no_propagation_returns_tiled_prior():
    prior = tip_prior()
    prop = make_no_propagation(prior)
    x_f, _, p_f_inv = prop(
        jnp.zeros((4, 7)), None, jnp.zeros((4, 7, 7)), jnp.eye(7),
        jnp.zeros((7,)),
    )
    x0, p0 = broadcast_prior(prior, 4)
    np.testing.assert_allclose(np.asarray(x_f), np.asarray(x0))
    np.testing.assert_allclose(np.asarray(p_f_inv), np.asarray(p0))


def test_blend_prior_matches_sparse_oracle():
    n_pix, p = 8, 7
    p_inv = random_spd(n_pix, p)
    c_inv = random_spd(n_pix, p)
    x_f = RNG.normal(size=(n_pix, p)).astype(np.float32)
    mu = RNG.normal(size=(n_pix, p)).astype(np.float32)
    x_c, a_c = blend_prior(
        jnp.asarray(mu), jnp.asarray(c_inv), jnp.asarray(x_f),
        jnp.asarray(p_inv),
    )
    x_ref, _ = oracle.blend_prior_np(mu, c_inv, x_f, p_inv)
    np.testing.assert_allclose(
        np.asarray(x_c).ravel(), x_ref, rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(np.asarray(a_c), p_inv + c_inv, rtol=1e-5)


def test_advance_dispatcher_branches():
    """The four-way branch of propagate_and_blend_prior
    (kf_tools.py:136-171)."""
    n_pix, p = 3, 7
    x_a = jnp.ones((n_pix, p))
    p_inv = jnp.asarray(random_spd(n_pix, p))
    m = jnp.eye(p)
    q = jnp.full((p,), 0.1)
    prior = tip_prior()
    mu, c_inv = broadcast_prior(prior, n_pix)

    # propagator only
    x1, _, pi1 = advance(x_a, None, p_inv, m, q,
                         state_propagator=propagate_information_filter)
    assert x1.shape == (n_pix, p) and pi1.shape == (n_pix, p, p)
    # prior only
    x2, _, pi2 = advance(x_a, None, p_inv, m, q, prior_mean=mu,
                         prior_cov_inverse=c_inv)
    np.testing.assert_allclose(np.asarray(x2), np.asarray(mu))
    # both -> blend
    x3, _, pi3 = advance(x_a, None, p_inv, m, q, prior_mean=mu,
                         prior_cov_inverse=c_inv,
                         state_propagator=propagate_information_filter)
    np.testing.assert_allclose(
        np.asarray(pi3), np.asarray(pi1 + c_inv), rtol=1e-5
    )
    # neither
    assert advance(x_a, None, p_inv, m, q) == (None, None, None)


def test_blocked_lu_solve_matches_full():
    """solve_batched(block=...) — the HBM-bounded path the information
    propagator uses at tile scale — must match the one-shot LU, with
    identity padding keeping partial blocks non-singular."""
    import jax.numpy as jnp

    from kafka_tpu.core.linalg import solve_batched

    rng = np.random.default_rng(11)
    a = rng.normal(size=(37, 5, 5)).astype(np.float32) + \
        5 * np.eye(5, dtype=np.float32)
    b = rng.normal(size=(37, 5, 5)).astype(np.float32)
    full = np.asarray(solve_batched(jnp.asarray(a), jnp.asarray(b)))
    blk = np.asarray(solve_batched(jnp.asarray(a), jnp.asarray(b), block=8))
    np.testing.assert_allclose(blk, full, rtol=2e-4, atol=2e-5)
