"""Elastic serving fleet (ISSUE 13): consistent-hash tile routing,
fleet-aware failover, warm-state replica migration, and the chaos
acceptance test.

Acceptance pins:

- the ring is STABLE (pinned digests — builtin ``hash()`` would shred
  cross-process agreement) and rebalances MINIMALLY: adding a replica
  moves only the tiles the new replica now owns, removing it restores
  the previous ownership exactly;
- a tile re-assigned to a fresh replica resumes WARM from the shared
  checkpoint set with output bit-identical (unfused CPU) to the
  original owner's uninterrupted run;
- chaos: loadgen against a 3-replica fleet behind ``kafka-route``, one
  replica SIGKILLed mid-request -> the router flags it dead within one
  heartbeat TTL and re-routes, zero admitted requests are lost, the
  re-served output equals the uninterrupted run's, and the
  serve_fleet_* BENCH rows emit and gate in bench_compare.

All tier-1 / CPU.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from kafka_tpu import telemetry
from kafka_tpu.serve import (
    AdmissionController,
    AdmissionPolicy,
    AssimilationService,
    HashRing,
    RequestJournal,
    RoutePolicy,
    ServeDaemon,
    TileRouter,
    TileSession,
    make_synthetic_tile,
    read_response,
    stable_hash,
    submit_request,
    synthetic_dates,
)
from kafka_tpu.serve.router import FleetWatch
from kafka_tpu.serve.synthetic import DEFAULT_BASE_DATE
from kafka_tpu.telemetry import MetricsRegistry

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DATES = synthetic_dates(DEFAULT_BASE_DATE, 16, 2)

TILES_30 = [f"tile{i}" for i in range(30)]


def _subprocess_env():
    from kafka_tpu.resilience import faults

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["KAFKA_TPU_LIVE_INTERVAL_S"] = "0.2"
    env.pop(faults.ENV_VAR, None)
    return env


class StubSession:
    """Duck-typed tile session (no JAX) for router-mechanics tests."""

    def __init__(self, name):
        self.name = name
        self.serves = 0

    def serve(self, date):
        self.serves += 1
        return {"status": "ok", "tile": self.name,
                "date": date.isoformat(), "x_sha256": f"stub-{self.name}"}


class StubFleet:
    """N in-process stub replicas (daemon threads) + helpers."""

    def __init__(self, tmp_path, n=2, tiles=4, policies=None):
        self.roots = {}
        self.daemons = []
        self.threads = []
        self.sessions = {}
        for i in range(n):
            rid = f"rep{i}"
            root = str(tmp_path / rid)
            sessions = {f"tile{t}": StubSession(f"tile{t}")
                        for t in range(tiles)}
            self.sessions[rid] = sessions
            policy = (policies or {}).get(
                rid, AdmissionPolicy(max_queue_depth=64)
            )
            svc = AssimilationService(sessions, root, policy=policy)
            d = ServeDaemon(svc, root, poll_interval_s=0.01)
            self.daemons.append(d)
            self.roots[rid] = root
            self.threads.append(threading.Thread(
                target=d.run, name=f"stub-{rid}", daemon=True,
            ))

    def start(self):
        for t in self.threads:
            t.start()
        return self

    def stop(self):
        for d in self.daemons:
            d.drain()
        for t in self.threads:
            t.join(timeout=60)


def run_router(router):
    t = threading.Thread(target=router.run, name="router", daemon=True)
    t.start()
    return t


def wait_response(root, rid, timeout_s=30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        got = read_response(root, rid)
        if got is not None:
            return got
        time.sleep(0.01)
    return None


# ---------------------------------------------------------------------------
# the stable hash + ring
# ---------------------------------------------------------------------------

class TestStableHash:
    def test_pinned_cross_process_values(self):
        """The digests are PINNED: any change here re-shuffles every
        deployed fleet's keyspace (and builtin hash() could never pin —
        it is salted per process)."""
        assert stable_hash("tile0") == 18108283901022872304
        assert stable_hash("rep0#0") == 245196271913887815
        assert stable_hash("") == 16476032584258269876

    def test_distinct_and_64bit(self):
        vals = {stable_hash(t) for t in TILES_30}
        assert len(vals) == len(TILES_30)
        assert all(0 <= v < 2 ** 64 for v in vals)


class TestHashRing:
    def test_owner_deterministic_and_covering(self):
        ring = HashRing(["a", "b", "c"])
        asg = ring.assignments(TILES_30)
        assert sorted(sum(asg.values(), [])) == sorted(TILES_30)
        # Every replica owns a share (vnodes spread the segments).
        assert all(asg[r] for r in ("a", "b", "c"))
        ring2 = HashRing(["c", "a", "b"])  # insertion order irrelevant
        assert {t: ring2.owner(t) for t in TILES_30} == \
            {t: ring.owner(t) for t in TILES_30}

    def test_join_moves_only_the_new_replicas_segments(self):
        """The consistent-hashing contract: adding a replica moves ONLY
        tiles the new replica now owns — no tile moves between the
        survivors."""
        ring = HashRing(["a", "b"])
        before = {t: ring.owner(t) for t in TILES_30}
        ring.add("c")
        after = {t: ring.owner(t) for t in TILES_30}
        moved = [t for t in TILES_30 if before[t] != after[t]]
        assert moved, "join moved nothing — ring is degenerate"
        assert all(after[t] == "c" for t in moved)
        # ...and only a minority segment moved, not a reshuffle.
        assert len(moved) < len(TILES_30) / 2

    def test_leave_restores_previous_ownership_exactly(self):
        ring = HashRing(["a", "b"])
        before = {t: ring.owner(t) for t in TILES_30}
        ring.add("c")
        ring.remove("c")
        assert {t: ring.owner(t) for t in TILES_30} == before

    def test_preference_walk_and_exclude(self):
        ring = HashRing(["a", "b", "c"])
        for t in TILES_30:
            pref = ring.preference(t)
            assert sorted(pref) == ["a", "b", "c"]
            assert ring.owner(t) == pref[0]
            assert ring.owner(t, exclude=[pref[0]]) == pref[1]
            assert ring.owner(t, exclude=pref) is None

    def test_empty_ring(self):
        ring = HashRing()
        assert ring.owner("tile0") is None
        assert ring.preference("tile0") == []


# ---------------------------------------------------------------------------
# retry_after_s backoff hints (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestRetryAfterHint:
    def test_load_state_rejections_carry_hint(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
                policy=AdmissionPolicy(max_queue_depth=0,
                                       retry_after_s=1.25),
            )
            try:
                ack = svc.submit({"tile": "t", "date": "2017-07-05",
                                  "request_id": "r1"})
                assert ack["reason"] == "queue_full"
                assert ack["retry_after_s"] == 1.25
                # ...and the hint reaches cross-process clients through
                # the response file.
                assert svc.journal.response("r1")["retry_after_s"] \
                    == 1.25
                svc.stop_admitting()
                drained = svc.submit({"tile": "t",
                                      "date": "2017-07-05",
                                      "request_id": "r2"})
                assert drained["reason"] == "draining"
                assert drained["retry_after_s"] == 1.25
            finally:
                svc.close()

    def test_request_shaped_rejections_carry_no_hint(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
            )
            try:
                bad = svc.submit({"tile": "t", "request_id": "rb"})
                assert bad["reason"] == "bad_request"
                assert "retry_after_s" not in bad
                unk = svc.submit({"tile": "nope", "date": "2017-07-05",
                                  "request_id": "ru"})
                assert unk["reason"] == "unknown_tile"
                assert "retry_after_s" not in unk
            finally:
                svc.close()

    def test_admission_controller_retry_after(self):
        ctl = AdmissionController(AdmissionPolicy(retry_after_s=0.75))
        assert ctl.retry_after("queue_full") == 0.75
        assert ctl.retry_after("fleet_degraded") == 0.75
        assert ctl.retry_after("draining") == 0.75
        assert ctl.retry_after("bad_request") is None
        assert ctl.retry_after("unknown_tile") is None


class TestLoadgenBackoff:
    def test_backoff_retries_instead_of_terminal_rejection(
            self, tmp_path):
        """A client with backoff budget waits out queue_full and lands
        every request; the waits are counted into serve_backoff_total."""
        from tools.loadgen import _Target, run_load

        with telemetry.use(MetricsRegistry()):
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
                policy=AdmissionPolicy(max_queue_depth=1,
                                       retry_after_s=0.05),
            ).start()
            try:
                plan = [{"tile": "t", "date": "2017-07-05"}
                        for _ in range(8)]
                rows = run_load(
                    _Target(service=svc), plan, concurrency=8,
                    timeout_s=60, backoff_budget=20,
                )
                assert rows["serve_ok_total"] == 8
                assert rows["serve_rejected_total"] == 0
                assert rows["serve_backoff_total"] >= 1
            finally:
                svc.close()

    def test_zero_budget_keeps_fast_rejection_contract(self, tmp_path):
        from tools.loadgen import _Target, run_load

        with telemetry.use(MetricsRegistry()):
            svc = AssimilationService(
                {"t": StubSession("t")}, str(tmp_path),
                policy=AdmissionPolicy(max_queue_depth=0),
            ).start()
            try:
                rows = run_load(
                    _Target(service=svc),
                    [{"tile": "t", "date": "2017-07-05"}],
                    concurrency=1, timeout_s=10,
                )
                assert rows["serve_rejected_total"] == 1
                assert rows["serve_backoff_total"] == 0
            finally:
                svc.close()


# ---------------------------------------------------------------------------
# journal compaction (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestJournalCompaction:
    def _fill(self, j, n, answered=True, start=0):
        for i in range(start, start + n):
            rid = f"r{i:04d}"
            j.record({"request_id": rid, "tile": "t",
                      "date": "2017-07-05", "pad": "x" * 40})
            if answered:
                j.respond(rid, {"status": "ok"})

    def test_answered_entries_rotate_into_segments(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            j = RequestJournal(str(tmp_path), rotate_bytes=2000, keep=2)
            self._fill(j, 60, answered=True)
            names = sorted(n for n in os.listdir(tmp_path)
                           if n.startswith("requests.jsonl"))
            assert "requests.jsonl.1" in names
            assert "requests.jsonl.3" not in names  # keep-N enforced
            # The live journal shrank below the cap (only pending —
            # here none — survives in it).
            assert os.path.getsize(j.journal_path) < 2000
            # Segments stay line-whole JSON.
            for n in names:
                with open(tmp_path / n) as f:
                    for line in f:
                        assert json.loads(line)["tile"] == "t"
            assert reg.value(
                "kafka_serve_journal_compactions_total") >= 1
            assert any(e["event"] == "journal_compacted"
                       for e in reg.events)
            j.close()

    def test_replay_correct_across_rotation_boundary(self, tmp_path):
        """The satellite's pin: entries answered before the rotation
        land in segments, pending ones stay live, and replay returns
        EXACTLY the unanswered set — wherever the boundary fell."""
        with telemetry.use(MetricsRegistry()):
            j = RequestJournal(str(tmp_path), rotate_bytes=600, keep=3)
            # Interleave answered and pending entries across several
            # rotations.
            pending = []
            for i in range(40):
                rid = f"r{i:04d}"
                j.record({"request_id": rid, "tile": "t",
                          "date": "2017-07-05", "pad": "x" * 30})
                if i % 5 == 0:
                    pending.append(rid)
                else:
                    j.respond(rid, {"status": "ok"})
            assert os.path.exists(str(tmp_path / "requests.jsonl.1"))
            j.close()
            # A fresh journal over the same root (the restart) replays
            # exactly the pending ids, oldest first.
            j2 = RequestJournal(str(tmp_path))
            assert [p["request_id"] for p in j2.replay()] == pending
            j2.close()

    def test_compaction_never_rotates_pending_entries(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            j = RequestJournal(str(tmp_path), rotate_bytes=400, keep=2)
            self._fill(j, 20, answered=False)
            # Nothing answered: the journal may exceed its cap but must
            # not lose a single pending entry to rotation.
            assert not os.path.exists(
                str(tmp_path / "requests.jsonl.1"))
            assert len(j.replay()) == 20
            j.close()

    def test_no_rotation_without_cap(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            j = RequestJournal(str(tmp_path))
            self._fill(j, 50, answered=True)
            assert sorted(
                n for n in os.listdir(tmp_path)
                if n.startswith("requests.jsonl")
            ) == ["requests.jsonl"]
            j.close()


# ---------------------------------------------------------------------------
# router mechanics (stub replicas, no JAX)
# ---------------------------------------------------------------------------

class TestRouterMechanics:
    def test_forward_relay_and_ring_ownership(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            fleet = StubFleet(tmp_path, n=2, tiles=4).start()
            router = TileRouter(fleet.roots, str(tmp_path / "front"),
                                poll_interval_s=0.01)
            rt = run_router(router)
            try:
                rids = {}
                for t in range(4):
                    tile = f"tile{t}"
                    rids[tile] = submit_request(
                        str(tmp_path / "front"),
                        {"tile": tile, "date": "2017-07-05"},
                    )
                ring = HashRing(fleet.roots)
                for tile, rid in rids.items():
                    got = wait_response(str(tmp_path / "front"), rid)
                    assert got is not None and got["status"] == "ok"
                    # The relay stamps WHICH replica answered, and it
                    # is the ring owner.
                    assert got["replica"] == ring.owner(tile)
                    assert got["x_sha256"] == f"stub-{tile}"
                assert reg.value("kafka_route_relayed_total") == 4
                # The router view facts cover the routed tiles.
                st = router.status()
                assert sorted(sum(st["router_ring"].values(), [])) == \
                    [f"tile{t}" for t in range(4)]
                assert st["router_inflight"] == 0
            finally:
                router.drain()
                rt.join(timeout=30)
                fleet.stop()

    def test_shedding_replica_rerouted_to_survivor(self, tmp_path):
        """A replica answering ``rejected: queue_full`` is NOT the end
        of the request: the router re-forwards to the next replica on
        the ring (which serves the tile warm from the shared
        checkpoints) and deprioritises the shedder."""
        with telemetry.use(MetricsRegistry()) as reg:
            ring = HashRing(["rep0", "rep1"])
            # Find a tile owned by each replica so we can shed exactly
            # the owner of the tile we request.
            asg = ring.assignments([f"tile{t}" for t in range(4)])
            tile = asg["rep0"][0] if asg["rep0"] else asg["rep1"][0]
            shedder = ring.owner(tile)
            fleet = StubFleet(
                tmp_path, n=2, tiles=4,
                policies={shedder: AdmissionPolicy(max_queue_depth=0)},
            ).start()
            router = TileRouter(fleet.roots, str(tmp_path / "front"),
                                poll_interval_s=0.01)
            rt = run_router(router)
            try:
                rid = submit_request(
                    str(tmp_path / "front"),
                    {"tile": tile, "date": "2017-07-05"},
                )
                got = wait_response(str(tmp_path / "front"), rid)
                assert got is not None and got["status"] == "ok"
                assert got["replica"] != shedder
                assert reg.value("kafka_route_rerouted_total",
                                 reason="rejected") >= 1
            finally:
                router.drain()
                rt.join(timeout=30)
                fleet.stop()

    def test_all_replicas_shedding_rejects_with_retry_after(
            self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            fleet = StubFleet(
                tmp_path, n=2, tiles=2,
                policies={
                    "rep0": AdmissionPolicy(max_queue_depth=0),
                    "rep1": AdmissionPolicy(max_queue_depth=0),
                },
            ).start()
            router = TileRouter(
                fleet.roots, str(tmp_path / "front"),
                policy=RoutePolicy(retry_after_s=0.9),
                poll_interval_s=0.01,
            )
            rt = run_router(router)
            try:
                rid = submit_request(
                    str(tmp_path / "front"),
                    {"tile": "tile0", "date": "2017-07-05"},
                )
                got = wait_response(str(tmp_path / "front"), rid)
                assert got is not None
                assert got["status"] == "rejected"
                assert got["reason"] == "fleet_degraded"
                assert got["retry_after_s"] == 0.9
            finally:
                router.drain()
                rt.join(timeout=30)
                fleet.stop()

    def test_router_restart_replays_unanswered(self, tmp_path):
        """Zero admitted requests lost across a ROUTER crash: the
        journal replays unanswered requests on restart and re-forwards
        them."""
        with telemetry.use(MetricsRegistry()) as reg:
            root0 = str(tmp_path / "rep0")
            front = str(tmp_path / "front")
            # First router life: no daemon behind rep0, so the forward
            # lands in an inbox nobody serves.
            router1 = TileRouter({"rep0": root0}, front,
                                 poll_interval_s=0.01)
            ack = router1.submit({"tile": "tile0",
                                  "date": "2017-07-05",
                                  "request_id": "lost1"})
            assert ack["status"] == "queued"
            router1.journal.close()
            # "Restart": the replica daemon is up now; the new router
            # replays the journal and the request completes.
            fleet = StubFleet(tmp_path, n=1, tiles=1).start()
            router2 = TileRouter(fleet.roots, front,
                                 poll_interval_s=0.01)
            rt = run_router(router2)
            try:
                got = wait_response(front, "lost1")
                assert got is not None and got["status"] == "ok"
                assert reg.value("kafka_route_replayed_total") == 1
            finally:
                router2.drain()
                rt.join(timeout=30)
                fleet.stop()

    def test_draining_router_rejects_with_hint(self, tmp_path):
        with telemetry.use(MetricsRegistry()):
            router = TileRouter({"rep0": str(tmp_path / "rep0")},
                                str(tmp_path / "front"))
            router.drain()
            ack = router.submit({"tile": "tile0",
                                 "date": "2017-07-05",
                                 "request_id": "late"})
            assert ack["status"] == "rejected"
            assert ack["reason"] == "draining"
            assert ack["retry_after_s"] == router.policy.retry_after_s
            router.journal.close()

    def test_bad_request_rejected_not_forwarded(self, tmp_path):
        with telemetry.use(MetricsRegistry()) as reg:
            router = TileRouter({"rep0": str(tmp_path / "rep0")},
                                str(tmp_path / "front"))
            ack = router.submit({"date": "2017-07-05",
                                 "request_id": "nob"})
            assert ack["status"] == "rejected"
            assert ack["reason"] == "bad_request"
            assert "retry_after_s" not in ack
            assert reg.value("kafka_route_rejected_total",
                             reason="bad_request") == 1
            # Not journaled: a bad request is not admitted work.
            assert router.journal.replay() == []
            router.journal.close()


# ---------------------------------------------------------------------------
# fleet watch: dead / shedding detection from live snapshots
# ---------------------------------------------------------------------------

def _write_snapshot(fleet_dir, host, pid, serve_root, ts, final=False,
                    interval_s=0.2, counters=None, gauges=None,
                    role="serve"):
    os.makedirs(fleet_dir, exist_ok=True)
    snap = {
        "schema": 1, "ts": ts, "host": host, "pid": pid, "role": role,
        "seq": 1, "interval_s": interval_s, "final": final,
        "run_id": None, "chunk_id": None,
        "health": {"unhealthy": None}, "quality": {}, "perf": {},
        "counters": counters or {}, "gauges": gauges or {},
        "histograms": {}, "series_truncated": 0, "crash_dumps": [],
        "status": {"serve_root": os.path.abspath(serve_root)},
    }
    path = os.path.join(fleet_dir, f"live_{host}_{pid}.json")
    with open(path, "w") as f:
        json.dump(snap, f)
    return snap


class TestFleetWatch:
    def test_stale_heartbeat_without_final_is_dead(self, tmp_path):
        fleet_dir = str(tmp_path / "tel")
        roots = {"rep0": str(tmp_path / "rep0"),
                 "rep1": str(tmp_path / "rep1"),
                 "rep2": str(tmp_path / "rep2")}
        now = time.time()
        _write_snapshot(fleet_dir, "h", 1, roots["rep0"], ts=now - 30)
        _write_snapshot(fleet_dir, "h", 2, roots["rep1"], ts=now)
        # rep2 exited CLEANLY long ago: final, so never "dead".
        _write_snapshot(fleet_dir, "h", 3, roots["rep2"], ts=now - 30,
                        final=True)
        watch = FleetWatch(fleet_dir, roots, RoutePolicy(ttl_s=1.0))
        view = watch.refresh()
        assert view["rep0"]["dead"] is True
        assert view["rep1"]["dead"] is False
        assert view["rep2"]["dead"] is False
        assert view["rep2"]["final"] is True

    def test_default_ttl_is_three_heartbeats(self, tmp_path):
        fleet_dir = str(tmp_path / "tel")
        roots = {"rep0": str(tmp_path / "rep0")}
        now = time.time()
        # interval 2.0 -> TTL 6.0: a 4s-old heartbeat is alive, a 7s-old
        # one is dead.
        _write_snapshot(fleet_dir, "h", 1, roots["rep0"], ts=now - 4,
                        interval_s=2.0)
        watch = FleetWatch(fleet_dir, roots, RoutePolicy())
        assert watch.refresh()["rep0"]["dead"] is False
        _write_snapshot(fleet_dir, "h", 1, roots["rep0"], ts=now - 7,
                        interval_s=2.0)
        assert watch.refresh()["rep0"]["dead"] is True

    def test_queue_full_counter_climb_marks_shedding(self, tmp_path):
        fleet_dir = str(tmp_path / "tel")
        roots = {"rep0": str(tmp_path / "rep0")}
        tag = 'kafka_serve_rejected_total{reason="queue_full"}'
        _write_snapshot(fleet_dir, "h", 1, roots["rep0"],
                        ts=time.time(), counters={tag: 2})
        watch = FleetWatch(fleet_dir, roots,
                           RoutePolicy(ttl_s=5.0, shed_backoff_s=30.0))
        watch.refresh()  # baseline
        assert watch.shedding("rep0") is False
        _write_snapshot(fleet_dir, "h", 1, roots["rep0"],
                        ts=time.time(), counters={tag: 5})
        watch.refresh()
        assert watch.shedding("rep0") is True

    def test_dead_replica_triggers_failover_and_rebalance(
            self, tmp_path):
        """In-process failover: requests in flight on a replica whose
        heartbeat went stale are re-forwarded to the survivor, and the
        ring rebalance is counted."""
        with telemetry.use(MetricsRegistry()) as reg:
            fleet_dir = str(tmp_path / "tel")
            fleet = StubFleet(tmp_path, n=2, tiles=4).start()
            ring = HashRing(fleet.roots)
            tile = ring.assignments(
                [f"tile{t}" for t in range(4)])["rep0"][0]
            now = time.time()
            # rep0 looks freshly alive; rep1 alive too.
            _write_snapshot(fleet_dir, "h", 10, fleet.roots["rep0"],
                            ts=now)
            _write_snapshot(fleet_dir, "h", 11, fleet.roots["rep1"],
                            ts=now)
            router = TileRouter(
                dict(fleet.roots), str(tmp_path / "front"),
                fleet_dir=fleet_dir,
                policy=RoutePolicy(ttl_s=1.0, refresh_s=0.05),
                poll_interval_s=0.01,
            )
            # Stop rep0's daemon so the forward stays unanswered, then
            # let its heartbeat go stale.
            fleet.daemons[0].drain()
            fleet.threads[0].join(timeout=30)
            rt = run_router(router)
            try:
                rid = submit_request(
                    str(tmp_path / "front"),
                    {"tile": tile, "date": "2017-07-05"},
                )
                time.sleep(0.1)
                # The heartbeat goes stale NOW (older than TTL).
                _write_snapshot(fleet_dir, "h", 10,
                                fleet.roots["rep0"], ts=now - 60)
                got = wait_response(str(tmp_path / "front"), rid,
                                    timeout_s=30)
                assert got is not None and got["status"] == "ok"
                assert got["replica"] == "rep1"
                assert reg.value("kafka_route_rerouted_total",
                                 reason="dead") >= 1
                assert reg.value("kafka_route_rebalanced_total") >= 1
                st = router.status()
                assert st["router_dead"] == ["rep0"]
                assert st["router_last_failover_ts"] is not None
            finally:
                router.drain()
                rt.join(timeout=30)
                fleet.stop()


# ---------------------------------------------------------------------------
# warm-state replica migration (ISSUE 13 satellite; real solve)
# ---------------------------------------------------------------------------

class TestWarmMigration:
    def test_reassigned_tile_resumes_warm_and_bit_identical(
            self, tmp_path):
        """A tile re-assigned to a FRESH replica resumes from the
        shared checkpoint set: zero windows re-run for an already-
        answered date, and the continued chain is bit-identical to the
        original owner's uninterrupted run (unfused CPU)."""
        with telemetry.use(MetricsRegistry()):
            shared_ckpt = str(tmp_path / "ckpt_shared")

            def session():
                # A fresh replica's view of the SAME tile: same spec,
                # same shared checkpoint dir.
                return TileSession(make_synthetic_tile(
                    "t", shared_ckpt, seed=0))

            # DATES[0]/DATES[3]/DATES[-1] sit in DISTINCT 4-day grid
            # windows, so each serve advances the chain.
            owner_a = session()
            r1 = owner_a.serve(DATES[0])
            r2 = owner_a.serve(DATES[3])
            assert r2["served_from"] == "warm"

            # Migration: replica B picks the tile up mid-chain.
            owner_b = session()
            noop = owner_b.serve(DATES[3])
            assert noop["served_from"] == "warm_noop"
            assert noop["windows_run"] == 0
            assert noop["x_sha256"] == r2["x_sha256"]
            cont = owner_b.serve(DATES[-1])
            assert cont["served_from"] == "warm"

            # The migrated chain equals an uninterrupted single-owner
            # chain, bit for bit.
            ref = TileSession(make_synthetic_tile(
                "t", str(tmp_path / "ckpt_ref"), seed=0))
            ref.serve(DATES[0])
            ref.serve(DATES[3])
            ref_final = ref.serve(DATES[-1])
            assert cont["x_sha256"] == ref_final["x_sha256"]
            assert r1["x_sha256"] == \
                TileSession(make_synthetic_tile(
                    "t", str(tmp_path / "ckpt_cold"), seed=0,
                )).serve(DATES[0])["x_sha256"]


# ---------------------------------------------------------------------------
# fleet_status router view (ISSUE 13 satellite)
# ---------------------------------------------------------------------------

class TestFleetStatusRouterView:
    def _router_snapshot(self, fleet_dir):
        os.makedirs(fleet_dir, exist_ok=True)
        snap = {
            "schema": 1, "ts": time.time(), "host": "rhost", "pid": 77,
            "role": "route", "seq": 3, "interval_s": 2.0,
            "final": False, "run_id": None, "chunk_id": None,
            "health": {"unhealthy": None}, "quality": {}, "perf": {},
            "counters": {"kafka_route_relayed_total": 9},
            "gauges": {"kafka_route_inflight": 2},
            "histograms": {}, "series_truncated": 0, "crash_dumps": [],
            "status": {
                "router_root": "/front",
                "router_replicas": {"rep0": "/r0", "rep1": "/r1",
                                    "rep2": "/r2"},
                "router_routable": ["rep0", "rep1"],
                "router_dead": ["rep2"],
                "router_ring": {"rep0": ["tile0", "tile3"],
                                "rep1": ["tile1", "tile2"],
                                "rep2": []},
                "router_inflight": 2,
                "router_rerouted_total": 4,
                "router_rebalanced_total": 1,
                "router_last_failover_ts": 1700000000.0,
            },
        }
        with open(os.path.join(fleet_dir, "live_rhost_77.json"),
                  "w") as f:
            json.dump(snap, f)

    def test_render_includes_ring_and_failover(self, tmp_path):
        from tools.fleet_status import build_view, render

        self._router_snapshot(str(tmp_path))
        fleet = build_view(str(tmp_path), ttl_s=60.0)
        text = render(fleet)
        assert "router rhost:77" in text
        assert "routable=2/3" in text
        assert "inflight=2" in text
        assert "rerouted=4" in text
        assert "rebalanced=1" in text
        assert "dead replicas: rep2" in text
        assert "ring rep0: 2 tile(s) [tile0,tile3]" in text
        assert "ring rep2 DEAD: 0 tile(s)" in text
        # A timestamp rendered, not the '-' placeholder (the exact
        # date text is timezone-dependent).
        assert "last_failover=-" not in text
        assert "last_failover=20" in text

    def test_cli_json_carries_router_status(self, tmp_path, capsys):
        from tools.fleet_status import main

        self._router_snapshot(str(tmp_path))
        assert main([str(tmp_path), "--json", "--ttl-s", "60"]) == 0
        payload = json.loads(capsys.readouterr().out)
        worker = payload["workers"][0]
        assert worker["role"] == "route"
        assert worker["status"]["router_rerouted_total"] == 4


# ---------------------------------------------------------------------------
# bench rows + bench_compare gate (ISSUE 13)
# ---------------------------------------------------------------------------

class TestFleetBenchRows:
    def test_bench_fleet_rows(self, tmp_path):
        from tools.loadgen import bench_fleet

        with telemetry.use(MetricsRegistry()):
            rows = bench_fleet(str(tmp_path), replicas=2, requests=6,
                               concurrency=2, tiles=2)
        assert rows["serve_fleet_ok_total"] == 6
        assert rows["serve_fleet_error_total"] == 0
        assert rows["serve_fleet_p50_ms"] > 0
        assert rows["serve_fleet_p99_ms"] >= rows["serve_fleet_p50_ms"]
        assert rows["serve_fleet_replicas"] == 2
        assert rows["serve_fleet_rerouted_total"] == 0
        assert rows["serve_fleet_cold_ms"] > 0
        assert rows["serve_backoff_total"] == 0

    def test_bench_compare_gates_fleet_p99(self, tmp_path, capsys):
        from tools.bench_compare import main as compare

        base = {"serve_fleet_p50_ms": 5.0, "serve_fleet_p99_ms": 20.0,
                "serve_fleet_rerouted_total": 0}
        old = tmp_path / "old.json"
        new = tmp_path / "new.json"
        old.write_text(json.dumps(base))
        # >10% p99 regression fails the gate.
        new.write_text(json.dumps({**base,
                                   "serve_fleet_p99_ms": 30.0}))
        assert compare([str(old), str(new)]) == 1
        err = capsys.readouterr().err
        assert "serve_fleet_p99_ms" in err and "REGRESSION" in err
        # Disappearance of the row gates too.
        new.write_text(json.dumps({"serve_fleet_p50_ms": 5.0}))
        assert compare([str(old), str(new)]) == 1
        # Within threshold passes; rerouted_total is informational.
        new.write_text(json.dumps({**base,
                                   "serve_fleet_p99_ms": 21.0,
                                   "serve_fleet_rerouted_total": 99}))
        assert compare([str(old), str(new)]) == 0


# ---------------------------------------------------------------------------
# the chaos acceptance: 3-replica fleet, SIGKILL one mid-request
# ---------------------------------------------------------------------------

def _replica_cmd(root, ckpt_root, tel_dir):
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_serve",
        "--root", str(root), "--ckpt-root", str(ckpt_root),
        "--tiles", "2", "--operator", "identity",
        "--ny", "16", "--nx", "20", "--days", "40", "--step", "2",
        "--obs-every", "2", "--poll-interval-s", "0.02",
        "--telemetry-dir", str(tel_dir),
    ]


def _router_cmd(front, replicas, fleet_dir, tel_dir):
    spec = ",".join(f"{rid}={root}" for rid, root in replicas.items())
    return [
        sys.executable, "-m", "kafka_tpu.cli.kafka_route",
        "--root", str(front), "--replicas", spec,
        "--fleet-dir", str(fleet_dir), "--ttl-s", "1.0",
        "--refresh-s", "0.2", "--poll-interval-s", "0.02",
        "--telemetry-dir", str(tel_dir),
    ]


class TestFleetChaosAcceptance:
    def test_sigkill_replica_rerouted_warm_zero_loss(self, tmp_path):
        """ISSUE 13 acceptance: loadgen against a 3-replica fleet
        behind kafka-route; the replica owning tile0 is SIGKILLed
        mid-request.  The router flags it dead within one heartbeat TTL
        and re-routes, the reassigned owner resumes the tile WARM from
        the shared checkpoint set, zero admitted requests are lost, the
        served output equals an uninterrupted run's bit-for-bit, and
        the serve_fleet_* rows emit."""
        from tools.loadgen import _Target, run_load

        env = _subprocess_env()
        tel = tmp_path / "tel"
        ckpt = tmp_path / "ckpt"
        front = str(tmp_path / "front")
        dates = synthetic_dates(DEFAULT_BASE_DATE, 40, 2)
        date = dates[-1]

        replicas = {f"rep{i}": str(tmp_path / f"rep{i}")
                    for i in range(3)}
        victim_rid = HashRing(replicas).owner("tile0")
        procs = {}
        router_proc = None
        try:
            for rid, root in replicas.items():
                procs[rid] = subprocess.Popen(
                    _replica_cmd(root, ckpt, tel / rid), env=env,
                    cwd=REPO_ROOT, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            router_proc = subprocess.Popen(
                _router_cmd(front, replicas, tel, tel / "router"),
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True,
            )
            victim = procs[victim_rid]

            rid = submit_request(front, {
                "tile": "tile0", "date": date.isoformat(),
                "request_id": "victimreq",
            })
            # Kill the owner as soon as warm state exists (shared
            # checkpoints on disk) and the request is admitted by it
            # (victim journal) but unanswered: mid-request by
            # construction.
            victim_journal = tmp_path / victim_rid / "requests.jsonl"
            ck_dir = ckpt / "ckpt_tile0"
            deadline = time.time() + 300
            while time.time() < deadline:
                if victim.poll() is not None:
                    pytest.fail(
                        f"victim exited rc={victim.returncode} before "
                        "it could be killed"
                    )
                if read_response(front, rid) is not None:
                    pytest.fail("fleet answered before the kill — "
                                "widen the request")
                journal_text = victim_journal.read_text() \
                    if victim_journal.exists() else ""
                if rid in journal_text and ck_dir.is_dir() and any(
                        n.endswith(".npz")
                        for n in os.listdir(ck_dir)):
                    break
                time.sleep(0.005)
            else:
                pytest.fail("victim never admitted + checkpointed")
            kill_ts = time.time()
            victim.kill()
            victim.wait(timeout=30)
            assert read_response(front, rid) is None

            # The router must flag the victim dead and re-route; the
            # reassigned owner resumes warm and answers.
            got = wait_response(front, rid, timeout_s=300)
            assert got is not None, "re-routed request was lost"
            assert got["status"] == "ok"
            assert got["replica"] != victim_rid
            # Warm migration: the new owner resumed from the victim's
            # checkpoints, not a cold rerun.
            assert got["served_from"] in ("warm", "warm_noop")

            # ...and the answer equals an uninterrupted run's, exactly
            # (bit-identical unfused CPU).
            ref = TileSession(make_synthetic_tile(
                "tile0", str(tmp_path / "ck_ref"), operator="identity",
                ny=16, nx=20, days=40, step_days=2, obs_every=2,
                seed=0,
            ))
            assert got["x_sha256"] == ref.serve(date)["x_sha256"]

            # Zero lost admitted requests under continued load: every
            # post-failover request lands (the fleet is one replica
            # down but fully serving).
            plan = []
            for i in range(6):
                plan.append({
                    "tile": f"tile{i % 2}",
                    "date": dates[-1 - (i % 2)].isoformat(),
                })
            rows = run_load(_Target(root=front), plan, concurrency=3,
                            timeout_s=300, backoff_budget=8)
            assert rows["serve_ok_total"] == 6
            assert rows["serve_error_total"] == 0
            # The serve_fleet_* BENCH rows this harness emits.
            fleet_rows = {
                "serve_fleet_p50_ms": rows["serve_p50_ms"],
                "serve_fleet_p99_ms": rows["serve_p99_ms"],
                "serve_fleet_rerouted_total": None,
            }
            assert fleet_rows["serve_fleet_p99_ms"] is not None
            assert fleet_rows["serve_fleet_p99_ms"] >= \
                fleet_rows["serve_fleet_p50_ms"]

            # Drain the router cleanly and read its summary: it
            # re-routed (failover) and rebalanced the ring.
            router_proc.send_signal(signal.SIGTERM)
            out, _ = router_proc.communicate(timeout=120)
            assert router_proc.returncode == 0
            summary = json.loads(out.strip().splitlines()[-1])
            assert summary["rerouted"] >= 1
            assert summary["rebalanced"] >= 1
            assert summary["relayed"] >= 7  # victimreq + the 6 loadgen

            # Failover latency: the router noticed within TTL-scale
            # time of the victim's LAST heartbeat (TTL 1.0s + refresh
            # 0.2s + scheduling slack).
            events_path = tel / "router" / "events.jsonl"
            failovers = []
            with open(events_path) as f:
                for line in f:
                    e = json.loads(line)
                    if e["event"] == "route_failover":
                        failovers.append(e)
            assert failovers, "router recorded no failover event"
            victim_snaps = [
                n for n in os.listdir(tel / victim_rid)
                if n.startswith("live_")
            ]
            assert victim_snaps, "victim published no heartbeat"
            with open(tel / victim_rid / victim_snaps[0]) as f:
                last_beat = json.load(f)["ts"]
            detect_lag = failovers[0]["ts"] - last_beat
            assert detect_lag <= 1.0 + 0.2 + 8.0, (
                f"failover took {detect_lag:.1f}s after the last "
                "heartbeat — far beyond one heartbeat TTL"
            )
            assert failovers[0]["ts"] >= kill_ts

            # The fleet view agrees: exactly the victim is dead.
            from tools.fleet_status import build_view

            fleet_view = build_view(str(tel), ttl_s=1.0)
            dead_pids = {w["pid"] for w in fleet_view["workers"]
                         if w["dead"]}
            assert victim.pid in dead_pids
        finally:
            for proc in list(procs.values()) + [router_proc]:
                if proc is not None and proc.poll() is None:
                    proc.kill()
