"""True multi-process distributed test (VERDICT round-1 item 9).

Spawns two real OS processes that meet at a localhost
``jax.distributed.initialize`` coordinator, form one global device mesh,
run a cross-process collective, and split the chunk scheduler's work by
their genuine ``jax.process_index()`` — the end-to-end replacement for the
reference's live-dask-cluster path (``kafka_test_Py36.py:242-255``) that
round 1 only exercised with a faked process index.
"""

import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_run(tmp_path):
    port = _free_port()
    outdir = str(tmp_path)
    env = dict(os.environ)
    # Bypass any TPU plugin sitecustomize: the children must come up on the
    # host platform only, like independent cluster workers would.
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"

    # Children log to files, not pipes: two piped children meeting at a
    # collective can deadlock on a full OS pipe buffer while the parent
    # drains them sequentially.
    log_paths = [os.path.join(outdir, f"worker_{i}.log") for i in range(2)]
    logs = [open(p, "wb") for p in log_paths]
    try:
        procs = [
            subprocess.Popen(
                [
                    sys.executable, "-m",
                    "kafka_tpu.testing.multiprocess_worker",
                    "--coordinator", f"localhost:{port}",
                    "--num-processes", "2",
                    "--process-id", str(i),
                    "--outdir", outdir,
                ],
                env=env,
                stdout=logs[i],
                stderr=subprocess.STDOUT,
            )
            for i in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=180)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
    finally:
        for f in logs:
            f.close()

    def logs_text():
        return "\n".join(
            f"--- worker {i} ---\n" + open(p, errors="replace").read()
            for i, p in enumerate(log_paths)
        )

    text = logs_text()
    if "Multiprocess computations aren't implemented on the CPU" in text:
        # Capability limit of THIS jaxlib build, not a bug in the
        # scheduler under test: the bundled XLA:CPU backend has no
        # cross-process collective support, so the workers can form the
        # coordinator but never run the psum.  On builds with the Gloo
        # CPU collectives (or real multi-host TPU) the test runs fully.
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"worker {i} failed:\n{logs_text()}"

    results = {}
    for i in range(2):
        with open(os.path.join(outdir, f"result_{i}.json")) as f:
            results[i] = json.load(f)

    for i, r in results.items():
        # Real two-process runtime with a 4-device global mesh
        assert r["process_count"] == 2
        assert r["global_devices"] == 4
        assert r["local_devices"] == 2
        # The cross-process psum saw every shard
        assert r["collective_sum"] == r["collective_expected"]
        # Round-robin: each process owned and ran exactly 2 of 4 chunks
        assert r["stats"]["assigned"] == 2
        assert r["stats"]["run"] == 2

    # The union of both processes' chunks covers all four, disjointly
    all_chunks = results[0]["chunks_run"] + results[1]["chunks_run"]
    assert sorted(all_chunks) == ["0001", "0002", "0003", "0004"]
    assert not set(results[0]["chunks_run"]) & set(results[1]["chunks_run"])
    # And every chunk's marker + output landed in the shared directory
    markers = [f for f in os.listdir(outdir) if f.endswith(".done")]
    assert len(markers) == 4
