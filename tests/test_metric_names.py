"""Tier-1 wiring of tools/check_metric_names.py (ISSUE 2 satellite): the
metric-name convention is enforced statically so a rename/duplicate breaks
the suite, not the dashboards scraping metrics.prom."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_metric_names",
        os.path.join(REPO_ROOT, "tools", "check_metric_names.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_repo_metric_names_are_clean():
    checker = _load_checker()
    errors = checker.check(REPO_ROOT)
    assert errors == [], "\n".join(errors)


def test_every_registration_found(tmp_path):
    """The scanner must actually see the production registrations — an
    empty scan (regex rot, moved files) must fail, not silently pass."""
    checker = _load_checker()
    regs = checker.collect_registrations(REPO_ROOT)
    # The engine/prefetch/shard/io/health metric families all register.
    subsystems = {name.split("_")[1] for name in regs}
    assert {"engine", "prefetch", "shard", "io", "health"} <= subsystems


def test_checker_flags_violations(tmp_path):
    checker = _load_checker()
    pkg = tmp_path / "kafka_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'reg.counter("kafka_engine_dup_total")\n'
        'reg.gauge("badName")\n'
    )
    (pkg / "b.py").write_text(
        'reg.counter("kafka_engine_dup_total")\n'
    )
    (tmp_path / "bench.py").write_text("")
    errors = checker.check(str(tmp_path))
    text = "\n".join(errors)
    assert "badName" in text
    assert "kafka_engine_dup_total" in text and "2 sites" in text


def test_checker_flags_empty_scan(tmp_path):
    checker = _load_checker()
    (tmp_path / "kafka_tpu").mkdir()
    (tmp_path / "bench.py").write_text("")
    errors = checker.check(str(tmp_path))
    assert errors and "no metric registrations" in errors[0]


def test_event_and_phase_names_collected():
    """The scanners must see the production emit()/span() vocabulary
    (regex rot would silently lint nothing)."""
    checker = _load_checker()
    events = checker.collect_names(REPO_ROOT, checker.EMIT_RE)
    phases = checker.collect_names(REPO_ROOT, checker.SPAN_RE)
    assert {"solve", "phase", "run_done", "chunk_done",
            "health_probe"} <= set(events)
    assert {"advance", "assimilate", "dump", "fused_scan"} <= set(phases)


def test_checker_flags_event_casing_and_near_duplicates(tmp_path):
    checker = _load_checker()
    pkg = tmp_path / "kafka_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'reg.emit("chunkDone", n=1)\n'          # off-convention casing
        'reg.emit("chunk_done", n=1)\n'         # + near-duplicate of it
        'with span("advance"):\n    pass\n'
    )
    (pkg / "b.py").write_text(
        'reg.counter("kafka_engine_ok_total")\n'
        'with span("Fused_Scan"):\n    pass\n'  # off-convention phase
    )
    (tmp_path / "bench.py").write_text("")
    text = "\n".join(checker.check(str(tmp_path)))
    assert "'chunkDone'" in text and "not lower_snake_case" in text
    assert "'Fused_Scan'" in text
    assert "near-duplicate" in text and "chunk_done" in text


def test_checker_flags_event_phase_name_collision(tmp_path):
    checker = _load_checker()
    pkg = tmp_path / "kafka_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'reg.counter("kafka_engine_ok_total")\n'
        'reg.emit("dump", n=1)\n'
        'with span("dump"):\n    pass\n'
    )
    (tmp_path / "bench.py").write_text("")
    text = "\n".join(checker.check(str(tmp_path)))
    assert "both an event and a span phase" in text


def test_exact_duplicates_across_sites_allowed(tmp_path):
    """run_done is legitimately emitted by each driver and span('dump')
    by both engine paths — same-literal reuse is NOT an error."""
    checker = _load_checker()
    pkg = tmp_path / "kafka_tpu"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        'reg.counter("kafka_engine_ok_total")\n'
        'reg.emit("run_done", n=1)\n'
        'with span("dump"):\n    pass\n'
    )
    (pkg / "b.py").write_text(
        'reg.emit("run_done", n=2)\n'
        'with span("dump"):\n    pass\n'
    )
    (tmp_path / "bench.py").write_text("")
    assert checker.check(str(tmp_path)) == []


def test_checker_main_exits_zero_on_repo():
    checker = _load_checker()
    assert checker.main([REPO_ROOT]) == 0
