"""Observation-operator tests: physics limits, autodiff-vs-analytic
gradients, emulator fidelity, and protocol machinery."""

import numpy as np
import jax
import jax.numpy as jnp

from kafka_tpu.core import BandBatch, iterated_solve, Linearization
from kafka_tpu.obsops import (
    GPBankOperator,
    IdentityOperator,
    MLPOperator,
    TwoStreamOperator,
    WCMAux,
    WCMOperator,
    WCM_PARAMETERS,
    fit_gp,
    fit_mlp,
    gp_predict_pixel,
    stack_gp_bank,
    tlai_to_lai,
    twostream_albedo,
    wcm_sigma0,
)

RNG = np.random.default_rng(3)


class TestWCM:
    def test_forward_matches_reference_formula(self):
        """Independent NumPy evaluation of the published WCM equations
        (sar_forward_model.py:74-78) vs the JAX operator."""
        lai, sm, theta = 2.3, 0.25, 30.0
        for pol, (a, b, c, d, e) in WCM_PARAMETERS.items():
            mu = np.cos(np.deg2rad(theta))
            tau = np.exp(-2 * b * lai / mu)
            expected = a * lai**e * mu * (1 - tau) + tau * 10 ** (
                (c + d * sm) / 10
            )
            got = float(wcm_sigma0(lai, sm, theta, (a, b, c, d, e)))
            np.testing.assert_allclose(got, expected, rtol=1e-6)

    def test_autodiff_gradient_matches_analytic(self):
        """The reference hand-codes dsigma0/d(LAI, SM)
        (sar_forward_model.py:82-98); autodiff must agree with the
        analytically re-derived gradient."""
        op = WCMOperator()
        theta = np.float32(23.0)
        x = jnp.asarray([1.7, 0.3], jnp.float32)
        aux = WCMAux(theta_deg=theta)
        grad = jax.jacfwd(lambda z: op.forward_pixel(aux, z))(x)
        for bi, pol in enumerate(("VV", "VH")):
            a, b, c, d, e = WCM_PARAMETERS[pol]
            mu = np.cos(np.deg2rad(23.0))
            v, sm = 1.7, 0.3
            tau = np.exp(-2 * b * v / mu)
            soil = 10 ** ((c + d * sm) / 10)
            # d/dv: a e v^(e-1) mu (1-tau) + a v^e mu tau 2b/mu - 2b/mu tau soil
            dv = (
                a * e * v ** (e - 1) * mu * (1 - tau)
                + a * v**e * 2 * b * tau
                - (2 * b / mu) * tau * soil
            )
            dsm = tau * soil * d * np.log(10) / 10
            np.testing.assert_allclose(float(grad[bi, 0]), dv, rtol=1e-4)
            np.testing.assert_allclose(float(grad[bi, 1]), dsm, rtol=1e-4)

    def test_linearize_shapes_and_per_pixel_theta(self):
        op = WCMOperator()
        n_pix = 17
        x = jnp.asarray(
            RNG.uniform(0.5, 3.0, size=(n_pix, 2)), jnp.float32
        )
        aux = WCMAux(
            theta_deg=jnp.asarray(
                RNG.uniform(20, 40, size=(n_pix,)), jnp.float32
            )
        )
        lin = op.linearize(aux, x)
        assert lin.h0.shape == (2, n_pix)
        assert lin.jac.shape == (2, n_pix, 2)
        assert bool(jnp.isfinite(lin.h0).all())
        # VH has E=0: no direct V^E term; sigma_veg = a*mu*(1-tau)
        assert not np.allclose(np.asarray(lin.h0[0]), np.asarray(lin.h0[1]))


class TestTwoStream:
    def test_zero_lai_returns_soil_albedo(self):
        alb = twostream_albedo(0.5, 1.0, 0.3, 1e-6)
        np.testing.assert_allclose(float(alb), 0.3, atol=1e-4)

    def test_infinite_lai_independent_of_soil(self):
        a1 = float(twostream_albedo(0.6, 1.0, 0.05, 50.0))
        a2 = float(twostream_albedo(0.6, 1.0, 0.95, 50.0))
        np.testing.assert_allclose(a1, a2, atol=1e-5)

    def test_albedo_physical_and_monotone_in_omega(self):
        lai = 3.0
        prev = -1.0
        for omega in [0.1, 0.3, 0.5, 0.7, 0.9]:
            alb = float(twostream_albedo(omega, 1.0, 0.2, lai))
            assert 0.0 <= alb <= 1.0
            assert alb > prev  # brighter leaves -> brighter canopy
            prev = alb

    def test_operator_on_tip_state_with_autodiff(self):
        from kafka_tpu.core import tip_prior, broadcast_prior

        op = TwoStreamOperator()
        prior = tip_prior()
        n_pix = 9
        x, p_inv = broadcast_prior(prior, n_pix)
        lin = op.linearize(None, x)
        assert lin.h0.shape == (2, n_pix)
        assert lin.jac.shape == (2, n_pix, 7)
        assert bool(jnp.isfinite(lin.jac).all())
        # VIS band must not depend on NIR params and vice versa.
        jac = np.asarray(lin.jac)
        np.testing.assert_allclose(jac[0][:, [3, 4, 5]], 0.0, atol=1e-7)
        np.testing.assert_allclose(jac[1][:, [0, 1, 2]], 0.0, atol=1e-7)
        # Both depend on TLAI (slot 6).
        assert np.abs(jac[:, :, 6]).min() > 0

    def test_end_to_end_recovers_lai(self):
        """Invert the two-stream model for TLAI from clean synthetic
        albedos — the core scientific use case of the MODIS pipeline."""
        from kafka_tpu.core import tip_prior, broadcast_prior

        op = TwoStreamOperator()
        prior = tip_prior()
        n_pix = 64
        x0, p_inv0 = broadcast_prior(prior, n_pix)
        # Pin the spectral/soil parameters with a tight prior so the albedo
        # signal must be attributed to TLAI (with the loose default prior the
        # 2-obs/7-param problem is genuinely ill-posed — the TIP ambiguity —
        # and the MAP legitimately spreads the signal).
        tight = 1e4 * jnp.eye(7, dtype=jnp.float32)
        tight = tight.at[6, 6].set(float(prior.inv_cov[6, 6]))
        p_inv0 = jnp.broadcast_to(tight, (n_pix, 7, 7))
        tlai_true = jnp.asarray(
            RNG.uniform(0.2, 0.8, size=(n_pix,)), jnp.float32
        )
        x_true = x0.at[:, 6].set(tlai_true)
        y = op.forward(None, x_true)
        obs = BandBatch(
            y=y,
            r_inv=jnp.full(y.shape, 1.0 / 0.005**2, jnp.float32),
            mask=jnp.ones(y.shape, bool),
        )
        x_a, _, diags = iterated_solve(op.linearize, obs, x0, p_inv0)
        # TLAI recovered well below prior sigma (0.5); observations must be
        # fit to within the stated noise either way.
        err = float(jnp.abs(x_a[:, 6] - tlai_true).mean())
        assert err < 0.05, err
        fwd_err = float(jnp.abs(op.forward(None, x_a) - y).mean())
        assert fwd_err < 0.01, fwd_err


class TestGPEmulator:
    def test_fit_and_predict_smooth_function(self):
        x = RNG.uniform(-1, 1, size=(400, 3)).astype(np.float32)
        y = np.sin(2 * x[:, 0]) + x[:, 1] ** 2 + 0.5 * x[:, 2]
        params = fit_gp(x, y)
        xt = RNG.uniform(-0.8, 0.8, size=(50, 3)).astype(np.float32)
        yt = np.sin(2 * xt[:, 0]) + xt[:, 1] ** 2 + 0.5 * xt[:, 2]
        pred = jax.vmap(lambda z: gp_predict_pixel(params, z))(jnp.asarray(xt))
        np.testing.assert_allclose(np.asarray(pred), yt, atol=0.05)

    def test_gp_jacobian_matches_finite_differences(self):
        x = RNG.uniform(-1, 1, size=(300, 2)).astype(np.float32)
        y = np.tanh(x[:, 0]) * x[:, 1]
        params = fit_gp(x, y)
        x0 = jnp.asarray([0.2, -0.4], jnp.float32)
        g = jax.grad(lambda z: gp_predict_pixel(params, z))(x0)
        eps = 1e-3
        for i in range(2):
            xp = x0.at[i].add(eps)
            xm = x0.at[i].add(-eps)
            fd = (gp_predict_pixel(params, xp) - gp_predict_pixel(params, xm)) / (
                2 * eps
            )
            np.testing.assert_allclose(float(g[i]), float(fd), atol=1e-2)

    def test_gp_bank_operator_emulates_twostream(self):
        """Train per-band GPs on the two-stream model over the TIP mapped
        4-d sub-space and check the banked operator reproduces it — the
        workflow replacing the reference's pickled emulators."""
        from kafka_tpu.obsops import VIS_MAPPER, NIR_MAPPER

        n_train = 500
        sub = np.stack(
            [
                RNG.uniform(0.1, 0.9, n_train),   # omega
                RNG.uniform(0.5, 2.0, n_train),   # d
                RNG.uniform(0.15, 0.9, n_train),  # tlai
                RNG.uniform(0.05, 0.5, n_train),  # soil
            ],
            axis=1,
        ).astype(np.float32)
        alb = np.asarray(
            twostream_albedo(
                sub[:, 0], sub[:, 1], sub[:, 3], np.asarray(tlai_to_lai(sub[:, 2]))
            )
        )
        gp_band = fit_gp(sub, alb, noise=1e-6)
        bank = stack_gp_bank([gp_band, gp_band])
        op = GPBankOperator(
            n_params=7, n_bands=2,
            state_mappers=np.stack([VIS_MAPPER, NIR_MAPPER]),
        )
        from kafka_tpu.core import tip_prior, broadcast_prior

        x, _ = broadcast_prior(tip_prior(), 5)
        pred = op.forward(bank, x)
        truth = TwoStreamOperator().forward(None, x)
        np.testing.assert_allclose(
            np.asarray(pred), np.asarray(truth), atol=0.02
        )


class TestMLPSurrogate:
    def test_mlp_emulates_wcm(self):
        def forward(x):
            return np.stack(
                [
                    np.asarray(
                        wcm_sigma0(x[:, 0], x[:, 1], 23.0, WCM_PARAMETERS[p])
                    )
                    for p in ("VV", "VH")
                ],
                axis=1,
            )

        x = np.stack(
            [RNG.uniform(0.2, 4.0, 2000), RNG.uniform(0.05, 0.5, 2000)],
            axis=1,
        ).astype(np.float32)
        params, loss = fit_mlp(forward, x, steps=1500)
        op = MLPOperator(n_params=2, n_bands=2)
        xt = jnp.asarray([[1.5, 0.2], [3.0, 0.4]], jnp.float32)
        pred = op.forward(params, xt)
        truth = np.asarray(WCMOperator().forward(
            WCMAux(theta_deg=jnp.full((2,), 23.0)), xt))
        np.testing.assert_allclose(np.asarray(pred), truth, atol=0.01)


class TestIdentity:
    def test_identity_linearization(self):
        op = IdentityOperator(n_params=3, obs_indices=(0, 2))
        x = jnp.asarray(RNG.normal(size=(4, 3)), jnp.float32)
        lin = op.linearize(None, x)
        np.testing.assert_allclose(np.asarray(lin.h0[0]), np.asarray(x[:, 0]))
        np.testing.assert_allclose(np.asarray(lin.h0[1]), np.asarray(x[:, 2]))
        expected_jac = np.zeros((2, 4, 3), np.float32)
        expected_jac[0, :, 0] = 1
        expected_jac[1, :, 2] = 1
        np.testing.assert_allclose(np.asarray(lin.jac), expected_jac)
