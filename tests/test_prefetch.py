"""Double-buffered observation prefetch (VERDICT round-1 item 8)."""

import datetime
import threading
import time

import numpy as np
import pytest

from kafka_tpu.engine import KalmanFilter, make_pixel_gather
from kafka_tpu.engine.prefetch import (
    ObservationPrefetcher,
    planned_observation_dates,
)


def day(i):
    return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)


class RecordingSource:
    """Synthetic source that logs read start/end times per date."""

    def __init__(self, dates, delay=0.0, fail_on=None):
        self.dates = list(dates)
        self.delay = delay
        self.fail_on = fail_on
        self.log = []
        self._lock = threading.Lock()

    def get_observations(self, date, gather):
        t0 = time.monotonic()
        if self.fail_on is not None and date == self.fail_on:
            # ValueError classifies POISON (deterministic failure), so
            # these tests pin the fail-fast path; transient-class errors
            # retry/degrade instead — covered in tests/test_resilience.py.
            raise ValueError(f"synthetic read failure for {date}")
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            self.log.append((date, t0, time.monotonic()))
        return ("obs", date, gather.n_pad)


class TestPlannedDates:
    def test_matches_time_grid_windowing(self):
        obs_dates = [day(i) for i in (1, 2, 5, 9, 10)]
        grid = [day(0), day(4), day(8), day(12)]
        plan = planned_observation_dates(grid, obs_dates)
        # Ordered, each obs date exactly once, windowed like the run loop
        assert plan == obs_dates

    def test_out_of_grid_dates_excluded(self):
        obs_dates = [day(-5), day(1), day(20)]
        grid = [day(0), day(4)]
        plan = planned_observation_dates(grid, obs_dates)
        assert day(1) in plan and day(20) not in plan


class TestPrefetcher:
    def test_in_order_delivery(self):
        dates = [day(i) for i in range(5)]
        src = RecordingSource(dates)
        gather = make_pixel_gather(np.ones((4, 4), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=2)
        try:
            for d in dates:
                tag, got, n_pad = pf.get(d)
                assert (tag, got, n_pad) == ("obs", d, gather.n_pad)
        finally:
            pf.close()

    def test_reads_run_ahead_of_consumption(self):
        """While the consumer holds date t, the worker must already be past
        reading date t+1 (double buffering)."""
        dates = [day(i) for i in range(4)]
        src = RecordingSource(dates, delay=0.05)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=2)
        try:
            pf.get(dates[0])
            # Simulate a slow device solve; the worker keeps reading.
            time.sleep(0.25)
            with src._lock:
                done = len(src.log)
            assert done >= 3  # t0 consumed, t1+t2 buffered ahead
        finally:
            pf.close()

    def test_worker_error_reraises_at_get(self):
        """POISON-class read errors keep the fail-fast contract: the
        original exception re-raises at the failing date's get()."""
        dates = [day(0), day(1), day(2)]
        src = RecordingSource(dates, fail_on=day(1))
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=2)
        try:
            pf.get(day(0))
            with pytest.raises(ValueError, match="synthetic read failure"):
                pf.get(day(1))
        finally:
            pf.close()

    def test_order_violation_detected(self):
        dates = [day(0), day(1)]
        src = RecordingSource(dates)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=2)
        try:
            with pytest.raises(RuntimeError, match="order violation"):
                pf.get(day(1))
        finally:
            pf.close()

    def test_close_mid_stream(self):
        dates = [day(i) for i in range(50)]
        src = RecordingSource(dates, delay=0.01)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=2)
        pf.get(dates[0])
        pf.close()  # must not hang on the full queue
        assert not any(t.is_alive() for t in pf._threads)


class TestMultiWorkerPrefetch:
    def test_ordered_delivery_with_racing_workers(self):
        """Reads completing out of order (random per-date delays across 3
        workers) must still deliver strictly in date order."""
        rng = np.random.default_rng(0)
        dates = [day(i) for i in range(12)]
        delays = {d: float(rng.uniform(0.0, 0.03)) for d in dates}

        class JitterSource(RecordingSource):
            def get_observations(self, date, gather):
                time.sleep(delays[date])
                return super().get_observations(date, gather)

        src = JitterSource(dates)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=4, workers=3)
        try:
            for d in dates:
                tag, got, _ = pf.get(d)
                assert got == d
        finally:
            pf.close()

    def test_workers_actually_overlap(self):
        """With 3 workers and slow reads, several reads must be in flight
        concurrently (wall time well under the serial sum)."""
        dates = [day(i) for i in range(6)]
        src = RecordingSource(dates, delay=0.1)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        t0 = time.monotonic()
        pf = ObservationPrefetcher(src, gather, dates, depth=6, workers=3)
        try:
            for d in dates:
                pf.get(d)
        finally:
            pf.close()
        wall = time.monotonic() - t0
        assert wall < 0.45, wall  # serial would be >= 0.6

    def test_error_reraises_at_position_with_workers(self):
        dates = [day(i) for i in range(6)]
        src = RecordingSource(dates, fail_on=day(3))
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        pf = ObservationPrefetcher(src, gather, dates, depth=3, workers=3)
        try:
            for d in dates[:3]:
                pf.get(d)
            with pytest.raises(ValueError, match="synthetic read failure"):
                pf.get(day(3))
        finally:
            pf.close()

    def test_transform_applied_on_worker(self):
        dates = [day(i) for i in range(4)]
        src = RecordingSource(dates)
        gather = make_pixel_gather(np.ones((2, 2), bool), pad_multiple=16)
        seen_threads = set()

        def tag(obs):
            seen_threads.add(threading.current_thread().name)
            return obs + ("transformed",)

        pf = ObservationPrefetcher(
            src, gather, dates, depth=2, workers=2, transform=tag
        )
        try:
            for d in dates:
                item = pf.get(d)
                assert item[-1] == "transformed"
        finally:
            pf.close()
        assert all(n.startswith("obs-prefetch") for n in seen_threads)


class TestFilterIntegration:
    def _run(self, prefetch_depth):
        import jax.numpy as jnp

        from kafka_tpu.core.propagators import PixelPrior
        from kafka_tpu.engine import FixedGaussianPrior
        from kafka_tpu.obsops import IdentityOperator
        from kafka_tpu.testing import MemoryOutput, SyntheticObservations

        rng = np.random.default_rng(3)
        mask = np.ones((6, 6), bool)
        p = 2
        op = IdentityOperator(n_params=p, obs_indices=(0, 1))
        truth = rng.uniform(0.3, 0.7, mask.shape + (p,)).astype(np.float32)
        obs = SyntheticObservations(
            dates=[day(i) for i in range(1, 7)],
            operator=op,
            truth_fn=lambda date: truth,
            sigma=0.02,
            seed=5,
        )
        out = MemoryOutput()
        mean = np.full((p,), 0.5, np.float32)
        cov = np.diag(np.full((p,), 0.25)).astype(np.float32)
        prior = FixedGaussianPrior(
            PixelPrior(
                mean=jnp.asarray(mean), cov=jnp.asarray(cov),
                inv_cov=jnp.asarray(np.linalg.inv(cov)),
            ),
            ("a", "b"),
        )
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=None, prior=prior, pad_multiple=16,
            prefetch_depth=prefetch_depth,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.zeros(p, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        grid = [day(0), day(3), day(6)]
        x_a, _, p_inv_a = kf.run(grid, x0, None, p_inv0)
        return np.asarray(x_a), np.asarray(p_inv_a)

    def test_prefetched_run_bitwise_matches_synchronous(self):
        """Prefetch is pure pipelining: results must equal the synchronous
        path exactly (same reads, same order, same arithmetic)."""
        x_sync, pinv_sync = self._run(prefetch_depth=0)
        x_pre, pinv_pre = self._run(prefetch_depth=2)
        np.testing.assert_array_equal(x_sync, x_pre)
        np.testing.assert_array_equal(pinv_sync, pinv_pre)
