"""Tier-1 wiring of tools/kafkalint (ISSUE 4): the JAX/TPU hazard and
repo-convention lints run over the production tree on every test run, so
a hidden host transfer, an f64 leak, an untracked thread, a silent
exception swallow or a telemetry-vocabulary drift breaks the suite —
not a TPU bench run three PRs later.

Also pins the plugin framework itself: every seeded violation in
tests/lint_fixtures/ must be reported by exactly its intended rule (the
``# expect: <rule>`` annotations), inline suppressions must silence,
the baseline must grandfather and age out, and the --json schema must
stay stable.
"""

import collections
import io
import json
import os
import re
import sys
import textwrap

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.kafkalint import cli, core  # noqa: E402
from tools.kafkalint.core import iter_files, make_rules, run_lint  # noqa: E402

FIXTURES = os.path.join(REPO_ROOT, "tests", "lint_fixtures")

EXPECT_RE = re.compile(r"#\s*expect:\s*([a-z0-9\-, ]+)")

ALL_RULES = {
    "host-transfer-in-jit", "implicit-f64", "untracked-thread",
    "bare-except", "static-arg-flag", "metric-name", "event-name",
    "event-collision", "kernel-relayout", "ad-hoc-retry",
    "naive-marker-write", "nonfinite-launder",
    "blocking-call-in-publisher", "magic-quality-threshold",
    "ad-hoc-timing", "nondeterministic-placement",
    "request-id-origin", "magic-slo-threshold",
    "forward-state-mutation-in-smoother", "raw-device-introspection",
    "unregistered-device-program", "unbatched-serve-dispatch",
}


# ---------------------------------------------------------------------------
# The production tree must lint clean (empty baseline is the goal state).
# ---------------------------------------------------------------------------

def test_production_tree_is_clean():
    result = run_lint(REPO_ROOT)
    assert result.findings == [], "\n".join(
        f.format() for f in result.findings
    )


def test_cli_exits_zero_on_production_tree(capsys):
    assert cli.main([REPO_ROOT]) == 0
    assert "clean" in capsys.readouterr().out


def test_scanned_set_covers_bench_and_tools():
    """bench.py and the tools scripts (bench_compare, roofline) are in
    the scanned set — an empty walk must never pass silently."""
    rels = {
        os.path.relpath(p, REPO_ROOT).replace(os.sep, "/")
        for p in iter_files(REPO_ROOT)
    }
    assert "bench.py" in rels
    assert "tools/bench_compare.py" in rels
    assert "tools/roofline.py" in rels
    assert any(r.startswith("kafka_tpu/core/") for r in rels)
    assert len(rels) > 60


def test_all_rules_registered():
    names = {r.name for r in make_rules()}
    assert ALL_RULES <= names


# ---------------------------------------------------------------------------
# Fixture tree: findings must match the # expect annotations EXACTLY.
# ---------------------------------------------------------------------------

def _expected_findings():
    expected = collections.Counter()
    for dirpath, _dirnames, filenames in os.walk(FIXTURES):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, FIXTURES).replace(os.sep, "/")
            with open(path) as f:
                for lineno, line in enumerate(f, start=1):
                    m = EXPECT_RE.search(line)
                    if not m:
                        continue
                    for rule in m.group(1).split(","):
                        expected[(rel, lineno, rule.strip())] += 1
    return expected


def test_fixture_findings_match_annotations_exactly():
    result = run_lint(FIXTURES)
    actual = collections.Counter(
        (f.path, f.line, f.rule) for f in result.findings
    )
    expected = _expected_findings()
    assert expected, "fixture annotations went missing"
    missing = expected - actual
    surplus = actual - expected
    assert not missing and not surplus, (
        f"missing findings: {sorted(missing)}\n"
        f"unexpected findings: {sorted(surplus)}"
    )


def test_every_rule_has_a_seeded_fixture_violation():
    rules_seeded = {rule for _, _, rule in _expected_findings()}
    assert rules_seeded == ALL_RULES


def test_suppressed_fixture_reports_nothing():
    result = run_lint(FIXTURES)
    assert not any("suppressed" in f.path for f in result.findings)


# ---------------------------------------------------------------------------
# Suppression mechanics in isolation.
# ---------------------------------------------------------------------------

def _write_tree(tmp_path, name, body):
    tools_dir = tmp_path / "tools"
    tools_dir.mkdir(exist_ok=True)
    (tools_dir / name).write_text(textwrap.dedent(body))


def test_trailing_suppression_silences_only_its_line(tmp_path):
    _write_tree(tmp_path, "s.py", """\
        def f(fn):
            try:
                fn()
            except Exception:  # kafkalint: disable=bare-except
                pass
            try:
                fn()
            except Exception:
                pass
        """)
    result = run_lint(str(tmp_path))
    assert [f.line for f in result.findings] == [8]
    assert result.findings[0].rule == "bare-except"


def test_disable_all_and_comment_block_form(tmp_path):
    _write_tree(tmp_path, "s.py", """\
        def f(fn):
            try:
                fn()
            # the teardown is best-effort by design
            # kafkalint: disable=all
            except Exception:
                pass
        """)
    assert run_lint(str(tmp_path)).findings == []


# ---------------------------------------------------------------------------
# Baseline: grandfather, then age out.
# ---------------------------------------------------------------------------

_VIOLATION = """\
    def f(fn):
        try:
            fn()
        except Exception:
            pass
    """


def _write_baseline(tmp_path, entries):
    bl_dir = tmp_path / "tools" / "kafkalint"
    bl_dir.mkdir(parents=True, exist_ok=True)
    (bl_dir / "baseline.json").write_text(json.dumps(entries))


def test_baseline_grandfathers_matching_finding(tmp_path):
    _write_tree(tmp_path, "legacy.py", _VIOLATION)
    _write_baseline(tmp_path, [{
        "rule": "bare-except", "path": "tools/legacy.py",
        "contains": "swallows the error",
        "reason": "pre-kafkalint code, tracked for cleanup",
    }])
    result = run_lint(str(tmp_path))
    assert result.findings == []
    assert result.baseline_entries == 1
    assert result.baseline_matched == 1


def test_stale_baseline_entry_is_a_finding(tmp_path):
    _write_tree(tmp_path, "clean.py", "X = 1\n")
    _write_baseline(tmp_path, [{
        "rule": "bare-except", "path": "tools/gone.py",
        "contains": "", "reason": "file was deleted",
    }])
    result = run_lint(str(tmp_path))
    assert [f.rule for f in result.findings] == ["stale-baseline"]
    assert "tools/gone.py" in result.findings[0].message


def test_baseline_update_regenerates_and_grandfathers(tmp_path, capsys):
    _write_tree(tmp_path, "legacy.py", _VIOLATION)
    assert cli.main([str(tmp_path)]) == 1  # dirty before
    capsys.readouterr()
    assert cli.main([str(tmp_path), "--baseline-update"]) == 0
    out = capsys.readouterr().out
    assert "wrote 1 baseline entry" in out
    bl_path = tmp_path / "tools" / "kafkalint" / "baseline.json"
    entries = json.loads(bl_path.read_text())
    assert [
        (e["rule"], e["path"]) for e in entries
    ] == [("bare-except", "tools/legacy.py")]
    assert all(e["contains"] and e["reason"] for e in entries)
    # the regenerated baseline grandfathers the finding...
    capsys.readouterr()
    assert cli.main([str(tmp_path)]) == 0
    # ...and stale semantics are unchanged: fix the code, entry goes
    # stale and is itself a finding.
    _write_tree(tmp_path, "legacy.py", "X = 1\n")
    result = run_lint(str(tmp_path))
    assert [f.rule for f in result.findings] == ["stale-baseline"]


def test_baseline_update_on_clean_tree_writes_empty_list(tmp_path, capsys):
    _write_tree(tmp_path, "ok.py", "X = 1\n")
    assert cli.main([str(tmp_path), "--baseline-update"]) == 0
    bl_path = tmp_path / "tools" / "kafkalint" / "baseline.json"
    assert json.loads(bl_path.read_text()) == []


def test_no_baseline_flag_ignores_baseline(tmp_path):
    _write_tree(tmp_path, "legacy.py", _VIOLATION)
    _write_baseline(tmp_path, [{
        "rule": "bare-except", "path": "tools/legacy.py",
        "contains": "", "reason": "grandfathered",
    }])
    result = run_lint(str(tmp_path), use_baseline=False)
    assert [f.rule for f in result.findings] == ["bare-except"]


# ---------------------------------------------------------------------------
# CLI: --json schema and exit codes.
# ---------------------------------------------------------------------------

def test_json_output_schema(capsys):
    rc = cli.main([FIXTURES, "--json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["version"] == 1
    assert payload["root"] == os.path.abspath(FIXTURES)
    assert payload["files_scanned"] == 23
    assert set(payload["rules"]) >= ALL_RULES
    assert isinstance(payload["findings"], list) and payload["findings"]
    for f in payload["findings"]:
        assert set(f) == {"rule", "path", "line", "message"}
        assert isinstance(f["line"], int) and f["line"] > 0
    bl = payload["baseline"]
    assert set(bl) == {"path", "entries", "matched"}
    assert bl["path"] is None  # fixtures carry no baseline file


def test_json_output_clean_tree(tmp_path, capsys):
    _write_tree(tmp_path, "ok.py", "X = 1\n")
    rc = cli.main([str(tmp_path), "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["findings"] == []


def test_rules_subset_and_unknown_rule(tmp_path, capsys):
    _write_tree(tmp_path, "legacy.py", _VIOLATION)
    assert cli.main([str(tmp_path), "--rules", "implicit-f64"]) == 0
    capsys.readouterr()
    assert cli.main([str(tmp_path), "--rules", "no-such-rule"]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_list_rules(capsys):
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ALL_RULES:
        assert rule in out


def test_parse_error_is_reported(tmp_path):
    _write_tree(tmp_path, "broken.py", "def f(:\n")
    result = run_lint(str(tmp_path))
    assert [f.rule for f in result.findings] == ["parse-error"]
