"""Numerical resilience (ISSUE 9): per-pixel solve-health verdicts,
adaptive damping escalation, QA-masked graceful degradation.

Acceptance pins:

- a ``solver.pixel``-seeded run with k deliberately-divergent pixels
  completes rc 0 with EXACTLY those pixels QA-flagged quarantined
  (forecast-valued, inflated uncertainty) while every healthy pixel's
  outputs are bit-identical (unfused) / within the 2e-3 budget (fused)
  to the fault-free run;
- the fused (in-kernel and out-of-kernel Pallas) and unfused (XLA)
  generations produce IDENTICAL verdict bitmasks on the same inputs;
- ``kafka_engine_device_reads_total == dispatches`` still holds — the
  health scalars ride the existing packed read, the QA band rides the
  output path.

All tier-1 / CPU.
"""

import datetime
import json
import os

import numpy as np
import jax.numpy as jnp
import pytest

from kafka_tpu import telemetry
from kafka_tpu.core import (
    BandBatch,
    Linearization,
    iterated_solve,
    kalman_update,
)
from kafka_tpu.core import solver_health as sh
from kafka_tpu.resilience import faults
from kafka_tpu.telemetry import MetricsRegistry

RNG = np.random.default_rng(7)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _problem(n=48, p=3, n_bands=2, mask_frac=0.0, seed=3):
    rng = np.random.default_rng(seed)
    jac = rng.normal(size=(n_bands, n, p)).astype(np.float32)
    h0 = rng.normal(size=(n_bands, n)).astype(np.float32)
    y = rng.normal(size=(n_bands, n)).astype(np.float32)
    r_inv = rng.uniform(0.5, 2.0, size=(n_bands, n)).astype(np.float32)
    mask = rng.uniform(size=(n_bands, n)) > mask_frac
    x_f = rng.normal(size=(n, p)).astype(np.float32)
    w = rng.normal(size=(n, p, p)).astype(np.float32)
    p_inv = np.einsum("npq,nrq->npr", w, w) + \
        3.0 * np.eye(p, dtype=np.float32)
    obs = BandBatch(
        y=jnp.asarray(np.where(mask, y, np.nan).astype(np.float32)),
        r_inv=jnp.asarray(np.where(mask, r_inv, 0.0).astype(np.float32)),
        mask=jnp.asarray(mask),
    )
    lin = lambda x: Linearization(h0=jnp.asarray(h0), jac=jnp.asarray(jac))
    return lin, obs, jnp.asarray(x_f), jnp.asarray(p_inv), mask


# ---------------------------------------------------------------------------
# solver_health unit surface
# ---------------------------------------------------------------------------

class TestHealthUnits:
    def test_escalation_arithmetic_identity_for_healthy(self):
        """The LM inflation/relaxation formulas are EXACT no-ops at
        esc=0 — the bit-identity guarantee's arithmetic core."""
        a = jnp.asarray(RNG.normal(size=(256,)).astype(np.float32))
        zero = jnp.zeros_like(a)
        assert (np.asarray(sh.inflate_diag(a, zero)) ==
                np.asarray(a)).all()
        r = jnp.float32(0.7)
        assert (np.asarray(sh.damped_relaxation(r, zero)) ==
                np.float32(0.7)).all()

    def test_chol_breakdown_flags_nonpositive_pivot(self):
        from kafka_tpu.core.linalg import cholesky_packed

        a_ok = [[jnp.asarray([4.0, 4.0])]]
        a_bad = [[jnp.asarray([0.0, -1.0])]]
        assert not np.asarray(
            sh.chol_breakdown(cholesky_packed(a_ok))
        ).any()
        assert np.asarray(
            sh.chol_breakdown(cholesky_packed(a_bad))
        ).all()

    def test_assemble_and_count_verdicts(self):
        observed = jnp.asarray([True, True, True, True, False])
        quar = jnp.asarray([False, True, False, False, False])
        moving = jnp.asarray([False, True, True, False, True])
        esc = jnp.asarray([False, True, False, True, False])
        v = np.asarray(sh.assemble_verdicts(
            observed, quar, jnp.asarray(True), moving, esc
        ))
        assert v[0] == sh.QA_CONVERGED
        assert v[1] == sh.QA_QUARANTINED          # quarantine wins
        assert v[2] == sh.QA_CAP_BAILOUT          # moving at the cap
        assert v[3] == sh.QA_CONVERGED | sh.QA_DAMPED_RECOVERED
        assert v[4] == sh.QA_NODATA               # unobserved
        cap, damped, q = sh.verdict_counts(jnp.asarray(v))
        assert (int(cap), int(damped), int(q)) == (1, 1, 1)

    def test_merge_verdicts_semantics(self):
        a = jnp.asarray([sh.QA_CONVERGED, sh.QA_NODATA,
                         sh.QA_QUARANTINED, sh.QA_NODATA], jnp.int32)
        b = jnp.asarray([sh.QA_CAP_BAILOUT, sh.QA_CONVERGED,
                         sh.QA_CONVERGED, sh.QA_NODATA], jnp.int32)
        m = np.asarray(sh.merge_verdicts(a, b))
        assert m[0] == sh.QA_CONVERGED | sh.QA_CAP_BAILOUT
        # one observed solve clears NODATA
        assert m[1] == sh.QA_CONVERGED
        assert m[2] == sh.QA_QUARANTINED | sh.QA_CONVERGED
        # unobserved in EVERY solve stays NODATA
        assert m[3] == sh.QA_NODATA

    def test_corruption_mask_pixel_grammar(self):
        assert sh.corruption_mask(16) is None  # disarmed: no argument
        faults.script("solver.pixel", "3-5")
        faults.script("solver.pixel", "9")
        with telemetry.use(MetricsRegistry()) as reg:
            mask = sh.corruption_mask(16)
        assert list(np.nonzero(mask)[0]) == [3, 4, 5, 9]
        assert reg.value(
            "kafka_resilience_faults_injected_total",
            site="solver.pixel",
        ) == 1
        assert any(e["event"] == "fault_injected" for e in reg.events)

    def test_corruption_open_range_clamps_to_batch(self):
        faults.script("solver.pixel", "14+")
        mask = sh.corruption_mask(16)
        assert list(np.nonzero(mask)[0]) == [14, 15]


# ---------------------------------------------------------------------------
# verdict parity across the three solve generations
# ---------------------------------------------------------------------------

class _QuadOp:
    inkernel_linearize = True

    def __init__(self, coeff):
        self.coeff = np.asarray(coeff, np.float32)

    def linearize(self, aux, x):
        c = jnp.asarray(self.coeff)
        return Linearization(
            h0=jnp.einsum("bp,np->bn", c, x**2),
            jac=2.0 * c[:, None, :] * x[None, :, :],
        )

    def kernel_linearize_rows(self, x_rows):
        B, p = self.coeff.shape
        h0 = [sum(float(c[k]) * x_rows[k] ** 2 for k in range(p))
              for c in self.coeff]
        jac = [[2.0 * float(c[k]) * x_rows[k] for k in range(p)]
               for c in self.coeff]
        return h0, jac


class TestVerdictParity:
    def _quad(self, n=64, p=3, n_bands=2, seed=11):
        rng = np.random.default_rng(seed)
        coeff = rng.uniform(0.5, 1.5, size=(n_bands, p)).astype(
            np.float32
        )
        op = _QuadOp(coeff)
        x_f = np.full((n, p), 0.8, np.float32)
        x_true = x_f + rng.normal(0, 0.05, (n, p)).astype(np.float32)
        y = np.einsum("bp,np->bn", coeff, x_true**2).astype(np.float32)
        mask = rng.uniform(size=y.shape) > 0.2
        obs = BandBatch(
            y=jnp.asarray(np.where(mask, y, np.nan).astype(np.float32)),
            r_inv=jnp.asarray(np.where(mask, 25.0, 0.0).astype(
                np.float32
            )),
            mask=jnp.asarray(mask),
        )
        p_inv = np.broadcast_to(
            4.0 * np.eye(p, dtype=np.float32), (n, p, p)
        ).copy()
        bounds = (jnp.full((p,), -10.0, jnp.float32),
                  jnp.full((p,), 10.0, jnp.float32))
        return op, obs, jnp.asarray(x_f), jnp.asarray(p_inv), bounds, \
            mask

    def _three_ways(self, corrupt=None):
        op, obs, x_f, p_inv, bounds, mask = self._quad()
        out = {}
        for name, kw in (
            ("xla", {}),
            ("rows", dict(use_pallas=True, inkernel_linearize=False)),
            ("kernel", dict(use_pallas=True)),
        ):
            out[name] = iterated_solve(
                op.linearize, obs, x_f, p_inv, state_bounds=bounds,
                corrupt=corrupt, **kw
            )
        return out, mask

    def test_identical_bitmasks_clean(self):
        out, _ = self._three_ways()
        v = {k: np.asarray(d.health_verdicts) for k, (_, _, d) in
             out.items()}
        np.testing.assert_array_equal(v["xla"], v["rows"])
        np.testing.assert_array_equal(v["xla"], v["kernel"])
        assert (v["xla"] & sh.QA_QUARANTINED).sum() == 0

    def test_identical_bitmasks_under_corruption(self):
        cor = np.zeros(64, np.float32)
        cor[[4, 17, 40]] = 1.0
        out, mask = self._three_ways(corrupt=jnp.asarray(cor))
        v = {k: np.asarray(d.health_verdicts) for k, (_, _, d) in
             out.items()}
        np.testing.assert_array_equal(v["xla"], v["rows"])
        np.testing.assert_array_equal(v["xla"], v["kernel"])
        observed = mask.any(axis=0)
        want = set(np.nonzero(cor.astype(bool) & observed)[0])
        assert set(np.nonzero(v["xla"] & sh.QA_QUARANTINED)[0]) == want
        for name, (x, a, d) in out.items():
            assert np.isfinite(np.asarray(x)).all(), name
            assert np.isfinite(np.asarray(a)).all(), name
            assert int(d.quarantined_count) == len(want), name

    def test_quarantined_pixels_forecast_valued_deflated_info(self):
        op, obs, x_f, p_inv, bounds, mask = self._quad()
        cor = np.zeros(64, np.float32)
        cor[5] = 1.0
        x, a, d = iterated_solve(
            op.linearize, obs, x_f, p_inv, state_bounds=bounds,
            corrupt=jnp.asarray(cor),
        )
        np.testing.assert_array_equal(np.asarray(x)[5],
                                      np.asarray(x_f)[5])
        np.testing.assert_allclose(
            np.asarray(a)[5],
            sh.QUARANTINE_INFO_SCALE * np.asarray(p_inv)[5],
            rtol=1e-6,
        )
        # zeroed diagnostics for the quarantined pixel
        assert (np.asarray(d.innovations)[:, 5] == 0).all()
        assert (np.asarray(d.fwd_modelled)[:, 5] == 0).all()

    def test_healthy_pixels_bit_identical_under_corruption_xla(self):
        op, obs, x_f, p_inv, bounds, mask = self._quad()
        cor = np.zeros(64, np.float32)
        cor[[4, 17]] = 1.0
        x0, a0, d0 = iterated_solve(
            op.linearize, obs, x_f, p_inv, state_bounds=bounds,
        )
        x1, a1, d1 = iterated_solve(
            op.linearize, obs, x_f, p_inv, state_bounds=bounds,
            corrupt=jnp.asarray(cor),
        )
        assert int(d0.n_iterations) == int(d1.n_iterations)
        healthy = np.ones(64, bool)
        healthy[[4, 17]] = False
        np.testing.assert_array_equal(
            np.asarray(x1)[healthy], np.asarray(x0)[healthy]
        )
        np.testing.assert_array_equal(
            np.asarray(a1)[healthy], np.asarray(a0)[healthy]
        )


# ---------------------------------------------------------------------------
# damping escalation: recoverable pixels recover (and say so)
# ---------------------------------------------------------------------------

class TestDampedRecovery:
    def _singular_problem(self, n=16, bad_pixel=6):
        """Identity-like operator observing ONLY parameter 0, with one
        pixel's prior information row zeroed: that pixel's A has an
        exactly-zero diagonal entry — Cholesky breakdown on iteration
        1, recoverable by the LM diagonal floor."""
        p, n_bands = 2, 1
        jac = np.zeros((n_bands, n, p), np.float32)
        jac[0, :, 0] = 1.0
        h0 = np.zeros((n_bands, n), np.float32)
        y = RNG.uniform(0.4, 0.6, size=(n_bands, n)).astype(np.float32)
        mask = np.ones((n_bands, n), bool)
        r_inv = np.full((n_bands, n), 25.0, np.float32)
        p_inv = np.broadcast_to(
            4.0 * np.eye(p, dtype=np.float32), (n, p, p)
        ).copy()
        p_inv[bad_pixel] = 0.0
        p_inv[bad_pixel, 0, 0] = 4.0
        obs = BandBatch(y=jnp.asarray(y), r_inv=jnp.asarray(r_inv),
                        mask=jnp.asarray(mask))
        lin = lambda x: Linearization(
            h0=jnp.einsum("bnp,np->bn", jnp.asarray(jac), x),
            jac=jnp.asarray(jac),
        )
        return lin, obs, jnp.full((n, p), 0.5, jnp.float32), \
            jnp.asarray(p_inv)

    def test_singular_prior_pixel_recovers_with_verdict(self):
        lin, obs, x_f, p_inv = self._singular_problem()
        x, a, d = iterated_solve(lin, obs, x_f, p_inv)
        v = np.asarray(d.health_verdicts)
        assert v[6] & sh.QA_DAMPED_RECOVERED, v[6]
        assert not v[6] & sh.QA_QUARANTINED
        assert int(d.damped_recovered_count) == 1
        assert int(d.quarantined_count) == 0
        assert np.isfinite(np.asarray(x)).all()
        # every other pixel is plainly converged
        others = np.ones(16, bool)
        others[6] = False
        assert (v[others] == sh.QA_CONVERGED).all()


# ---------------------------------------------------------------------------
# edge-case regressions through both kalman_update paths (satellite)
# ---------------------------------------------------------------------------

class TestEdgeCases:
    def _both_updates(self, lin, obs, x_lin, x_f, p_inv):
        x_xla, a_xla = kalman_update(lin, obs, x_lin, x_f, p_inv)
        x_pal, a_pal = kalman_update(
            lin, obs, x_lin, x_f, p_inv, use_pallas=True
        )
        return (x_xla, a_xla), (x_pal, a_pal)

    def test_zero_valid_observation_window(self):
        """All-masked window: the update is prior-only — x equals the
        forecast (up to factor round-off) through BOTH paths, and the
        iterated solve verdicts every pixel NODATA."""
        _, obs, x_f, p_inv, _ = _problem(mask_frac=1.1)
        assert not np.asarray(obs.mask).any()
        h0 = jnp.zeros_like(obs.y)
        jac = jnp.zeros(obs.y.shape + (x_f.shape[-1],), jnp.float32)
        lin = Linearization(h0=h0, jac=jac)
        for x, a in self._both_updates(lin, obs, x_f, x_f, p_inv):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(x_f), rtol=1e-5, atol=1e-5
            )
            assert np.isfinite(np.asarray(x)).all()
        _, _, d = iterated_solve(lambda x: lin, obs, x_f, p_inv)
        assert (np.asarray(d.health_verdicts) == sh.QA_NODATA).all()
        assert int(d.quarantined_count) == 0

    def test_all_nan_nodata_pixel_stays_inert(self):
        """One pixel masked (NaN y) in EVERY band: its posterior is its
        forecast, no NaN leaks into neighbours, verdict NODATA — both
        update paths."""
        lin_fn, obs, x_f, p_inv, mask = _problem(mask_frac=0.0)
        y = np.asarray(obs.y).copy()
        m = np.asarray(obs.mask).copy()
        r = np.asarray(obs.r_inv).copy()
        y[:, 7] = np.nan
        m[:, 7] = False
        r[:, 7] = 0.0
        obs = BandBatch(y=jnp.asarray(y), r_inv=jnp.asarray(r),
                        mask=jnp.asarray(m))
        lin = lin_fn(x_f)
        for x, a in self._both_updates(lin, obs, x_f, x_f, p_inv):
            x = np.asarray(x)
            assert np.isfinite(x).all()
        _, _, d = iterated_solve(lin_fn, obs, x_f, p_inv)
        v = np.asarray(d.health_verdicts)
        assert v[7] == sh.QA_NODATA
        assert (v[np.arange(48) != 7] != sh.QA_NODATA).all()

    def test_singular_prior_raw_update_nan_is_local(self):
        """Regression pin of the RAW single-update behavior both paths
        share: a singular system NaNs ONLY its own pixel (per-pixel
        factorisation — no cross-pixel contamination), which is exactly
        the failure the iterated solve's health layer detects and
        contains (TestDampedRecovery)."""
        n, p = 12, 2
        jac = np.zeros((1, n, p), np.float32)
        jac[0, :, 0] = 1.0
        lin = Linearization(
            h0=jnp.zeros((1, n), jnp.float32), jac=jnp.asarray(jac)
        )
        obs = BandBatch(
            y=jnp.full((1, n), 0.5, jnp.float32),
            r_inv=jnp.full((1, n), 25.0, jnp.float32),
            mask=jnp.ones((1, n), bool),
        )
        p_inv = np.broadcast_to(
            4.0 * np.eye(p, dtype=np.float32), (n, p, p)
        ).copy()
        p_inv[4] = 0.0
        p_inv[4, 0, 0] = 4.0
        x_f = jnp.full((n, p), 0.5, jnp.float32)
        for x, a in self._both_updates(
            lin, obs, x_f, x_f, jnp.asarray(p_inv)
        ):
            x = np.asarray(x)
            bad = ~np.isfinite(x).all(axis=-1)
            assert bad[4]
            assert not bad[np.arange(n) != 4].any()


# ---------------------------------------------------------------------------
# chaos acceptance: the full engine + GeoTIFF QA band story
# ---------------------------------------------------------------------------

def _engine_run(tmp_path, tag, scan_window, fault_spec=None):
    from kafka_tpu.core import propagate_information_filter
    from kafka_tpu.core.propagators import PixelPrior
    from kafka_tpu.engine import FixedGaussianPrior, KalmanFilter
    from kafka_tpu.io import GeoTIFFOutput
    from kafka_tpu.obsops.identity import IdentityOperator
    from kafka_tpu.testing import SyntheticObservations
    from kafka_tpu.testing.fixtures import DEFAULT_GEO

    faults.reset()
    if fault_spec is not None:
        faults.script("solver.pixel", fault_spec)
    rng = np.random.default_rng(0)
    mask = np.ones((6, 6), bool)
    p = 2
    op = IdentityOperator(n_params=p, obs_indices=(0, 1))
    truth = rng.uniform(0.3, 0.7, mask.shape + (p,)).astype(np.float32)

    def day(i):
        return datetime.datetime(2021, 3, 1) + datetime.timedelta(days=i)

    obs = SyntheticObservations(
        dates=[day(i) for i in (1, 2, 3, 4)], operator=op,
        truth_fn=lambda date: truth, sigma=0.02, seed=5, mask_prob=0.05,
    )
    mean = np.full((p,), 0.5, np.float32)
    cov = np.diag(np.full((p,), 0.25)).astype(np.float32)
    prior = FixedGaussianPrior(
        PixelPrior(
            mean=jnp.asarray(mean), cov=jnp.asarray(cov),
            inv_cov=jnp.asarray(np.linalg.inv(cov)),
        ),
        ("a", "b"),
    )
    outdir = str(tmp_path / tag)
    out = GeoTIFFOutput(("a", "b"), DEFAULT_GEO.geotransform,
                        DEFAULT_GEO.projection, outdir,
                        epsg=DEFAULT_GEO.epsg)
    with telemetry.use(MetricsRegistry()) as reg:
        kf = KalmanFilter(
            obs, out, mask, ("a", "b"),
            state_propagation=propagate_information_filter, prior=None,
            pad_multiple=16, prefetch_depth=0, scan_window=scan_window,
        )
        kf.set_trajectory_model()
        kf.set_trajectory_uncertainty(np.full(p, 1e-3, np.float32))
        x0, p_inv0 = prior.process_prior(None, kf.gather)
        kf.run([day(i) for i in range(0, 6)], x0, None, p_inv0)
    faults.reset()
    return kf, reg, outdir


def _read(outdir, name):
    from kafka_tpu.io import read_geotiff

    arr, _ = read_geotiff(os.path.join(outdir, name))
    return np.asarray(arr)


class TestChaosAcceptance:
    """The acceptance scenario, unfused and fused: k deliberately-
    divergent pixels, exactly k quarantined in the QA band, healthy
    pixels bit-identical, device-read invariant intact."""

    BAD = [3, 4, 5]  # armed pixel indices (0-based, gather order)

    def _coords(self):
        rows, cols = np.nonzero(np.ones((6, 6), bool))
        return rows[self.BAD], cols[self.BAD]

    @pytest.mark.parametrize("scan_window", [1, 4])
    def test_quarantine_qa_band_and_healthy_parity(self, tmp_path,
                                                   scan_window):
        kf_c, reg_c, dir_c = _engine_run(tmp_path, f"c{scan_window}",
                                         scan_window)
        kf_f, reg_f, dir_f = _engine_run(tmp_path, f"f{scan_window}",
                                         scan_window, "3-5")
        # rc 0 — both runs completed; every window counted its verdicts.
        assert all(r["quarantined"] == len(self.BAD)
                   for r in kf_f.diagnostics_log)
        assert all(r["quarantined"] == 0
                   for r in kf_c.diagnostics_log)
        assert reg_f.value(
            "kafka_solver_quarantined_pixels_total"
        ) == len(self.BAD) * len(kf_f.diagnostics_log)
        # Zero added device reads, chaos or not: one packed read per
        # dispatch (a fused block of k windows is one dispatch).
        for kf, reg in ((kf_c, reg_c), (kf_f, reg_f)):
            expected = sum(
                1.0 / r.get("fused", 1) for r in kf.diagnostics_log
            )
            assert reg.value(
                "kafka_engine_device_reads_total"
            ) == expected
        br, bc = self._coords()
        healthy = np.ones((6, 6), bool)
        healthy[br, bc] = False
        qa_files = sorted(
            f for f in os.listdir(dir_f) if f.startswith("solver_qa")
        )
        assert len(qa_files) == len(kf_f.diagnostics_log) if \
            scan_window == 1 else len(qa_files) >= 1
        for fn in qa_files:
            qa = _read(dir_f, fn)
            # exactly the armed pixels are quarantined
            assert (qa[br, bc].astype(int) & sh.QA_QUARANTINED).all()
            assert (qa[healthy].astype(int) & sh.QA_QUARANTINED).sum() \
                == 0
            # the clean run's QA band reports everything converged
            qa_clean = _read(dir_c, fn)
            assert (qa_clean[healthy].astype(int)
                    & sh.QA_CONVERGED).all()
        for fn in sorted(os.listdir(dir_c)):
            if fn.startswith("solver_qa") or not fn.endswith(".tif"):
                continue
            a_clean = _read(dir_c, fn)
            a_fault = _read(dir_f, fn)
            if scan_window == 1:
                # unfused: healthy pixels bit-identical
                np.testing.assert_array_equal(
                    a_fault[healthy], a_clean[healthy], err_msg=fn
                )
            else:
                np.testing.assert_allclose(
                    a_fault[healthy], a_clean[healthy], atol=2e-3,
                    err_msg=fn,
                )

    def test_quarantined_outputs_forecast_valued_inflated_unc(
            self, tmp_path):
        """The quarantined pixels' product values ARE the forecast —
        with no prior blend and an identity trajectory the forecast
        never leaves the initial mean (0.5) — and their uncertainty is
        INFLATED relative to the clean run's converged sigma."""
        _, _, dir_c = _engine_run(tmp_path, "cv", 1)
        _, _, dir_f = _engine_run(tmp_path, "fv", 1, "3-5")
        br, bc = self._coords()
        # only windows that actually assimilated carry a QA band (and a
        # quarantine); the first grid window here is observation-less.
        solved_dates = {
            fn.split("_")[-1].replace(".tif", "")
            for fn in os.listdir(dir_f) if fn.startswith("solver_qa")
        }
        checked = 0
        for fn in sorted(os.listdir(dir_f)):
            if not fn.endswith(".tif") or fn.startswith("solver_qa"):
                continue
            if not any(d in fn for d in solved_dates):
                continue
            checked += 1
            vals = _read(dir_f, fn)[br, bc]
            if fn.endswith("_unc.tif") or "_unc_" in fn:
                clean = _read(dir_c, fn)[br, bc]
                assert (vals > clean).all(), fn
            else:
                np.testing.assert_array_equal(
                    vals, np.full(len(self.BAD), 0.5, np.float32),
                    err_msg=fn,
                )
        assert checked >= 8  # 4 solved windows x 2 params x (val+unc)/2


class TestRunSyntheticChaos:
    def test_env_spec_reaches_the_driver(self, tmp_path, monkeypatch):
        """KAFKA_TPU_FAULTS='solver.pixel@...' through the real driver:
        run_synthetic completes rc 0 and writes QA bands with exactly
        the armed pixels quarantined."""
        from kafka_tpu.cli import run_synthetic

        outdir = str(tmp_path / "out")
        monkeypatch.setenv(faults.ENV_VAR, "solver.pixel@2-4")
        argv = ["--operator", "identity", "--ny", "12", "--nx", "12",
                "--days", "6", "--step", "2", "--obs-every", "2",
                "--outdir", outdir]
        summary = run_synthetic.main(argv)
        faults.reset()
        assert summary["n_pixels"] > 0
        qa_files = [f for f in os.listdir(outdir)
                    if f.startswith("solver_qa")]
        assert qa_files
        from kafka_tpu.io import read_geotiff
        from kafka_tpu.testing.fixtures import make_pivot_mask

        mask = make_pivot_mask(12, 12)
        rows, cols = np.nonzero(mask)
        qa, _ = read_geotiff(os.path.join(outdir, sorted(qa_files)[-1]))
        qa = np.asarray(qa)
        flagged = set(np.nonzero(
            qa[rows, cols].astype(int) & sh.QA_QUARANTINED
        )[0])
        assert flagged == {2, 3, 4}
